# Model training / scoring — per-algo wrappers like h2o-r's gbm.R etc.

.h2o.model <- function(key) structure(list(key = key), class = "H2OModel")

.h2o.frame_key <- function(x) if (inherits(x, "H2OFrame")) x$key else x

#' Train any algorithm by name — POST /3/ModelBuilders/{algo}.
h2o.train <- function(algo, training_frame, validation_frame = NULL, ...) {
  params <- list(...)
  params$training_frame <- .h2o.frame_key(training_frame)
  if (!is.null(validation_frame))
    params$validation_frame <- .h2o.frame_key(validation_frame)
  out <- .h2o.request("POST", paste0("/3/ModelBuilders/", algo),
                      body = params)
  .h2o.model(out$model$model_id$name)
}

# ---- per-algo wrappers (h2o-r naming) -------------------------------------
h2o.gbm <- function(y, training_frame, ...)
  h2o.train("gbm", training_frame, response_column = y, ...)
h2o.glm <- function(y, training_frame, ...)
  h2o.train("glm", training_frame, response_column = y, ...)
h2o.randomForest <- function(y, training_frame, ...)
  h2o.train("drf", training_frame, response_column = y, ...)
h2o.deeplearning <- function(y, training_frame, ...)
  h2o.train("deeplearning", training_frame, response_column = y, ...)
h2o.xgboost <- function(y, training_frame, ...)
  h2o.train("xgboost", training_frame, response_column = y, ...)
h2o.kmeans <- function(training_frame, ...)
  h2o.train("kmeans", training_frame, ...)
h2o.prcomp <- function(training_frame, ...)
  h2o.train("pca", training_frame, ...)
h2o.naiveBayes <- function(y, training_frame, ...)
  h2o.train("naivebayes", training_frame, response_column = y, ...)
h2o.isolationForest <- function(training_frame, ...)
  h2o.train("isolationforest", training_frame, ...)
h2o.coxph <- function(y, training_frame, ...)
  h2o.train("coxph", training_frame, response_column = y, ...)
h2o.gam <- function(y, training_frame, ...)
  h2o.train("gam", training_frame, response_column = y, ...)
h2o.glrm <- function(training_frame, ...)
  h2o.train("glrm", training_frame, ...)
h2o.rulefit <- function(y, training_frame, ...)
  h2o.train("rulefit", training_frame, response_column = y, ...)
h2o.stackedEnsemble <- function(y, training_frame, ...)
  h2o.train("stackedensemble", training_frame, response_column = y, ...)
h2o.infogram <- function(y, training_frame, ...)
  h2o.train("infogram", training_frame, response_column = y, ...)

#' Handle to an existing model.
h2o.getModel <- function(key) {
  .h2o.request("GET", paste0("/3/Models/",
                             utils::URLencode(key, reserved = TRUE)))
  .h2o.model(key)
}

.h2o.model_schema <- function(key) {
  .h2o.request("GET", paste0("/3/Models/",
                             utils::URLencode(key, reserved = TRUE))
               )$models[[1]]
}

#' Score a frame; returns an H2OFrame of predictions.
h2o.predict <- function(object, newdata) {
  out <- .h2o.request("POST", paste0(
    "/3/Predictions/models/", utils::URLencode(object$key, reserved = TRUE),
    "/frames/", utils::URLencode(.h2o.frame_key(newdata), reserved = TRUE)))
  .h2o.frame(out$predictions_frame$name)
}

#' Metrics of a model on a frame.
h2o.performance <- function(model, newdata) {
  .h2o.request("POST", paste0(
    "/3/ModelMetrics/models/",
    utils::URLencode(model$key, reserved = TRUE),
    "/frames/", utils::URLencode(.h2o.frame_key(newdata),
                                 reserved = TRUE)))$model_metrics[[1]]
}

#' Variable importances.
h2o.varimp <- function(model) {
  out <- .h2o.request("GET", paste0(
    "/3/Models/", utils::URLencode(model$key, reserved = TRUE), "/varimp"))
  do.call(rbind, lapply(out$varimp, as.data.frame))
}

#' Partial dependence data for one column.
h2o.partialPlot <- function(model, data, column, nbins = 20) {
  .h2o.request("POST", "/3/PartialDependence",
               body = list(model = model$key,
                           frame = .h2o.frame_key(data),
                           column = column,
                           nbins = nbins))$partial_dependence
}

#' Scoring history entries.
h2o.scoreHistory <- function(model) {
  .h2o.request("GET", paste0(
    "/3/Models/", utils::URLencode(model$key, reserved = TRUE),
    "/scoring_history"))$scoring_history
}

#' Save a model server-side; returns the server path.
h2o.saveModel <- function(model, path) {
  .h2o.request("POST", paste0("/99/Models.bin/",
                              utils::URLencode(model$key, reserved = TRUE)),
               body = list(dir = path))$path
}

#' Load = upload a locally downloaded artifact back to the server.
h2o.loadModel <- function(path) h2o.upload_model(path)

#' Download the binary model artifact to a local file.
h2o.download_model <- function(model, path) {
  raw <- .h2o.request("GET", paste0(
    "/3/Models.fetch.bin/", utils::URLencode(model$key, reserved = TRUE)),
    binary = TRUE)
  writeBin(raw, path)
  path
}

#' Upload a binary model artifact; returns the installed model.
h2o.upload_model <- function(path) {
  raw <- readBin(path, "raw", file.info(path)$size)
  out <- .h2o.request("POST", "/3/Models.upload.bin", body = raw)
  .h2o.model(out$models[[1]]$model_id$name)
}

#' Download the portable scoring artifact (MOJO analog).
h2o.download_mojo <- function(model, path) {
  raw <- .h2o.request("GET", paste0(
    "/3/Models/", utils::URLencode(model$key, reserved = TRUE), "/mojo"),
    binary = TRUE)
  writeBin(raw, path)
  path
}

#' @export
print.H2OModel <- function(x, ...) {
  sch <- .h2o.model_schema(x$key)
  cat(sprintf("H2OModel %s (%s)\n", x$key, sch$algo))
  invisible(x)
}

#' @export
summary.H2OModel <- function(object, ...) .h2o.model_schema(object$key)
