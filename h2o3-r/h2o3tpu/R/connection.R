# Connection + HTTP transport for the h2o3-tpu REST API.
#
# Reference surface: h2o-r/h2o-package/R/connection.R + communication.R —
# h2o.init / h2o.connect and a versioned REST transport.  The transport
# here is base-R sockets (no libcurl dependency): one HTTP/1.1 request
# per call, JSON via jsonlite.

.h2o.env <- new.env(parent = emptyenv())

#' Connect to a running h2o3-tpu server.
#' @param url server base url, e.g. "http://127.0.0.1:54321"
#' @param username,password optional HTTP basic credentials
h2o.connect <- function(url = "http://127.0.0.1:54321",
                        username = "", password = "") {
  parts <- .h2o.parse_url(url)
  conn <- structure(list(host = parts$host, port = parts$port,
                         auth = if (nzchar(username))
                           paste0(username, ":", password) else NULL),
                    class = "H2OConnection")
  assign("conn", conn, envir = .h2o.env)
  cloud <- .h2o.request("GET", "/3/Cloud")
  message(sprintf("Connected to h2o3-tpu cloud (platform %s, %s process(es))",
                  cloud$platform, cloud$cloud_size))
  invisible(conn)
}

#' h2o.init analog: connect, assuming a server is already running.
h2o.init <- function(ip = "127.0.0.1", port = 54321, ...) {
  h2o.connect(sprintf("http://%s:%d", ip, port), ...)
}

#' Cluster status (/3/Cloud).
h2o.clusterInfo <- function() .h2o.request("GET", "/3/Cloud")

#' There is no remote shutdown route; stop the server process instead.
h2o.shutdown <- function(prompt = TRUE) {
  warning("h2o3-tpu has no remote shutdown; stop the server process")
  invisible(NULL)
}

.h2o.parse_url <- function(url) {
  u <- sub("^https?://", "", url)
  host <- sub(":.*$", "", u)
  port <- if (grepl(":", u)) as.integer(sub("^.*:", "", sub("/.*$", "", u)))
          else 80L
  list(host = host, port = port)
}

.h2o.conn <- function() {
  if (!exists("conn", envir = .h2o.env))
    stop("not connected; call h2o.init() / h2o.connect() first")
  get("conn", envir = .h2o.env)
}

# One HTTP request over a base-R socket; returns parsed JSON (or raw
# bytes when binary = TRUE).
.h2o.request <- function(method, route, params = NULL, body = NULL,
                         binary = FALSE) {
  conn <- .h2o.conn()
  path <- route
  payload <- raw(0)
  headers <- c(sprintf("Host: %s:%d", conn$host, conn$port),
               "Connection: close")
  if (!is.null(conn$auth))
    headers <- c(headers, paste0(
      "Authorization: Basic ",
      jsonlite::base64_enc(charToRaw(conn$auth))))
  if (identical(method, "GET") && length(params)) {
    q <- paste(vapply(names(params), function(k) paste0(
      utils::URLencode(k, reserved = TRUE), "=",
      utils::URLencode(as.character(params[[k]]), reserved = TRUE)),
      character(1)), collapse = "&")
    path <- paste0(path, "?", q)
  } else if (!is.null(body)) {
    payload <- if (is.raw(body)) body else
      charToRaw(jsonlite::toJSON(body, auto_unbox = TRUE, null = "null"))
    headers <- c(headers,
                 if (is.raw(body)) "Content-Type: application/octet-stream"
                 else "Content-Type: application/json",
                 sprintf("Content-Length: %d", length(payload)))
  } else if (method %in% c("POST", "DELETE")) {
    headers <- c(headers, "Content-Length: 0")
  }
  sock <- socketConnection(conn$host, conn$port, open = "w+b",
                           blocking = TRUE, timeout = 600)
  on.exit(close(sock), add = TRUE)
  writeBin(charToRaw(paste0(method, " ", path, " HTTP/1.1\r\n",
                            paste(headers, collapse = "\r\n"),
                            "\r\n\r\n")), sock)
  if (length(payload)) writeBin(payload, sock)
  flush(sock)
  status_line <- .h2o.read_line(sock)
  status <- as.integer(strsplit(status_line, " ")[[1]][2])
  clen <- -1L
  repeat {
    line <- .h2o.read_line(sock)
    if (!nzchar(line)) break
    if (grepl("^[Cc]ontent-[Ll]ength:", line))
      clen <- as.integer(trimws(sub("^[^:]*:", "", line)))
  }
  raw_body <- if (clen >= 0) .h2o.read_n(sock, clen) else
    .h2o.read_all(sock)
  if (binary && status < 300) return(raw_body)
  out <- tryCatch(jsonlite::fromJSON(rawToChar(raw_body),
                                     simplifyVector = FALSE),
                  error = function(e) list(error = rawToChar(raw_body)))
  if (status >= 300)
    stop(sprintf("%s %s -> %d: %s", method, route, status,
                 if (is.null(out$error)) "error" else out$error))
  out
}

.h2o.read_line <- function(sock) {
  bytes <- raw(0)
  repeat {
    b <- readBin(sock, "raw", 1L)
    if (!length(b)) break
    if (identical(b, as.raw(10L))) break
    bytes <- c(bytes, b)
  }
  sub("\r$", "", rawToChar(bytes))
}

.h2o.read_n <- function(sock, n) {
  out <- raw(0)
  while (length(out) < n) {
    chunk <- readBin(sock, "raw", n - length(out))
    if (!length(chunk)) break
    out <- c(out, chunk)
  }
  out
}

.h2o.read_all <- function(sock) {
  out <- raw(0)
  repeat {
    chunk <- readBin(sock, "raw", 65536L)
    if (!length(chunk)) break
    out <- c(out, chunk)
  }
  out
}
