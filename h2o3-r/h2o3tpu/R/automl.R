# Grid search + AutoML — h2o-r grid.R / automl.R analogs.

#' Hyperparameter grid search — POST /99/Grid/{algo}.
#' @param hyper_params named list of value vectors, e.g.
#'   list(max_depth = c(3, 5), ntrees = c(20, 50))
h2o.grid <- function(algo, hyper_params, training_frame,
                     validation_frame = NULL, search_criteria = NULL, ...) {
  params <- list(...)
  params$training_frame <- .h2o.frame_key(training_frame)
  params$hyper_parameters <- hyper_params
  if (!is.null(validation_frame))
    params$validation_frame <- .h2o.frame_key(validation_frame)
  if (!is.null(search_criteria)) params$search_criteria <- search_criteria
  out <- .h2o.request("POST", paste0("/99/Grid/", algo), body = params)
  structure(list(key = out$grid_id$name, schema = out), class = "H2OGrid")
}

#' Fetch an existing grid.
h2o.getGrid <- function(grid_id) {
  out <- .h2o.request("GET", paste0(
    "/99/Grids/", utils::URLencode(grid_id, reserved = TRUE)))
  structure(list(key = out$grid_id$name, schema = out), class = "H2OGrid")
}

#' Run AutoML — POST /99/AutoMLBuilder.
h2o.automl <- function(y, training_frame, validation_frame = NULL,
                       max_models = 10, project_name = NULL, ...) {
  params <- list(...)
  params$training_frame <- .h2o.frame_key(training_frame)
  params$response_column <- y
  params$max_models <- max_models
  if (!is.null(project_name)) params$project_name <- project_name
  if (!is.null(validation_frame))
    params$validation_frame <- .h2o.frame_key(validation_frame)
  out <- .h2o.request("POST", "/99/AutoMLBuilder", body = params)
  structure(list(project_name = out$project_name,
                 leader = .h2o.model(out$leader$name),
                 schema = out), class = "H2OAutoML")
}

#' Leaderboard of a finished AutoML run.
h2o.get_leaderboard <- function(object) {
  project <- if (inherits(object, "H2OAutoML")) object$project_name
             else object
  out <- .h2o.request("GET", paste0(
    "/99/Leaderboards/", utils::URLencode(project, reserved = TRUE)))
  do.call(rbind, lapply(out$leaderboard_table, function(r)
    as.data.frame(r, stringsAsFactors = FALSE)))
}

#' @export
print.H2OGrid <- function(x, ...) {
  cat(sprintf("H2OGrid %s: %d models\n", x$key,
              length(x$schema$model_ids)))
  invisible(x)
}

#' @export
print.H2OAutoML <- function(x, ...) {
  cat(sprintf("H2OAutoML %s, leader %s\n", x$project_name, x$leader$key))
  invisible(x)
}
