# Frame handles and munging — h2o-r/h2o-package/R/frame.R analog (compact).

.h2o.frame <- function(key) structure(list(key = key), class = "H2OFrame")

#' Import one or many files (paths, globs, persist URIs) as a frame.
h2o.importFile <- function(path, destination_frame = NULL, ...) {
  out <- .h2o.request("POST", "/3/Parse",
                      body = list(path = path,
                                  destination_frame = destination_frame))
  .h2o.frame(out$destination_frame$name)
}

#' Handle to an existing server-side frame.
h2o.getFrame <- function(key) {
  .h2o.request("GET", paste0("/3/Frames/", utils::URLencode(key,
                                                            reserved = TRUE)))
  .h2o.frame(key)
}

#' All keys (frames + models) in the cluster.
h2o.ls <- function() {
  frames <- vapply(.h2o.request("GET", "/3/Frames")$frames,
                   function(f) f$frame_id$name, character(1))
  models <- vapply(.h2o.request("GET", "/3/Models")$models,
                   function(m) m$model_id$name, character(1))
  data.frame(key = c(frames, models),
             type = c(rep("frame", length(frames)),
                      rep("model", length(models))))
}

#' Remove a key from the DKV.
h2o.rm <- function(x) {
  key <- if (inherits(x, c("H2OFrame", "H2OModel"))) x$key else x
  .h2o.request("DELETE", paste0("/3/DKV/",
                                utils::URLencode(key, reserved = TRUE)))
  invisible(NULL)
}

#' Split a frame by ratios; returns a list of H2OFrame.
h2o.splitFrame <- function(data, ratios = 0.75, seed = 0) {
  out <- .h2o.request("POST", "/3/SplitFrame",
                      body = list(key = data$key,
                                  ratios = jsonlite::toJSON(ratios),
                                  seed = seed))
  lapply(out$destination_frames, .h2o.frame)
}

#' Export a frame to a path / persist URI.
h2o.exportFile <- function(data, path) {
  .h2o.request("POST", paste0("/3/Frames/",
                              utils::URLencode(data$key, reserved = TRUE),
                              "/export"),
               body = list(path = path))$path
}

#' Evaluate a Rapids expression string.
h2o.rapids <- function(ast) .h2o.request("POST", "/99/Rapids",
                                         body = list(ast = ast))

#' Column summaries (rollups) for a frame.
h2o.describe <- function(data) {
  .h2o.request("GET", paste0("/3/Frames/",
                             utils::URLencode(data$key, reserved = TRUE),
                             "/summary"))$frames[[1]]$summary
}

#' First n rows as a data.frame.
h2o.head <- function(data, n = 10) {
  out <- .h2o.request("GET", paste0(
    "/3/Frames/", utils::URLencode(data$key, reserved = TRUE), "/data"),
    params = list(row_offset = 0, row_count = n))
  as.data.frame(lapply(out$data, function(col)
    unlist(lapply(col, function(v) if (is.null(v)) NA else v))),
    stringsAsFactors = FALSE)
}

.h2o.frame_schema <- function(key) {
  .h2o.request("GET", paste0("/3/Frames/",
                             utils::URLencode(key, reserved = TRUE))
               )$frames[[1]]
}

#' @export
dim.H2OFrame <- function(x) {
  sch <- .h2o.frame_schema(x$key)
  c(sch$rows, length(sch$columns))
}

#' @export
print.H2OFrame <- function(x, ...) {
  sch <- .h2o.frame_schema(x$key)
  cat(sprintf("H2OFrame %s: %d rows x %d cols\n", x$key, sch$rows,
              length(sch$columns)))
  invisible(x)
}
