"""Scope: temporary-key lifetime tracking — ``water/Scope.java`` analog.

The reference brackets work in Scope.enter()/exit(): every Key created
inside the scope is tracked and swept on exit unless protected (tests and
Rapids sessions lean on this to avoid leaking temporaries).  Here the DKV
put hook feeds the innermost active scopes; ``protect`` (or returning a
value from ``with``) keeps survivors.
"""

from __future__ import annotations

import threading
from typing import List, Set

_local = threading.local()


def _stack() -> List["Scope"]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def track(key: str) -> None:
    """Called by dkv.put for every new key.

    Only the INNERMOST scope records it (water/Scope.java tracks at the
    current level): a key protected when the inner scope exits therefore
    survives all outer scopes without re-declaration.
    """
    st = _stack()
    if st:
        st[-1]._created.add(key)


class Scope:
    """Context manager sweeping unprotected keys created inside it."""

    def __init__(self):
        self._created: Set[str] = set()
        self._protected: Set[str] = set()

    def __enter__(self) -> "Scope":
        _stack().append(self)
        return self

    def protect(self, *objs) -> None:
        """Keep these keys (or .key-bearing objects) past scope exit."""
        for o in objs:
            key = o if isinstance(o, str) else getattr(o, "key", None)
            if key:
                self._protected.add(key)

    def __exit__(self, exc_type, exc, tb) -> None:
        from . import dkv
        _stack().remove(self)
        for key in self._created - self._protected:
            dkv.remove(key)
        # track() records at the innermost level only, so keys that
        # survive here (protected) are invisible to outer scopes
        return None
