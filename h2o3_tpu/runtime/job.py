"""Jobs: async work tracking for train/parse/score.

Reference: ``water/Job.java:24`` (565 LoC) — every long-running action is a
Job living in the DKV with progress, cancellation, and exceptional-completion
tracking; clients poll ``/3/Jobs``.

TPU-native redesign: the driver process orchestrates compiled SPMD programs,
so a Job is a host-side record (status, progress, timing, result key) in the
DKV index.  Work may run inline (blocking train, the common case) or on a
thread (``start(fn)``) for the async ``h2o.train(..., async)`` pattern.
"""

from __future__ import annotations

import heapq
import threading
import time
import traceback
from typing import Any, Callable, Optional

from . import dkv

MIRROR_PREFIX = "!job/"    # plain status stamps, replicated coordinator-side

CREATED = "CREATED"
RUNNING = "RUNNING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"


class JobCancelled(Exception):
    pass


class Job:
    """A tracked unit of work — analog of water.Job."""

    def __init__(self, description: str, dest_key: Optional[str] = None):
        self.key = dkv.make_key("job")
        self.description = description
        self.dest_key = dest_key
        self.status = CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.exception: Optional[BaseException] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self._cancel_requested = threading.Event()
        self._done = threading.Event()
        # recovery-journal entry URI (set by the training driver when
        # H2O3_TPU_RECOVERY_DIR is active); gates progress snapshots
        self.journal_uri: Optional[str] = None
        self._queued = False                 # on a scheduler queue
        self._owner = None                   # the scheduler it queued on
        self._thread: Optional[threading.Thread] = None
        self.result: Any = None
        # scheduling metadata (set by ClusterScheduler.submit)
        self.priority: Optional[int] = None
        self.device_budget: Any = None
        self.retry_budget: int = 0
        self.user: Optional[str] = None
        self.retries = 0
        # streaming-ingest progress (ingest/stream.py): the tree drivers'
        # stream= mode keeps this updated at every chunk fence so
        # GET /3/Jobs shows watermark/landed/backpressure live
        self.stream: Optional[dict] = None
        # run-token: each (re)run holds a fresh token; epilogues only
        # apply when the token still matches, so a worker thread wedged
        # in a dead collective cannot clobber a requeued job's state
        self._run_token: Optional[object] = None
        dkv.put(self.key, self)

    # ------------------------------------------------------------- lifecycle
    def run(self, fn: Callable[["Job"], Any]) -> Any:
        """Run ``fn(self)`` inline, tracking status/exceptions (blocking).

        Opens a root trace span: every span/DKV RPC under ``fn`` (across
        processes — the context rides the RPC envelope) shares one
        trace_id, so /3/Timeline renders the job as a single tree."""
        from .observability import record, trace
        token = object()
        self._run_token = token
        self.status = RUNNING
        self.start_time = time.time()
        record("job_start", job=self.key, description=self.description,
               attempt=self.retries)
        try:
            with trace("job", job=self.key, description=self.description):
                self._mirror()
                result = fn(self)
            if self._run_token is token:
                self.result = result
                if self.status == RUNNING:  # external fail() wins the race
                    self.status = DONE
                    self.progress = 1.0
            return result
        except JobCancelled:
            if self._run_token is token and self.status == RUNNING:
                self.status = CANCELLED
            raise
        except BaseException as e:
            if self._run_token is token and self.status == RUNNING:
                self.status = FAILED
                self.exception = e
                self.traceback = traceback.format_exc()
            raise
        finally:
            if self._run_token is token:
                self.end_time = time.time()
                self._done.set()
                record("job_end", job=self.key, status=self.status,
                       duration_s=round(self.run_time, 4))
                self._mirror()

    def _mirror(self) -> None:
        """Replicate a plain status stamp under ``!job/<key>``.

        The Job object itself holds host state (threads, events) and
        never leaves this process; the mirror is plain data, so when the
        process is attached to a DKV coordinator the put crosses the RPC
        boundary — the coordinator sees every member's jobs, and the
        start-of-run mirror (inside the job's root trace) is what stitches
        the coordinator's handler spans into the job's trace tree."""
        try:
            dkv.put(MIRROR_PREFIX + self.key, self.describe())
        except Exception:               # noqa: BLE001 — status is best-effort
            pass

    def start(self, fn: Callable[["Job"], Any]) -> "Job":
        """Run ``fn(self)`` on a background thread (async job)."""
        def _runner():
            try:
                self.run(fn)
            except BaseException:
                pass  # recorded on the job
        self._thread = threading.Thread(target=_runner, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> Any:
        """Wait for completion (threaded OR scheduler-queued runs).

        Waits on the completion event, never the worker thread: an
        external ``fail()`` (failure watchdog) must release joiners even
        while the worker thread stays wedged in a dead collective.  A job
        that was never started or queued returns immediately."""
        if self._thread is not None or self._queued \
                or self.status != CREATED:
            self._done.wait(timeout)
        if self.status == FAILED:
            raise self.exception
        return self.result

    # -------------------------------------------------------------- progress
    def update(self, progress: float, msg: str = "") -> None:
        """Advance progress; raises JobCancelled if a cancel was requested."""
        self.progress = float(progress)
        if msg:
            self.progress_msg = msg
        if self._cancel_requested.is_set():
            raise JobCancelled(self.description)

    def cancel(self) -> None:
        """Request cancellation.  A queued-but-unstarted job is dequeued
        from the scheduler and marked CANCELLED immediately — it never
        runs; a running job cancels cooperatively at its next update()."""
        self._cancel_requested.set()
        if self._queued and self.status == CREATED:
            s = self._owner or _scheduler
            if s is not None:
                try:
                    s.try_cancel(self)
                except Exception:   # noqa: BLE001 — cooperative flag stands
                    pass

    def _mark_cancelled(self) -> None:
        """Terminal CANCELLED for a job that never started (dequeued)."""
        if self.status != CREATED:
            return
        from .observability import record
        self.status = CANCELLED
        self.end_time = time.time()
        self._done.set()
        record("job_cancelled", job=self.key, queued=True)
        self._mirror()

    def _reset_for_retry(self) -> None:
        """Rearm for another run on the SAME object (degraded-mode
        requeue): joiners keep waiting on the same completion event; a
        fresh run token orphans the stale worker thread."""
        self._run_token = object()
        self.status = CREATED
        self.exception = None
        self.end_time = None
        self.progress = 0.0
        self._done.clear()
        self._queued = True
        self.retries += 1
        self._mirror()

    def fail(self, exc: BaseException) -> None:
        """Externally abort a job (failure watchdog): mark FAILED and
        release joiners NOW.  The worker thread may stay blocked in a
        collective that can never complete (gang member lost) — it is a
        daemon thread and its eventual outcome is ignored."""
        if self.status not in (CREATED, RUNNING):
            return
        self.status = FAILED
        self.exception = exc
        self.traceback = "".join(traceback.format_exception(exc))
        self.end_time = time.time()
        self._done.set()
        self._mirror()

    @property
    def is_running(self) -> bool:
        return self.status == RUNNING

    @property
    def run_time(self) -> float:
        if self.start_time is None:
            return 0.0
        return (self.end_time or time.time()) - self.start_time

    def describe(self) -> dict:
        d = {
            "key": self.key, "description": self.description,
            "status": self.status, "progress": self.progress,
            "msg": self.progress_msg, "dest": self.dest_key,
            "run_time": self.run_time,
            "exception": repr(self.exception) if self.exception else None,
            "priority": self.priority, "device_budget": self.device_budget,
            "retry_budget": self.retry_budget, "user": self.user,
            "retries": self.retries,
        }
        if self.stream is not None:
            d["stream"] = self.stream
        return d


def list_jobs() -> list:
    """All jobs in the DKV — the `/3/Jobs` analog."""
    return [dkv.get(k) for k in dkv.keys("job_")]


# ---------------------------------------------------------------- scheduler
class JobScheduler:
    """Priority work queue — the H2O.submitTask / F/J priority-pool analog.

    The reference runs MRTasks on fork/join pools indexed by priority so
    admin/interactive tasks never starve behind long builds
    (water/H2O.java H2OCountedCompleter priorities).  Here the DEVICE is
    the scarce resource and jit dispatch is serialized anyway, so the
    scheduler is a small thread pool draining a heap: lower ``priority``
    value runs first, FIFO within a level.
    """

    #: reference-like priority levels
    PRIORITY_ADMIN = 0
    PRIORITY_INTERACTIVE = 50
    PRIORITY_BUILD = 100

    def __init__(self, workers: int = 2):
        self._heap: list = []
        self._cv = threading.Condition()
        self._seq = 0
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"job-scheduler-{i}")
            for i in range(max(workers, 1))]
        for t in self._threads:
            t.start()

    def submit(self, job: "Job", fn: Callable[["Job"], Any],
               priority: int = PRIORITY_BUILD) -> "Job":
        """Queue ``fn(job)``; returns the job immediately (poll/join it)."""
        with self._cv:
            if self._shutdown:
                raise RuntimeError("job scheduler is stopped")
            job._queued = True
            job._owner = self
            self._seq += 1
            heapq.heappush(self._heap, (priority, self._seq, job, fn))
            self._cv.notify()
        return job

    def _worker(self):
        while True:
            with self._cv:
                while not self._heap and not self._shutdown:
                    self._cv.wait()
                if not self._heap:        # shutdown with a drained queue
                    return
                _, _, job, fn = heapq.heappop(self._heap)
            try:
                job.run(fn)
            except BaseException as e:    # noqa: BLE001
                # Job.run records its own failures; anything that still
                # escapes (e.g. a raise from run's epilogue) must reach
                # the job so joiners are released, never swallowed
                if not job._done.is_set():
                    job.fail(e)

    def try_cancel(self, job: "Job") -> bool:
        """Drop a still-queued job from the heap; False if it left."""
        with self._cv:
            for i, item in enumerate(self._heap):
                if item[2] is job:
                    self._heap.pop(i)
                    heapq.heapify(self._heap)
                    break
            else:
                return False
        job._mark_cancelled()
        return True

    def stop(self):
        """Stop accepting work; workers drain what is already queued."""
        global _scheduler
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        with _sched_lock:
            if _scheduler is self:
                _scheduler = None


_scheduler = None          # JobScheduler | scheduler.ClusterScheduler
_sched_lock = threading.Lock()


def scheduler():
    """Process-wide scheduler, created on first use.

    Returns the elastic fair-share ``ClusterScheduler``
    (runtime/scheduler.py); the legacy fixed-pool ``JobScheduler``
    above remains for direct construction in tests."""
    global _scheduler
    with _sched_lock:
        if _scheduler is None:
            from .scheduler import ClusterScheduler
            _scheduler = ClusterScheduler()
        return _scheduler
