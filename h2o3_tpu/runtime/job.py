"""Jobs: async work tracking for train/parse/score.

Reference: ``water/Job.java:24`` (565 LoC) — every long-running action is a
Job living in the DKV with progress, cancellation, and exceptional-completion
tracking; clients poll ``/3/Jobs``.

TPU-native redesign: the driver process orchestrates compiled SPMD programs,
so a Job is a host-side record (status, progress, timing, result key) in the
DKV index.  Work may run inline (blocking train, the common case) or on a
thread (``start(fn)``) for the async ``h2o.train(..., async)`` pattern.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Optional

from . import dkv

CREATED = "CREATED"
RUNNING = "RUNNING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"


class JobCancelled(Exception):
    pass


class Job:
    """A tracked unit of work — analog of water.Job."""

    def __init__(self, description: str, dest_key: Optional[str] = None):
        self.key = dkv.make_key("job")
        self.description = description
        self.dest_key = dest_key
        self.status = CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.exception: Optional[BaseException] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self._cancel_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.result: Any = None
        dkv.put(self.key, self)

    # ------------------------------------------------------------- lifecycle
    def run(self, fn: Callable[["Job"], Any]) -> Any:
        """Run ``fn(self)`` inline, tracking status/exceptions (blocking)."""
        from .observability import record
        self.status = RUNNING
        self.start_time = time.time()
        record("job_start", job=self.key, description=self.description)
        try:
            self.result = fn(self)
            self.status = DONE
            self.progress = 1.0
            return self.result
        except JobCancelled:
            self.status = CANCELLED
            raise
        except BaseException as e:
            self.status = FAILED
            self.exception = e
            self.traceback = traceback.format_exc()
            raise
        finally:
            self.end_time = time.time()
            record("job_end", job=self.key, status=self.status,
                   duration_s=round(self.run_time, 4))

    def start(self, fn: Callable[["Job"], Any]) -> "Job":
        """Run ``fn(self)`` on a background thread (async job)."""
        def _runner():
            try:
                self.run(fn)
            except BaseException:
                pass  # recorded on the job
        self._thread = threading.Thread(target=_runner, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> Any:
        if self._thread is not None:
            self._thread.join(timeout)
        if self.status == FAILED:
            raise self.exception
        return self.result

    # -------------------------------------------------------------- progress
    def update(self, progress: float, msg: str = "") -> None:
        """Advance progress; raises JobCancelled if a cancel was requested."""
        self.progress = float(progress)
        if msg:
            self.progress_msg = msg
        if self._cancel_requested.is_set():
            raise JobCancelled(self.description)

    def cancel(self) -> None:
        self._cancel_requested.set()

    @property
    def is_running(self) -> bool:
        return self.status == RUNNING

    @property
    def run_time(self) -> float:
        if self.start_time is None:
            return 0.0
        return (self.end_time or time.time()) - self.start_time

    def describe(self) -> dict:
        return {
            "key": self.key, "description": self.description,
            "status": self.status, "progress": self.progress,
            "msg": self.progress_msg, "dest": self.dest_key,
            "run_time": self.run_time,
            "exception": repr(self.exception) if self.exception else None,
        }


def list_jobs() -> list:
    """All jobs in the DKV — the `/3/Jobs` analog."""
    return [dkv.get(k) for k in dkv.keys("job_")]
