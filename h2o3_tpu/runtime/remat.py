"""Partial re-materialization of lost frame shards from lineage.

The reference's recovery contract (Recovery.java:72-81; recovery.py:9)
treats data loss as total: any host death means the whole frame is
re-imported from source.  This resolver walks the ``!lineage/`` records
``frame/lineage.py`` stamps at parse/derive time and rebuilds ONLY what
was lost, cheapest source first:

1. **copy** — shards still held by the live frame (or an up-to-date
   survivor) are copied, not recomputed;
2. **replica** — hot frames under ``H2O3_TPU_REPLICATE_BELOW_MB`` keep a
   DCN-neighbor replica of every shard in the DKV: recovery is a fetch
   verified by content hash;
3. **reparse / checkpoint** — parse-kind records re-parse only the lost
   shard's newline-aligned byte range (the source span's sha1 is checked
   first, so a mutated file can never rebuild silently-wrong rows);
   checkpoint-kind records load the canonical snapshot;
4. **replay** — derived-kind records recover their root frame the same
   way, then replay the recorded op chain.

Every rebuilt shard with a recorded value hash is verified bitwise
(canonical column bytes); a mismatch raises :class:`RematError` and the
caller — ``recovery.resume_entry`` / the scheduler's degraded-mode
requeue — degrades to the old full re-import.  Wrong data is never
produced silently: the failure mode is cost, not corruption.

Metrics: ``remat_shards_total{mode}``, ``remat_seconds``,
``lineage_records`` (docs/operations.md "Data plane recovery").
Fault-injection point ``remat`` (failure.py) fires at the top of every
recovery attempt so chaos rows can prove the degrade path.
"""

from __future__ import annotations

import csv
import hashlib
import io
import os
import time
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..frame import lineage
from ..frame.vec import (T_CAT, T_NUM, T_STR, T_TIME, T_UUID, Vec,
                         encode_domain)


class RematError(RuntimeError):
    """Lineage-driven re-materialization failed (or would be unsafe);
    callers degrade to full re-import from source."""


# most recent recovery, for tests/REST: frame, per-mode shard lists,
# reparsed byte ranges, wall seconds
last_stats: Dict[str, object] = {}


def lost_host_indices() -> Optional[Set[int]]:
    """Host (shard) indices of members declared dead by the failure
    watchdog — read from the ``!failures/`` records, which carry the
    process index the heartbeat stamped.  None when no death carries a
    usable index (callers then treat every shard as lost)."""
    from . import dkv
    from .failure import FAILURES_PREFIX
    lost: Set[int] = set()
    try:
        for k in dkv.keys(FAILURES_PREFIX):
            rec = dkv.get(k)
            if isinstance(rec, dict) and rec.get("host_index") is not None:
                lost.add(int(rec["host_index"]))
    except Exception:                    # noqa: BLE001 — coordinator gone
        return None
    return lost or None


def repair(frame_key: str, lost: Optional[Sequence[int]] = None):
    """Degraded-mode entry point: rebuild a frame's lost shards if (and
    only if) it has lineage.  Returns the repaired Frame, or None when
    no lineage record exists — the caller keeps its old fallback."""
    if lineage.get_record(frame_key) is None:
        return None
    return recover_frame(frame_key, lost)


def recover_frame(frame_key: str, lost: Optional[Sequence[int]] = None):
    """Rebuild ``frame_key`` from its lineage record.  ``lost`` is the
    set of shard indices to re-materialize (None = all, the fresh-
    process restart case).  Registers and returns the rebuilt Frame;
    raises :class:`RematError` when lineage cannot prove a correct
    rebuild."""
    from . import dkv
    from .failure import maybe_inject
    from .observability import inc, log, observe, record
    t0 = time.perf_counter()
    rec = lineage.get_record(frame_key)
    if rec is None:
        raise RematError(f"no lineage record for {frame_key!r}")
    stats: Dict[str, object] = {"frame": frame_key, "copied": [],
                                "replica": [], "reparsed": [],
                                "checkpoint": [], "replay": []}
    try:
        maybe_inject("remat")
        if rec.get("kind") == "derived":
            frame = _recover_derived(rec, lost, stats)
        else:
            frame = _recover_base(rec, lost, stats)
    except RematError:
        raise
    except Exception as e:               # noqa: BLE001 — normalize
        raise RematError(
            f"re-materialization of {frame_key!r} failed: {e!r}") from e
    frame._lineage = rec
    if rec.get("kind") == "parse":
        frame.source_uri = rec.get("source")
    dt = time.perf_counter() - t0
    stats["seconds"] = round(dt, 4)
    stats["mode"] = "replay" if stats["replay"] else (
        "reparse" if stats["reparsed"] else (
            "replica" if stats["replica"] else (
                "checkpoint" if stats["checkpoint"] else "copy")))
    last_stats.clear()
    last_stats.update(stats)
    for key, mode in (("copied", "copy"), ("replica", "replica"),
                      ("reparsed", "reparse"), ("checkpoint", "checkpoint"),
                      ("replay", "replay")):
        n = len(stats[key])
        if n:
            inc("remat_shards_total", n, mode=mode)
    observe("remat_seconds", dt)
    record("remat", frame=frame_key, mode=stats["mode"],
           seconds=stats["seconds"],
           lost=sorted(int(i) for i in lost) if lost is not None else None)
    try:
        from .observability import set_gauge
        set_gauge("lineage_records",
                  float(len(dkv.keys(lineage.LINEAGE_PREFIX))))
    except Exception:                    # noqa: BLE001
        pass
    log.info("remat: rebuilt %r via %s in %.3fs (copied=%d replica=%d "
             "reparsed=%d checkpoint=%d replay=%d)", frame_key,
             stats["mode"], dt, len(stats["copied"]), len(stats["replica"]),
             len(stats["reparsed"]), len(stats["checkpoint"]),
             len(stats["replay"]))
    return frame


# --------------------------------------------------------------- base frames

def _alloc_cols(types: Sequence[str], nrows: int) -> List[np.ndarray]:
    out = []
    for t in types:
        if t == T_CAT:
            out.append(np.full(nrows, -1, np.int32))
        elif t == T_TIME:
            out.append(np.full(nrows, np.nan, np.float64))
        elif t in (T_STR, T_UUID):
            out.append(np.full(nrows, None, object))
        else:
            out.append(np.full(nrows, np.nan, np.float32))
    return out


def _live_canonical(rec) -> Optional[List[np.ndarray]]:
    from . import dkv
    live = dkv.get(rec["frame"])
    if live is None or getattr(live, "nrows", None) != rec["nrows"] \
            or getattr(live, "names", None) != rec["schema"]["names"]:
        return None
    try:
        return lineage.canonical_cols(live)
    except Exception:                    # noqa: BLE001 — shards may be gone
        return None


def _copy_shard(dst: List[np.ndarray], src: Sequence[np.ndarray],
                lo: int, hi: int) -> None:
    for d, s in zip(dst, src):
        d[lo:hi] = s[lo:hi]


def _try_replica(rec, shard: int, cols: List[np.ndarray],
                 types: Sequence[str], lo: int, hi: int) -> bool:
    """Fill a shard from its ``!replica/`` record; True on verified hit."""
    from . import dkv
    meta = (rec.get("replicas") or {}).get(str(shard))
    if meta is None:
        return False
    rep = dkv.get(lineage.replica_key(rec["frame"], shard))
    if not isinstance(rep, dict) or len(rep.get("cols", ())) != len(cols):
        return False
    for d, s in zip(cols, rep["cols"]):
        if len(s) != hi - lo:
            return False
        d[lo:hi] = s
    if lineage.hash_cols(cols, types, lo, hi) != meta.get("sha1"):
        from .observability import log
        log.warning("remat: replica of %r shard %d fails its content "
                    "hash; falling back to recompute", rec["frame"], shard)
        return False
    return True


def _recover_base(rec, lost: Optional[Sequence[int]], stats) -> object:
    """Rebuild a parse- or checkpoint-kind frame shard by shard."""
    schema = rec["schema"]
    types = schema["types"]
    nrows = int(rec["nrows"])
    n_shards = int(rec["n_shards"])
    lost_set = set(range(n_shards)) if lost is None \
        else {int(i) for i in lost}
    live_cols = _live_canonical(rec)
    if live_cols is None:
        lost_set = set(range(n_shards))
    cols = _alloc_cols(types, nrows)
    ckpt = None
    for s in rec["shards"]:
        i, lo = int(s["shard"]), int(s["row_lo"])
        hi = lo + int(s["rows"])
        if hi <= lo:
            continue
        want = s.get("val_sha1")
        if i not in lost_set:
            _copy_shard(cols, live_cols, lo, hi)
            if want is None or lineage.hash_cols(cols, types, lo, hi) == want:
                stats["copied"].append(i)
                continue                 # verified survivor
            # survivor failed its hash: rebuild it like a lost shard
        if _try_replica(rec, i, cols, types, lo, hi):
            stats["replica"].append(i)
            continue
        if rec.get("kind") == "checkpoint":
            if ckpt is None:
                _, ck_rows, ckpt = lineage.load_checkpoint(rec)
                if ck_rows != nrows:
                    raise RematError(
                        f"checkpoint of {rec['frame']!r} has {ck_rows} "
                        f"rows, lineage says {nrows}")
            _copy_shard(cols, ckpt, lo, hi)
            stats["checkpoint"].append(i)
        else:
            _reparse_span(rec, s, cols, types, schema)
            stats["reparsed"].append([int(s["lo"]), int(s["hi"])])
        if want is not None \
                and lineage.hash_cols(cols, types, lo, hi) != want:
            raise RematError(
                f"rebuilt shard {i} of {rec['frame']!r} fails its content "
                "hash — source or engine drift; use full re-import")
    return _frame_from_canonical(schema, cols, rec["frame"])


def _frame_from_canonical(schema, cols: List[np.ndarray], key: str):
    from ..frame.frame import Frame
    vecs = []
    for name, t, c in zip(schema["names"], schema["types"], cols):
        if t == T_CAT:
            vecs.append(Vec.from_numpy(
                c, T_CAT, domain=(schema.get("domains") or {}).get(name)))
        elif t == T_TIME:
            vecs.append(Vec.from_numpy(
                c, T_TIME,
                time_base=(schema.get("time_base") or {}).get(name)))
        elif t in (T_STR, T_UUID):
            vecs.append(Vec(None, t, len(c), host_data=c))
        else:
            vecs.append(Vec.from_numpy(c, T_NUM))
    return Frame(schema["names"], vecs, key=key)


# ---------------------------------------------------------- span re-parsing

def _reparse_span(rec, shard: dict, cols: List[np.ndarray],
                  types: Sequence[str], schema) -> None:
    """Re-parse ONE shard's byte range of the source file into ``cols``
    rows [row_lo, row_lo+rows) — the fastcsv ranged fan-out applied to
    recovery.  The span's sha1 is verified against the lineage stamp
    before any value is trusted."""
    from .failure import maybe_inject
    path = rec["source"]
    lo_b, hi_b = int(shard["lo"]), int(shard["hi"])
    row_lo, n = int(shard["row_lo"]), int(shard["rows"])
    try:
        with open(path, "rb") as f:
            f.seek(lo_b)
            span = f.read(hi_b - lo_b)
    except OSError as e:
        raise RematError(f"source {path!r} unreadable: {e!r}") from e
    if len(span) != hi_b - lo_b \
            or hashlib.sha1(span).hexdigest() != shard["src_sha1"]:
        raise RematError(
            f"source {path!r} bytes [{lo_b},{hi_b}) no longer match their "
            "lineage hash — file changed since parse; use full re-import")
    maybe_inject("parse_range")
    if (rec.get("parse") or {}).get("format") == "parquet":
        return _reparse_groups(rec, shard, cols, types, schema)
    sepc = rec["parse"].get("sep") or ","
    parsed = _tokenize_span(span, sepc, len(types))
    if parsed is None:
        raise RematError(f"cannot tokenize span of {path!r}")
    vals, flags, text = parsed
    if len(vals) != n:
        raise RematError(
            f"span of {path!r} re-parsed to {len(vals)} rows, lineage "
            f"says {n}")
    for j, t in enumerate(types):
        cols[j][row_lo:row_lo + n] = _typed_column(
            t, vals, flags, text, j, schema, j_name=schema["names"][j])


def _reparse_groups(rec, shard: dict, cols: List[np.ndarray],
                    types: Sequence[str], schema) -> None:
    """Columnar peer of the CSV span re-parse: the shard's column-chunk
    byte span already passed its sha1 check, so re-read ONLY its row
    groups and write rows [row_lo, row_lo+rows) in canonical form typed
    by the SCHEMA (never re-guessed)."""
    import pyarrow.parquet as pq
    path = rec["source"]
    row_lo, n = int(shard["row_lo"]), int(shard["rows"])
    g_lo, g_hi = int(shard["group_lo"]), int(shard["group_hi"])
    table = pq.ParquetFile(path).read_row_groups(list(range(g_lo, g_hi)))
    off = row_lo - int(shard.get("group_row_lo", row_lo))
    if off < 0 or off + n > table.num_rows:
        raise RematError(
            f"row groups [{g_lo},{g_hi}) of {path!r} hold "
            f"{table.num_rows} rows, lineage wants [{off},{off + n})")
    table = table.slice(off, n)
    for j, (t, name) in enumerate(zip(types, schema["names"])):
        cols[j][row_lo:row_lo + n] = _parquet_canonical(
            t, table.column(name), name, schema)


def _parquet_canonical(t: str, col, name: str, schema) -> np.ndarray:
    """One arrow column in canonical form under the lineage schema —
    mirrors the ``parse_arrow`` type mapping cell for cell so rebuilt
    shards pass their bitwise value hash."""
    import pyarrow as pa
    from ..frame.parse import _NA
    pa_type = col.type
    if t == T_NUM and (pa.types.is_floating(pa_type)
                       or pa.types.is_integer(pa_type)
                       or pa.types.is_boolean(pa_type)):
        return col.cast(pa.float64()).to_numpy(
            zero_copy_only=False).astype(np.float32)
    if t == T_TIME and (pa.types.is_timestamp(pa_type)
                        or pa.types.is_date(pa_type)):
        ms = col.cast(pa.timestamp("ms")).to_numpy(
            zero_copy_only=False).astype("datetime64[ms]") \
            .astype("int64").astype(np.float64)
        ms[col.is_null().to_numpy(zero_copy_only=False)] = np.nan
        return ms
    sv = np.asarray(["" if v is None else str(v) for v in col.to_pylist()],
                    dtype=object).astype(str)
    na = np.isin(sv, list(_NA))
    if t == T_NUM:
        out = np.full(len(sv), np.nan, np.float64)
        ok = ~na
        out[ok] = sv[ok].astype(np.float64)
        return out.astype(np.float32)
    if t == T_CAT:
        dom = (schema.get("domains") or {}).get(name) or []
        return encode_domain(sv, dom, na_mask=na)
    if t == T_TIME:
        import pandas as pd
        with np.errstate(all="ignore"):
            dt = pd.to_datetime(pd.Series(sv.astype(object)),
                                errors="coerce", format="mixed")
        ms = dt.to_numpy().astype("datetime64[ms]").astype("int64") \
            .astype(np.float64)
        ms[dt.isna().to_numpy() | na] = np.nan
        return ms
    out = sv.astype(object)
    out[na] = None
    return out


def _tokenize_span(span: bytes, sepc: str, ncols: int):
    """Tokenize a byte span: native fastcsv when available, stdlib csv
    otherwise.  Returns (vals f64 [n,ncols], flags u8 [n,ncols],
    text(j) -> object column) or None."""
    from .. import native
    if len(sepc) == 1 and native.load() is not None:
        out = native.parse_bytes(span, sepc, ncols=ncols)
        if out is not None:
            vals, flags, offs, consumed = out
            if consumed == len(span):
                from ..frame.parse import _decode_text_column
                return (np.asarray(vals), np.asarray(flags),
                        lambda j: _decode_text_column(span, offs, j))
    rows = [r for r in csv.reader(io.StringIO(
        span.decode(errors="replace")), delimiter=sepc) if r]
    n = len(rows)
    vals = np.full((n, ncols), np.nan, np.float64)
    flags = np.zeros((n, ncols), np.uint8)
    cells = np.full((n, ncols), "", object)
    for i, r in enumerate(rows):
        for j in range(min(len(r), ncols)):
            c = r[j].strip()
            cells[i, j] = c
            try:
                vals[i, j] = float(c)
            except ValueError:
                flags[i, j] = 1
    return vals, flags, lambda j: cells[:, j]


def _typed_column(t: str, vals, flags, text, j: int, schema,
                  j_name: str) -> np.ndarray:
    """One span column in canonical form, typed by the SCHEMA (never
    re-guessed: a subset of rows must not change a column's type)."""
    from ..frame.parse import _NA
    if t == T_NUM and not flags[:, j].any():
        return vals[:, j].astype(np.float32)
    sv = np.asarray(text(j)).astype(str)
    na = np.isin(sv, list(_NA))
    if t == T_NUM:
        out = np.full(len(sv), np.nan, np.float64)
        ok = ~na
        out[ok] = sv[ok].astype(np.float64)
        return out.astype(np.float32)
    if t == T_CAT:
        dom = (schema.get("domains") or {}).get(j_name) or []
        return encode_domain(sv, dom, na_mask=na)
    if t == T_TIME:
        import pandas as pd
        with np.errstate(all="ignore"):
            dt = pd.to_datetime(pd.Series(sv.astype(object)),
                                errors="coerce", format="mixed")
        ms = dt.to_numpy().astype("datetime64[ms]").astype("int64") \
            .astype(np.float64)
        ms[dt.isna().to_numpy() | na] = np.nan
        return ms
    out = sv.astype(object)
    out[na] = None
    return out


# -------------------------------------------------------------- derived replay

_MAX_ROOT_DEPTH = 4                      # checkpoint cap bounds real chains


def _recover_derived(rec, lost: Optional[Sequence[int]], stats,
                     depth: int = 0) -> object:
    """Rebuild a derived-kind frame: replica shards first (no recompute),
    else recover the root and replay the recorded op chain."""
    schema = rec["schema"]
    types = schema["types"]
    nrows = int(rec["nrows"])
    n_shards = int(rec["n_shards"])
    lost_set = set(range(n_shards)) if lost is None \
        else {int(i) for i in lost}
    live_cols = _live_canonical(rec)
    if live_cols is None:
        lost_set = set(range(n_shards))
    # cheap path: every missing shard patched from survivors + replicas
    cols = _alloc_cols(types, nrows)
    patched, copied, replicated = True, [], []
    for s in rec["shards"]:
        i, lo = int(s["shard"]), int(s["row_lo"])
        hi = lo + int(s["rows"])
        if hi <= lo:
            continue
        want = s.get("val_sha1")
        if i not in lost_set:
            _copy_shard(cols, live_cols, lo, hi)
            if want is None or lineage.hash_cols(cols, types, lo, hi) == want:
                copied.append(i)
                continue
        if _try_replica(rec, i, cols, types, lo, hi):
            replicated.append(i)
            continue
        patched = False
        break
    if patched:
        stats["copied"] += copied
        stats["replica"] += replicated
        return _frame_from_canonical(schema, cols, rec["frame"])
    # replay path: a correct root, then the op chain
    if depth > _MAX_ROOT_DEPTH:
        raise RematError(f"lineage root chain of {rec['frame']!r} too deep")
    from . import dkv
    root_key = rec["root"]
    root = dkv.get(root_key) if lost is None else None
    if root is None:
        root_rec = lineage.get_record(root_key)
        if root_rec is None:
            raise RematError(
                f"derived frame {rec['frame']!r} has no recoverable root "
                f"{root_key!r}")
        if root_rec.get("kind") == "derived":
            root = _recover_derived(root_rec, lost, stats, depth + 1)
        else:
            root = _recover_base(root_rec, lost, stats)
    out = root
    for op in rec.get("ops") or []:
        out = _apply_op(out, op)
    if out.nrows != nrows or list(out.names) != list(schema["names"]):
        raise RematError(
            f"replayed chain of {rec['frame']!r} produced "
            f"{out.nrows}x{list(out.names)}, lineage says "
            f"{nrows}x{schema['names']}")
    re_cols = lineage.canonical_cols(out)
    for s in rec["shards"]:
        want = s.get("val_sha1")
        if want is None or not s["rows"]:
            continue
        lo = int(s["row_lo"])
        if lineage.hash_cols(re_cols, types, lo, lo + int(s["rows"])) != want:
            raise RematError(
                f"replayed shard {s['shard']} of {rec['frame']!r} fails "
                "its content hash — use full re-import")
        stats["replay"].append(int(s["shard"]))
    if not stats["replay"]:
        stats["replay"] += [int(s["shard"]) for s in rec["shards"]
                            if s["rows"]]
    out.key = rec["frame"]
    dkv.put(out.key, out)
    return out


def _apply_op(fr, op: dict):
    kind = op.get("op")
    if kind == "cols":
        return fr[list(op["cols"])]
    if kind == "drop":
        return fr.drop(list(op["cols"]))
    if kind == "rename":
        return fr.rename(dict(op["mapping"]))
    if kind == "rows":
        return fr.rows(lineage.unpack_index(op["index"]))
    if kind == "split":
        return fr.split_frame(list(op["ratios"]),
                              seed=int(op["seed"]))[int(op["piece"])]
    from ..rapids import ops as rapids_ops
    if kind == "sort":
        return rapids_ops.sort(fr, list(op["by"]),
                               ascending=list(op["ascending"]))
    if kind == "impute":
        return rapids_ops.impute(fr, op["column"], method=op["method"],
                                 combine_method=op["combine_method"])
    if kind == "scale":
        return rapids_ops.scale(fr, center=bool(op["center"]),
                                scale_=bool(op["scale"]))
    raise RematError(f"unknown lineage op {kind!r}")
