"""Heartbeat / liveness daemon — the water/HeartBeatThread analog.

Each process runs a daemon thread that stamps ``!hb/<node>`` in the DKV
every ``interval`` seconds with its wall-clock time and load facts.  Any
member (or a REST client via /3/Cloud) classifies peers from the stamp
age IN UNITS OF THE STAMP'S OWN INTERVAL (each stamp carries the
interval it was made under, so mixed or non-default intervals classify
correctly): ``alive`` (< 3 intervals), ``suspect`` (< 10), ``dead``
otherwise — the reference's client_disconnect/suspect escalation, minus
UDP multicast (the DKV coordinator is the rendezvous; heartbeats ride
the same DCN control plane as every other key).  Stamps dead for > 100
intervals are garbage-collected by ``members()`` so a crashed-and-
restarted process (new pid ⇒ new node name) does not poison
``cloud_healthy`` forever.

Wall clocks are compared across processes, so the suspect window is
deliberately generous; sub-second skew cannot cause a false ``dead``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import dkv

PREFIX = "!hb/"

_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_node: Optional[str] = None


def node_name() -> str:
    import socket
    return f"{socket.gethostname()}:{os.getpid()}"


def _beat(name: str, interval: float) -> None:
    from .config import config
    # a short retry budget, NOT the full 30 s default: one missed stamp
    # is better than a beat thread blocked past several intervals
    with dkv.retry_budget(config().hb_dkv_budget_s):
        dkv.put(PREFIX + name, {
            "ts": time.time(),
            "interval": interval,
            "pid": os.getpid(),
            "keys": dkv.local_size(),
        })


def start(interval: float = 5.0, name: Optional[str] = None) -> str:
    """Start (or restart) this process's heartbeat thread."""
    global _thread, _node
    stop()
    _node = name or node_name()
    _stop.clear()
    try:
        _beat(_node, interval)          # immediate first stamp, best-effort
    except Exception:                   # noqa: BLE001 — must not fail init
        pass

    def _run():
        while not _stop.wait(interval):
            try:
                _beat(_node, interval)
            except Exception:           # noqa: BLE001 — beat must not die
                pass

    _thread = threading.Thread(target=_run, name="heartbeat", daemon=True)
    _thread.start()
    return _node


def stop() -> None:
    global _thread
    _stop.set()
    if _thread is not None:
        _thread.join(timeout=2.0)
        _thread = None
    if _node is not None:
        try:
            dkv.remove(PREFIX + _node)  # clean departure ≠ failure
        except Exception:               # noqa: BLE001
            pass


def members(interval: float = 5.0, now: Optional[float] = None) -> Dict[str, dict]:
    """Liveness view over every heartbeating process.

    Returns ``{node: {status, age, ...stamp}}``.  ``interval`` is only
    the fallback for stamps that don't carry their own (pre-upgrade
    peers); long-dead stamps are removed from the DKV as a side effect.
    """
    now = time.time() if now is None else now
    out: Dict[str, dict] = {}
    for key in dkv.keys(PREFIX):
        stamp = dkv.get(key)
        if not isinstance(stamp, dict):
            continue
        step = float(stamp.get("interval", interval))
        age = now - float(stamp.get("ts", 0.0))
        if age > 100 * step:            # GC: crashed peer, long gone
            dkv.remove(key)
            continue
        status = ("alive" if age < 3 * step
                  else "suspect" if age < 10 * step else "dead")
        out[key[len(PREFIX):]] = {"status": status,
                                  "age": round(age, 3), **stamp}
    return out
