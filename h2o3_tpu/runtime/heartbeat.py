"""Heartbeat / liveness daemon — the water/HeartBeatThread analog.

Each process runs a daemon thread that stamps ``!hb/<node>`` in the DKV
every ``interval`` seconds with its wall-clock time and load facts.  Any
member (or a REST client via /3/Cloud) classifies peers from the stamp
age IN UNITS OF THE STAMP'S OWN INTERVAL (each stamp carries the
interval it was made under, so mixed or non-default intervals classify
correctly): ``alive`` (< 3 intervals), ``suspect`` (< 10), ``dead``
otherwise — the reference's client_disconnect/suspect escalation, minus
UDP multicast (the DKV coordinator is the rendezvous; heartbeats ride
the same DCN control plane as every other key).  Stamps dead for > 100
intervals are garbage-collected by ``members()`` so a crashed-and-
restarted process (new pid ⇒ new node name) does not poison
``cloud_healthy`` forever.

Wall clocks are compared across processes, so the suspect window is
deliberately generous; sub-second skew cannot cause a false ``dead``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import dkv

PREFIX = "!hb/"

_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_node: Optional[str] = None
_interval: float = 5.0
_atexit_hooked = False


def node_name() -> str:
    import socket
    return f"{socket.gethostname()}:{os.getpid()}"


def _beat(name: str, interval: float) -> None:
    from . import observability as obs
    from .config import config
    cfg = config()
    stamp = {
        "ts": time.time(),
        "interval": interval,
        "pid": os.getpid(),
        "keys": dkv.local_size(),
    }
    try:
        import sys
        if "jax" in sys.modules:        # never boot jax from the beat
            import jax
            # which mesh host (frame shard block) dies with this process
            # — failure._on_dead forwards it to runtime/remat.py
            stamp["proc"] = int(jax.process_index())
    except Exception:                   # noqa: BLE001 — stamp still valid
        pass
    # telemetry rides the stamp: the full (cumulative) metric registry
    # plus a bounded event tail.  Cumulative — not a delta — so a lost
    # or duplicated stamp cannot skew the coordinator's merged view, and
    # the plain-dict stamp is in dkv._local_plain, so an epoch bump
    # re-pushes it to the new coordinator incarnation automatically.
    if cfg.metrics_enabled:
        try:
            import sys
            if "jax" in sys.modules:    # never boot jax from the beat
                from . import cluster
                cluster.sample_memory_gauges()
        except Exception:               # noqa: BLE001 — gauges optional
            pass
        stamp["metrics"] = obs.metrics_wire()
        if cfg.hb_ship_events:
            stamp["events"] = obs.events_wire(cfg.hb_ship_events)
    # a short retry budget, NOT the full 30 s default: one missed stamp
    # is better than a beat thread blocked past several intervals
    with dkv.retry_budget(cfg.hb_dkv_budget_s):
        dkv.put(PREFIX + name, stamp)


def reship() -> bool:
    """Stamp immediately with a fresh telemetry snapshot.

    Called after a DKV epoch bump (``dkv._repush``): the new coordinator
    incarnation gets this worker's metrics without waiting out the beat
    interval, closing the telemetry gap across a coordinator restart."""
    if _node is None or _stop.is_set():
        return False
    from . import observability as obs
    _beat(_node, _interval)
    obs.record("metrics_reship", node=_node)
    return True


def start(interval: float = 5.0, name: Optional[str] = None) -> str:
    """Start (or restart) this process's heartbeat thread."""
    global _thread, _node, _interval, _atexit_hooked
    if not _atexit_hooked:
        # registered after jax's own atexit hooks, so it runs BEFORE
        # them: the beat thread is joined while the backend still exists
        # (the stamp is left behind; members() GC handles stale ones)
        import atexit
        atexit.register(stop, remove=False)
        _atexit_hooked = True
    stop()
    _node = name or node_name()
    _interval = interval
    _stop.clear()
    try:
        _beat(_node, interval)          # immediate first stamp, best-effort
    except Exception:                   # noqa: BLE001 — must not fail init
        pass

    def _run():
        while not _stop.wait(interval):
            try:
                _beat(_node, interval)
            except Exception:           # noqa: BLE001 — beat must not die
                pass

    _thread = threading.Thread(target=_run, name="heartbeat", daemon=True)
    _thread.start()
    return _node


def stop(remove: bool = True) -> None:
    """Halt the beat thread; ``remove=False`` leaves the stamp behind.

    Always join the thread before process exit: the beat samples device
    gauges through jax, and a beat racing interpreter/XLA teardown can
    abort the process from a C++ destructor."""
    global _thread
    _stop.set()
    if _thread is not None:
        _thread.join(timeout=2.0)
        _thread = None
    if remove and _node is not None:
        try:
            dkv.remove(PREFIX + _node)  # clean departure ≠ failure
        except Exception:               # noqa: BLE001
            pass


def members(interval: float = 5.0, now: Optional[float] = None) -> Dict[str, dict]:
    """Liveness view over every heartbeating process.

    Returns ``{node: {status, age, ...stamp}}``.  ``interval`` is only
    the fallback for stamps that don't carry their own (pre-upgrade
    peers); long-dead stamps are removed from the DKV as a side effect.
    """
    now = time.time() if now is None else now
    out: Dict[str, dict] = {}
    for key in dkv.keys(PREFIX):
        stamp = dkv.get(key)
        if not isinstance(stamp, dict):
            continue
        step = float(stamp.get("interval", interval))
        age = now - float(stamp.get("ts", 0.0))
        if age > 100 * step:            # GC: crashed peer, long gone
            dkv.remove(key)
            continue
        status = ("alive" if age < 3 * step
                  else "suspect" if age < 10 * step else "dead")
        out[key[len(PREFIX):]] = {"status": status,
                                  "age": round(age, 3), **stamp}
    return out
