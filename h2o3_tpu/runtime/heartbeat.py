"""Heartbeat / liveness daemon — the water/HeartBeatThread analog.

Each process runs a daemon thread that stamps ``!hb/<node>`` in the DKV
every ``interval`` seconds with its wall-clock time and load facts.  Any
member (or a REST client via /3/Cloud) classifies peers from the stamp
age: ``alive`` (< 3 intervals), ``suspect`` (< 10), ``dead`` otherwise —
the reference's client_disconnect/suspect escalation, minus UDP
multicast (the DKV coordinator is the rendezvous; heartbeats ride the
same DCN control plane as every other key).

Wall clocks are compared across processes, so the suspect window is
deliberately generous; sub-second skew cannot cause a false ``dead``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import dkv

PREFIX = "!hb/"

_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_node: Optional[str] = None


def node_name() -> str:
    import socket
    return f"{socket.gethostname()}:{os.getpid()}"


def _beat(name: str) -> None:
    dkv.put(PREFIX + name, {
        "ts": time.time(),
        "pid": os.getpid(),
        "keys": len(dkv.keys()),
    })


def start(interval: float = 5.0, name: Optional[str] = None) -> str:
    """Start (or restart) this process's heartbeat thread."""
    global _thread, _node
    stop()
    _node = name or node_name()
    _stop.clear()
    _beat(_node)                        # immediate first stamp

    def _run():
        while not _stop.wait(interval):
            try:
                _beat(_node)
            except Exception:           # noqa: BLE001 — beat must not die
                pass

    _thread = threading.Thread(target=_run, name="heartbeat", daemon=True)
    _thread.start()
    return _node


def stop() -> None:
    global _thread
    _stop.set()
    if _thread is not None:
        _thread.join(timeout=2.0)
        _thread = None
    if _node is not None:
        try:
            dkv.remove(PREFIX + _node)  # clean departure ≠ failure
        except Exception:               # noqa: BLE001
            pass


def members(interval: float = 5.0, now: Optional[float] = None) -> Dict[str, dict]:
    """Liveness view over every heartbeating process.

    Returns ``{node: {status, age, ...stamp}}`` with status alive /
    suspect / dead by stamp age in units of the heartbeat interval.
    """
    now = time.time() if now is None else now
    out: Dict[str, dict] = {}
    for key in dkv.keys(PREFIX):
        stamp = dkv.get(key)
        if not isinstance(stamp, dict):
            continue
        age = now - float(stamp.get("ts", 0.0))
        status = ("alive" if age < 3 * interval
                  else "suspect" if age < 10 * interval else "dead")
        out[key[len(PREFIX):]] = {"status": status,
                                  "age": round(age, 3), **stamp}
    return out
