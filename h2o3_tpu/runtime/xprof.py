"""xprof: the device/compiler observability plane.

PR 8's telemetry plane (runtime/observability.py) stops at the dispatch
boundary: spans and histograms time HOST work, and nothing records when
XLA recompiles a program (~6 s per fresh compile on a tunnelled
backend), what a compiled program costs in FLOPs/bytes, or how much of a
bench section's wall clock was compilation.  This module is the layer
below that boundary, riding the same metric registry:

* **Compile ledger** — every cached-program seam (the hist/level
  builders, the tree scan programs, ``map_reduce``, GLM's path runner,
  the fused split search) wraps its ``jax.jit`` product in
  ``register_program(name, jitted)``.  The wrapper compiles
  ahead-of-time (``lower().compile()``) on each new argument signature,
  timing the compile into ``compile_seconds{program}``, bumping
  ``recompiles_total{program,reason}`` and publishing the compiled
  program's ``cost_analysis()`` / ``memory_analysis()`` as
  ``program_flops{program}``, ``program_bytes_accessed{program}`` and
  ``program_temp_bytes{program}`` gauges.  Called under an active trace
  the wrapper is transparent (the program inlines into the outer trace
  exactly as before); any AOT failure downgrades the wrapper to the
  plain jitted function permanently, so the ledger can never break a
  training path it observes.

  Recompile reasons: ``first`` (program name never compiled in this
  process), ``cluster_reinit`` (first compile after
  ``cluster._invalidate_compiled_caches()`` flushed the compiled
  caches), ``shape_change`` (every other recompile — a new argument
  signature, or a seam that rebuilds its program per call, like
  ``map_reduce`` over a fresh lambda).

* **jax.monitoring backstop** — a duration listener on
  ``/jax/core/compile/*`` records every backend compile jax performs,
  including seams the ledger does not wrap, into
  ``jax_compile_seconds{event}`` (guarded: jax builds without
  ``jax.monitoring`` simply skip it).

* **Device-phase timing** — ``tree_phase_seconds`` measures host
  dispatch only (the level loop runs at trace time).  With
  ``H2O3_TPU_DEVICE_TIMING=sampled|full``, ``maybe_device_sync``
  block-until-ready-syncs eagerly-dispatched work (every Nth call under
  ``sampled``; every call under ``full``) and records the true
  dispatch→ready wall time into ``tree_phase_device_seconds{phase}``.
  ``bench_pieces.py xprof`` pins the ``sampled`` overhead < 2%.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional, Tuple

from . import observability as obs

_lock = threading.Lock()

# name -> ledger entry (survives builder-LRU clears and metric resets,
# so recompile REASONS stay correct across cluster re-inits)
_LEDGER: "collections.OrderedDict[str, dict]" = collections.OrderedDict()

# global invalidation epoch: cluster._invalidate_compiled_caches() bumps
# it; wrappers compare their snapshot per call and drop stale compiled
# executables (which closed over the dead mesh) without any per-wrapper
# bookkeeping on the invalidation side.
_EPOCH = 0

# cap of AOT-compiled signatures retained per program (oldest evicted);
# jax's own jit cache backs anything beyond it
_MAX_SIGS_PER_PROGRAM = 32


# ------------------------------------------------------------- signatures

def _sig_of(x) -> tuple:
    """Signature atom: arrays by (shape, dtype, sharding), scalars by
    type (jit traces python scalars to one weak-typed aval per type),
    containers structurally.  Statics are keyed by VALUE by the caller."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        sharding = getattr(x, "sharding", None)
        return ("a", tuple(shape), str(dtype),
                str(sharding) if sharding is not None else "")
    if isinstance(x, (bool, int, float, complex)) or x is None:
        return ("s", type(x).__name__)
    if isinstance(x, (tuple, list)):
        return ("t", tuple(_sig_of(v) for v in x))
    return ("o", type(x).__name__, repr(x)[:120])


def _static_key(x) -> tuple:
    try:
        hash(x)
        return ("v", x)
    except TypeError:
        return ("v", repr(x)[:200])


# ---------------------------------------------------------------- ledger

def _note_compile(name: str, seconds: float, compiled) -> str:
    """Record one compile into the ledger + registry; returns the reason."""
    global _EPOCH
    with _lock:
        ent = _LEDGER.get(name)
        if ent is None:
            reason = "first"
            ent = _LEDGER.setdefault(name, {
                "compiles": 0, "compile_s": 0.0, "last_compile_s": 0.0,
                "reasons": collections.Counter(), "epoch": _EPOCH,
                "flops": None, "bytes_accessed": None, "temp_bytes": None,
            })
        elif ent["epoch"] != _EPOCH:
            reason = "cluster_reinit"
        else:
            reason = "shape_change"
        ent["epoch"] = _EPOCH
        ent["compiles"] += 1
        ent["compile_s"] += seconds
        ent["last_compile_s"] = seconds
        ent["reasons"][reason] += 1
    obs.observe("compile_seconds", seconds, program=name)
    obs.inc("recompiles_total", program=name, reason=reason)
    _publish_costs(name, compiled)
    return reason


def _publish_costs(name: str, compiled) -> None:
    """cost_analysis()/memory_analysis() -> per-program gauges + ledger."""
    flops = bytes_accessed = temp = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            flops = ca.get("flops")
            bytes_accessed = ca.get("bytes accessed")
    except Exception:                    # noqa: BLE001 — backend-optional
        pass
    try:
        ma = compiled.memory_analysis()
        temp = getattr(ma, "temp_size_in_bytes", None)
    except Exception:                    # noqa: BLE001
        pass
    if flops is not None:
        obs.set_gauge("program_flops", float(flops), program=name)
    if bytes_accessed is not None:
        obs.set_gauge("program_bytes_accessed", float(bytes_accessed),
                      program=name)
    if temp is not None:
        obs.set_gauge("program_temp_bytes", float(temp), program=name)
    with _lock:
        ent = _LEDGER.get(name)
        if ent is not None:
            if flops is not None:
                ent["flops"] = float(flops)
            if bytes_accessed is not None:
                ent["bytes_accessed"] = float(bytes_accessed)
            if temp is not None:
                ent["temp_bytes"] = float(temp)


def invalidate(reason: str = "cluster_reinit") -> None:
    """Mark every registered program stale (cluster re-init flushes the
    compiled caches): the NEXT compile of each program is attributed to
    ``reason`` and wrappers drop their stale executables lazily."""
    global _EPOCH
    with _lock:
        _EPOCH += 1
    obs.record("xprof_invalidate", reason=reason)


def ledger_snapshot() -> dict:
    """Plain-data view of the compile ledger (bench compile-vs-steady
    split, the tier-1 compile-stats artifact, /metrics cross-checks)."""
    with _lock:
        programs = {
            name: {
                "compiles": ent["compiles"],
                "compile_s": round(ent["compile_s"], 6),
                "last_compile_s": round(ent["last_compile_s"], 6),
                "reasons": dict(ent["reasons"]),
                "flops": ent["flops"],
                "bytes_accessed": ent["bytes_accessed"],
                "temp_bytes": ent["temp_bytes"],
            }
            for name, ent in _LEDGER.items()
        }
        epoch = _EPOCH
    return {
        "programs": programs,
        "epoch": epoch,
        "total_compiles": sum(p["compiles"] for p in programs.values()),
        "total_compile_s": round(
            sum(p["compile_s"] for p in programs.values()), 6),
    }


def reset_ledger() -> None:
    """Tests only: forget every program (reasons restart at 'first')."""
    with _lock:
        _LEDGER.clear()


# ------------------------------------------------------------- registrar

def _tracing() -> bool:
    try:
        import jax.core
        return not jax.core.trace_state_clean()
    except Exception:                    # noqa: BLE001
        return False


class _Program:
    """AOT-compiling wrapper around one jitted program (see module doc).

    Calls with a previously-seen signature dispatch the stored compiled
    executable directly (no retrace); a new signature pays one timed
    ``lower().compile()``.  Under an active jax trace, or after any AOT
    failure, calls go straight to the wrapped jitted function."""

    def __init__(self, name: str, jitted, static_argnums: Tuple[int, ...],
                 static_argnames: Tuple[str, ...], orig=None):
        self.name = name
        self.jitted = jitted
        self.orig = orig if orig is not None else jitted
        self.static_argnums = tuple(static_argnums)
        self.static_argnames = tuple(static_argnames)
        self.fallback = False
        self.calls = 0
        self.compiled: "collections.OrderedDict[tuple, Any]" = \
            collections.OrderedDict()
        self.epoch = _EPOCH
        self.__name__ = name
        self.__qualname__ = name

    def _sig(self, args, kwargs) -> tuple:
        parts = []
        for i, a in enumerate(args):
            parts.append(_static_key(a) if i in self.static_argnums
                         else _sig_of(a))
        for k in sorted(kwargs):
            parts.append((k, _static_key(kwargs[k])
                          if k in self.static_argnames
                          else _sig_of(kwargs[k])))
        return tuple(parts)

    def _strip_static(self, args, kwargs):
        dyn_args = tuple(a for i, a in enumerate(args)
                         if i not in self.static_argnums)
        dyn_kwargs = {k: v for k, v in kwargs.items()
                      if k not in self.static_argnames}
        return dyn_args, dyn_kwargs

    def _compile(self, args, kwargs):
        t0 = time.perf_counter()
        try:
            compiled = self.jitted.lower(*args, **kwargs).compile()
        except Exception as e:           # noqa: BLE001 — never break a seam
            self.fallback = True
            obs.record("xprof_fallback", program=self.name,
                       stage="compile", error=type(e).__name__)
            return None
        _note_compile(self.name, time.perf_counter() - t0, compiled)
        return compiled

    def __call__(self, *args, **kwargs):
        if self.fallback or not obs.enabled() or _tracing():
            return self._passthrough(args, kwargs)
        if self.epoch != _EPOCH:
            # cluster re-init flushed the mesh these executables bound
            self.compiled.clear()
            self.epoch = _EPOCH
        sig = self._sig(args, kwargs)
        compiled = self.compiled.get(sig)
        if compiled is None:
            compiled = self._compile(args, kwargs)
            if compiled is None:
                return self.jitted(*args, **kwargs)
            self.compiled[sig] = compiled
            while len(self.compiled) > _MAX_SIGS_PER_PROGRAM:
                self.compiled.popitem(last=False)
        dyn_args, dyn_kwargs = self._strip_static(args, kwargs)
        self.calls += 1
        t0 = time.perf_counter()
        try:
            out = compiled(*dyn_args, **dyn_kwargs)
        except Exception as e:           # noqa: BLE001 — never break a seam
            self.fallback = True
            self.compiled.clear()
            obs.record("xprof_fallback", program=self.name, stage="call",
                       error=type(e).__name__)
            return self.jitted(*args, **kwargs)
        maybe_device_sync(self.name, self.calls, t0, out)
        return out

    def _passthrough(self, args, kwargs):
        # under a trace prefer the ORIGINAL function (inlines into the
        # outer program without a nested-jit hop, exactly as before
        # registration); disabled/fallback paths keep the jitted one
        fn = self.orig if (_tracing() and not self.fallback) else self.jitted
        return fn(*args, **kwargs)

    # the builders' LRU values are sometimes introspected (and passed to
    # jax.export, which duck-checks the stages.Wrapped protocol: lower +
    # trace); delegate the common jit surface so the wrapper stays a
    # drop-in
    def lower(self, *args, **kwargs):
        return self.jitted.lower(*args, **kwargs)

    def trace(self, *args, **kwargs):
        return self.jitted.trace(*args, **kwargs)

    def __repr__(self):
        return (f"<xprof.program {self.name!r} sigs={len(self.compiled)} "
                f"fallback={self.fallback}>")


def register_program(name: str, jitted, static_argnums: Tuple[int, ...] = (),
                     static_argnames: Tuple[str, ...] = (), orig=None):
    """Wrap a ``jax.jit`` product in the compile ledger (module doc).

    ``static_argnums``/``static_argnames`` MUST mirror the jit's own
    statics: statics key the signature by value and are stripped before
    invoking the compiled executable.  ``orig`` (optional) is the plain
    traceable function used when the wrapper is entered under an active
    trace — defaults to ``jitted`` (nested jit calls inline too)."""
    return _Program(name, jitted, static_argnums, static_argnames, orig)


# --------------------------------------------------- monitoring backstop

_listener_installed = False


def install_monitoring_listener() -> bool:
    """Record every jax backend compile into ``jax_compile_seconds{event}``
    via ``jax.monitoring`` — the backstop for seams the ledger does not
    wrap.  Idempotent; returns False on jax builds without the API."""
    global _listener_installed
    with _lock:
        if _listener_installed:
            return True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event.startswith("/jax/core/compile"):
                obs.observe("jax_compile_seconds", duration,
                            event=event.rsplit("/", 1)[-1])

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:                    # noqa: BLE001 — jax-version guard
        return False
    with _lock:
        _listener_installed = True
    return True


# ----------------------------------------------------- device-phase time

def device_timing_mode() -> str:
    """Effective ``H2O3_TPU_DEVICE_TIMING``: ``off`` | ``sampled`` |
    ``full`` (unknown values read as ``off``)."""
    from .config import config
    mode = config().device_timing
    return mode if mode in ("sampled", "full") else "off"


def maybe_device_sync(phase: str, seq: int, started: float, out) -> bool:
    """Block until ``out`` is device-ready and record the dispatch→ready
    wall time into ``tree_phase_device_seconds{phase}``.

    ``started`` is the caller's ``time.perf_counter()`` taken BEFORE the
    dispatch, so the observation covers real device execution, not just
    the wait.  Under ``sampled`` only every Nth ``seq``
    (``H2O3_TPU_DEVICE_TIMING_SAMPLE``, default 4) syncs — the bounded-
    overhead mode training keeps on; ``full`` syncs every call.
    Returns whether a sync happened."""
    if not obs.enabled():
        return False
    mode = device_timing_mode()
    if mode == "off":
        return False
    if mode == "sampled":
        from .config import config
        every = max(int(config().device_timing_sample), 1)
        if seq % every:
            return False
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:                    # noqa: BLE001 — tracers, tokens
        return False
    dt = time.perf_counter() - started
    obs.observe("tree_phase_device_seconds", dt, phase=phase)
    try:
        # feed the autotuner's measured-refinement loop: the sample
        # attributes to whatever config the calling thread's active
        # decision scope is running (no scope -> no-op)
        from . import autotune
        autotune.on_device_sample(phase, dt)
    except Exception:                    # noqa: BLE001 — observer only
        pass
    return True


def count_kernel_launches(fn, *args, **kwargs) -> int:
    """Static kernel-dispatch sites in ``fn``'s traced program.

    Traces ``fn`` on the given args (abstract evaluation only — nothing
    executes) and counts the jaxpr eqns that dispatch a compiled kernel
    program: ``shard_map`` (every hist/split/partition kernel seam goes
    through one) and ``pallas_call`` (a hand-written kernel outside a
    seam).  Sub-jaxprs of higher-order primitives (scan/cond/pjit/...)
    are descended and each body is counted ONCE — so a level-unrolled
    tree build reports one site per level while the scan-fused build
    reports a depth-independent handful.  That static count is the
    dispatch-overhead proxy the treescan bench pins: XLA launches the
    unrolled program's kernels one by one, while a ``lax.scan`` body is
    a single compiled loop on device.
    """
    import jax

    jaxpr = jax.make_jaxpr(fn, **kwargs)(*args)

    def _subjaxprs(v):
        out = []
        for x in (v if isinstance(v, (list, tuple)) else [v]):
            if hasattr(x, "jaxpr") and hasattr(x, "consts"):
                out.append(x.jaxpr)          # ClosedJaxpr
            elif hasattr(x, "eqns"):
                out.append(x)                # raw Jaxpr
        return out

    def _count(jx) -> int:
        n = 0
        for eqn in jx.eqns:
            if eqn.primitive.name in ("shard_map", "pallas_call"):
                n += 1
            for val in eqn.params.values():
                for sub in _subjaxprs(val):
                    n += _count(sub)
        return n

    return _count(jaxpr.jaxpr)
