"""Elastic, admission-controlled, fair-share cluster scheduler.

Reference: ``water/H2O.java`` runs one fork/join pool per priority level
and locks cloud membership at the first job — a cluster can never grow,
and a big build monopolizes the JVM until it finishes.  Here the scarce
resource is the device mesh, and membership is heartbeat-driven, so the
scheduler composes the repo's existing planes instead:

* **Admission + fair share** — jobs arrive with ``priority`` (lower runs
  first), a ``device_budget`` (fraction of the row mesh, or an explicit
  chip count) and a ``retry_budget``.  The dispatcher packs jobs whose
  budgets fit the free chip count; ties within a priority level break on
  accumulated per-tenant chip-seconds (classic fair share), then FIFO.
  A bounded admission queue rejects overload instead of buffering it.
  On the virtual-host CI backend every compiled program still timeshares
  the full mesh — the budget ledger bounds *co-residency* (how many jobs
  run at once), which is what the makespan bench measures; true submesh
  placement slots into ``_chips_for`` when per-job meshes land.

* **Durability** — queue/assignment state is mirrored as plain records
  under ``!sched/<jobkey>`` so a WAL-backed coordinator (runtime/dkv.py)
  persists it across restarts; ``readmit()`` walks the recovery journal
  (runtime/recovery.py) plus those records and re-submits every job that
  was queued or in flight, resuming from progress snapshots where they
  exist.

* **Degraded mode** — when the failure watchdog classifies a host dead,
  ``on_node_dead`` requeues that host's in-flight jobs from their
  journal entries (snapshot-resume) instead of failing them; the SAME
  Job object is re-dispatched onto the shrunken mesh, so callers blocked
  in ``join()`` still get their model.  Jobs without retry budget or
  journal fall through to the watchdog's normal fail path.

* **Elastic membership** — with ``H2O3_TPU_SCHED_ELASTIC=1`` an observer
  thread watches ``heartbeat.members()``; a newly-alive host arms a
  fenced mesh rebuild that ``chunk_fence()`` applies at the next
  job-chunk boundary (tree drivers call it from ``chunk_schedule``),
  driving ``cluster.init(hosts=...)`` -> ``_invalidate_compiled_caches``
  exactly once.  A ``Quarantine`` ledger damps flapping hosts so a
  kill/rejoin loop cannot thrash rebuilds.

Prometheus series: ``sched_queue_depth``, ``sched_running_jobs``,
``sched_admission_rejected_total{reason}``, ``sched_requeue_total{reason}``,
``sched_rebuild_total{reason}``, ``sched_join_total``,
``sched_join_quarantined_total``, ``sched_quarantined_hosts``.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

from . import dkv
from .config import config
from .observability import inc, log, record, set_gauge

#: plain DKV records holding queue/assignment state (WAL-durable on a
#: coordinator, epoch-repushed to a restarted one)
SCHED_PREFIX = "!sched/"

# reference-like priority levels (water/H2O.java H2OCountedCompleter)
PRIORITY_ADMIN = 0
PRIORITY_INTERACTIVE = 50
PRIORITY_BUILD = 100


# ---------------------------------------------------------------- device lease
class DeviceLease:
    """Serializes compiled-program launches across concurrent jobs.

    XLA's in-process collectives deadlock when two SPMD programs that
    contain cross-module collectives execute concurrently: each device
    stream picks up work from whichever program enqueued first, so the
    per-device participants of the two executions interleave at the
    collective rendezvous and neither can complete.  Training drivers
    hold the lease for the device-touching part of a fit and *yield* it
    at every chunk boundary, so concurrent jobs time-share the mesh
    chunk-by-chunk — small jobs still finish far ahead of a co-resident
    large one — without ever launching collectives on top of each other.

    Reentrant per thread (CV folds fit inline under the outer fit's
    lease).  ``force_release`` breaks the lease of a worker wedged in a
    collective that lost a member, so a requeued retry can launch.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._owner: Optional[threading.Thread] = None
        self._depth = 0
        self._waiters = 0

    def acquire(self) -> None:
        me = threading.current_thread()
        with self._cv:
            if self._owner is not None and self._owner is not me:
                self._waiters += 1
                try:
                    while self._owner is not None and self._owner is not me:
                        self._cv.wait(timeout=1.0)
                finally:
                    self._waiters -= 1
            self._owner = me
            self._depth += 1

    def release(self) -> None:
        with self._cv:
            if self._owner is not threading.current_thread():
                return
            self._depth -= 1
            if self._depth <= 0:
                self._owner, self._depth = None, 0
                self._cv.notify_all()

    def yield_turn(self) -> None:
        """Give waiters a chunk-sized window; no-op when not the owner."""
        me = threading.current_thread()
        with self._cv:
            if self._owner is not me:
                return
            if not self._waiters:       # uncontended: keep the lease
                return
            depth, self._owner, self._depth = self._depth, None, 0
            self._cv.notify_all()
        # Condition wakeups are not fair: without this pause the
        # releasing thread usually re-acquires before any waiter runs
        time.sleep(0.001)
        with self._cv:
            self._waiters += 1
            try:
                while self._owner is not None:
                    self._cv.wait(timeout=1.0)
            finally:
                self._waiters -= 1
            self._owner, self._depth = me, depth

    def force_release(self, thread: Optional[threading.Thread]) -> None:
        """Break the lease held by a wedged worker (node-death requeue)."""
        with self._cv:
            if thread is not None and self._owner is thread:
                self._owner, self._depth = None, 0
                self._cv.notify_all()


#: process-wide — the hazard is per-backend, not per-scheduler
DEVICE_LEASE = DeviceLease()


@contextmanager
def device_slot():
    """Hold the device lease for a driver's device-touching section."""
    DEVICE_LEASE.acquire()
    try:
        yield
    finally:
        DEVICE_LEASE.release()


# ------------------------------------------------------------------ quarantine
class Quarantine:
    """Flap damping for elastic membership.

    A host may join (and trigger a rebuild) at most ``max_flaps`` times
    per sliding ``window_s``; past that it is quarantined until the
    window expires — joins are acknowledged but arm no rebuild, so a
    kill/rejoin loop costs at most ``max_flaps`` rebuilds per window.
    """

    def __init__(self, window_s: float = 60.0, max_flaps: int = 2):
        self.window_s = float(window_s)
        self.max_flaps = int(max_flaps)
        self._joins: dict = {}      # host -> [join ts within window]
        self._until: dict = {}      # host -> quarantined-until ts

    def note_join(self, host: str, now: Optional[float] = None) -> bool:
        """Record a join; True if the host is admitted (may rebuild)."""
        now = time.time() if now is None else now
        ts = [t for t in self._joins.get(host, ()) if now - t < self.window_s]
        ts.append(now)
        self._joins[host] = ts
        if now < self._until.get(host, 0.0):
            return False
        if len(ts) > self.max_flaps:
            self._until[host] = now + self.window_s
            log.warning("scheduler: quarantining flapping host %s "
                        "(%d joins in %.0fs window)", host, len(ts),
                        self.window_s)
            record("host_quarantined", node=host, joins=len(ts))
            return False
        return True

    def is_quarantined(self, host: str, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        return now < self._until.get(host, 0.0)

    def active(self, now: Optional[float] = None) -> list:
        now = time.time() if now is None else now
        return sorted(h for h, u in self._until.items() if u > now)

    def describe(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        return {"window_s": self.window_s, "max_flaps": self.max_flaps,
                "quarantined": self.active(now)}


# --------------------------------------------------------------------- entries
class _Entry:
    __slots__ = ("job", "fn", "priority", "budget", "retry_budget", "user",
                 "seq", "chips", "submit_ts", "released", "thread")

    def __init__(self, job, fn, priority, budget, retry_budget, user, seq):
        self.job = job
        self.fn = fn
        self.priority = priority
        self.budget = budget
        self.retry_budget = retry_budget
        self.user = user
        self.seq = seq
        self.chips = 0
        self.submit_ts = time.time()
        self.released = False
        self.thread: Optional[threading.Thread] = None


class ClusterScheduler:
    """Admission-controlled fair-share scheduler (see module docstring).

    Keeps the ``JobScheduler`` contract — ``PRIORITY_*`` constants and
    ``submit(job, fn, priority=...)`` — so existing callers run
    unchanged; they just get budget-aware packing instead of a fixed
    2-worker pool.
    """

    PRIORITY_ADMIN = PRIORITY_ADMIN
    PRIORITY_INTERACTIVE = PRIORITY_INTERACTIVE
    PRIORITY_BUILD = PRIORITY_BUILD

    def __init__(self, capacity: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 elastic: Optional[bool] = None):
        cfg = config()
        self._capacity_override = capacity or cfg.sched_capacity or None
        self._queue_limit = (queue_limit if queue_limit is not None
                             else cfg.sched_queue_limit)
        self._default_budget = cfg.sched_default_budget
        self._queue: list = []               # pending _Entry, submit order
        self._running: dict = {}             # job.key -> _Entry
        self._used_chips = 0
        self._usage: dict = {}               # tenant -> chip-seconds served
        self._cv = threading.Condition()
        self._seq = 0
        self._shutdown = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="sched-dispatch")
        self._dispatcher.start()
        # ------------------------------------------------ elastic membership
        self._elastic = cfg.sched_elastic if elastic is None else elastic
        self._known: set = set()             # alive hosts last observed
        self._seeded = False                 # first observation baselines
        self._pending_rebuild = False
        self._rebuild_lock = threading.Lock()
        self.quarantine = Quarantine(cfg.sched_quarantine_window_s,
                                     cfg.sched_quarantine_flaps)
        self._stop_member = threading.Event()
        if self._elastic:
            threading.Thread(target=self._member_loop, daemon=True,
                             name="sched-membership").start()

    # -------------------------------------------------------------- capacity
    def capacity(self) -> int:
        """Row-mesh chip count — from the live mesh when booted."""
        if self._capacity_override:
            return int(self._capacity_override)
        from . import cluster as _cluster_mod
        cl = _cluster_mod._cluster
        if cl is not None:
            return int(cl.n_row_shards)
        return 8                    # pre-boot fallback; real value on boot

    def _chips_for(self, budget, cap: int) -> int:
        """Budget spec -> chip count.  ``None`` -> scheduler default
        fraction; float in (0, 1] -> fraction of the row mesh; int >= 1
        -> explicit chip count (capped at the mesh)."""
        if budget is None:
            budget = self._default_budget
        if isinstance(budget, float) and 0.0 < budget <= 1.0:
            return max(1, round(budget * cap))
        n = int(budget)
        if n < 1:
            raise ValueError(f"device_budget must be a fraction in (0, 1] "
                             f"or a chip count >= 1, got {budget!r}")
        return min(n, cap)

    # ---------------------------------------------------------------- submit
    def submit(self, job, fn: Callable[[Any], Any],
               priority: int = PRIORITY_BUILD,
               device_budget=None, retry_budget: int = 0,
               user: Optional[str] = None):
        """Admit ``fn(job)``; returns the job immediately (poll/join it).

        Raises ``RuntimeError`` when the admission queue is full — the
        caller sheds load instead of the cluster buffering it."""
        self._chips_for(device_budget, self.capacity())   # validate early
        with self._cv:
            if self._shutdown:
                raise RuntimeError("job scheduler is stopped")
            if len(self._queue) >= self._queue_limit:
                inc("sched_admission_rejected_total", reason="queue_full")
                raise RuntimeError(
                    f"scheduler admission queue full "
                    f"({len(self._queue)} queued, limit {self._queue_limit})")
            self._seq += 1
            ent = _Entry(job, fn, priority, device_budget, retry_budget,
                         user, self._seq)
            job._queued = True
            job._owner = self
            job.priority = priority
            job.device_budget = device_budget
            job.retry_budget = retry_budget
            job.user = user
            self._queue.append(ent)
            set_gauge("sched_queue_depth", len(self._queue))
            self._persist(ent, "queued")
            self._cv.notify_all()
        return job

    def _persist(self, ent: _Entry, state: str, **extra) -> None:
        """Mirror scheduling state as a plain (WAL-durable) DKV record."""
        try:
            dkv.put(SCHED_PREFIX + ent.job.key, {
                "job": ent.job.key, "description": ent.job.description,
                "priority": ent.priority, "device_budget": ent.budget,
                "retry_budget": ent.retry_budget, "user": ent.user,
                "state": state, "chips": ent.chips, "seq": ent.seq,
                "retries": getattr(ent.job, "retries", 0),
                "ts": time.time(), **extra})
        except Exception:           # noqa: BLE001 — state mirror best-effort
            pass

    def _unpersist(self, job) -> None:
        try:
            dkv.remove(SCHED_PREFIX + job.key)
        except Exception:           # noqa: BLE001
            pass

    # -------------------------------------------------------------- dispatch
    def _pick_locked(self) -> Optional[_Entry]:
        """Best admissible entry: (priority, tenant usage, seq) order among
        those whose chip demand fits the free capacity.  An idle mesh
        always admits the front-runner so demand > capacity cannot
        deadlock the queue."""
        cap = self.capacity()
        free = cap - self._used_chips
        best = None
        best_key = None
        best_chips = 0
        for ent in self._queue:
            try:
                chips = self._chips_for(ent.budget, cap)
            except ValueError:
                chips = cap
            if chips > free and self._used_chips > 0:
                continue
            k = (ent.priority, self._usage.get(ent.user or "", 0.0), ent.seq)
            if best_key is None or k < best_key:
                best, best_key, best_chips = ent, k, chips
        if best is not None:
            self._queue.remove(best)
            best.chips = best_chips
            self._used_chips += best.chips
            self._running[best.job.key] = best
            set_gauge("sched_queue_depth", len(self._queue))
            set_gauge("sched_running_jobs", len(self._running))
        return best

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                ent = self._pick_locked()
                while ent is None:
                    if self._shutdown and not self._queue:
                        return
                    self._cv.wait(timeout=0.25)
                    ent = self._pick_locked()
            threading.Thread(target=self._run_entry, args=(ent,),
                             daemon=True,
                             name=f"sched-run-{ent.job.key}").start()

    def _run_entry(self, ent: _Entry) -> None:
        from . import failure
        job = ent.job
        ent.thread = threading.current_thread()
        self._persist(ent, "running")
        t0 = time.monotonic()
        try:
            failure.maybe_inject("sched_assign")
            job.run(ent.fn)
        except BaseException as e:   # noqa: BLE001
            # a worker-thread exception must always reach the job — even
            # one thrown outside Job.run (injection, scheduler bugs)
            if not job._done.is_set():
                job.fail(e)
        finally:
            with self._cv:
                if self._running.get(job.key) is ent:
                    self._running.pop(job.key)
                if not ent.released:
                    ent.released = True
                    self._used_chips -= ent.chips
                tenant = ent.user or ""
                self._usage[tenant] = (self._usage.get(tenant, 0.0)
                                       + ent.chips * (time.monotonic() - t0))
                requeued = any(e.job is job for e in self._queue)
                set_gauge("sched_running_jobs", len(self._running))
                self._cv.notify_all()
            if not requeued:
                if job.status == "FAILED":
                    self._persist(ent, "failed")
                else:
                    self._unpersist(job)

    # ---------------------------------------------------------------- cancel
    def try_cancel(self, job) -> bool:
        """Dequeue a queued-but-unstarted job and mark it CANCELLED
        without ever running it.  False if it already left the queue
        (Job.cancel's cooperative flag covers the running case)."""
        with self._cv:
            for ent in self._queue:
                if ent.job is job:
                    self._queue.remove(ent)
                    set_gauge("sched_queue_depth", len(self._queue))
                    break
            else:
                return False
        job._mark_cancelled()
        self._unpersist(job)
        record("sched_cancel_dequeued", job=job.key)
        return True

    # --------------------------------------------------------- degraded mode
    def on_node_dead(self, node: str, err: BaseException) -> set:
        """Requeue the dead host's in-flight jobs from their journal
        entries; returns the requeued job keys (the watchdog fails the
        rest).  The wedged worker thread's chips are released NOW — a
        gang that lost a member never completes, and the run-token guard
        in Job.run keeps the stale thread from clobbering the retry.
        The requeued entry resumes through ``recovery.resume_entry``,
        which repairs the training frame's lost shards from lineage
        (``runtime/remat.py``) before retraining — the data-plane half
        of degraded-mode survival."""
        requeued: set = set()
        with self._cv:
            for key, ent in list(self._running.items()):
                job = ent.job
                retries = getattr(job, "retries", 0)
                if (ent.retry_budget and retries < ent.retry_budget
                        and job.journal_uri):
                    self._running.pop(key)
                    if not ent.released:
                        ent.released = True
                        self._used_chips -= ent.chips
                    job._reset_for_retry()
                    self._seq += 1
                    new = _Entry(job, _resume_fn(job.journal_uri),
                                 ent.priority, ent.budget, ent.retry_budget,
                                 ent.user, self._seq)
                    self._queue.append(new)
                    inc("sched_requeue_total", reason="node_dead")
                    record("sched_requeue", job=key, node=node,
                           retries=job.retries)
                    log.warning("scheduler: requeueing %s after %s died "
                                "(retry %d/%d)", key, node, job.retries,
                                ent.retry_budget)
                    self._persist(new, "queued")
                    requeued.add(key)
                    # the stale worker may be wedged inside a collective
                    # that lost a member — holding the device lease; the
                    # retry cannot launch until the lease is broken
                    DEVICE_LEASE.force_release(ent.thread)
            set_gauge("sched_queue_depth", len(self._queue))
            set_gauge("sched_running_jobs", len(self._running))
            self._cv.notify_all()
        return requeued

    # ------------------------------------------------------------ membership
    def _member_loop(self) -> None:
        cfg = config()
        while not self._stop_member.wait(cfg.sched_member_poll_s):
            if self._shutdown:
                return
            try:
                self.observe_members()
            except Exception:        # noqa: BLE001 — observer must survive
                pass

    def observe_members(self, members: Optional[dict] = None,
                        now: Optional[float] = None) -> None:
        """One membership observation: new alive hosts arm a fenced
        rebuild (unless quarantined).  The first observation baselines
        the membership — booting next to an existing cloud must not arm
        a rebuild for hosts that were always there."""
        from . import failure, heartbeat
        if members is None:
            members = heartbeat.members()
        now = time.time() if now is None else now
        alive = {n for n, m in members.items()
                 if m.get("status") == "alive"}
        with self._cv:
            joined = set() if not self._seeded else alive - self._known
            self._seeded = True
            self._known = alive
        for node in sorted(joined):
            failure.maybe_inject("host_join")
            if self.quarantine.note_join(node, now):
                inc("sched_join_total")
                record("host_join", node=node)
                log.warning("scheduler: host %s joined; mesh rebuild armed "
                            "for the next chunk boundary", node)
                with self._cv:
                    self._pending_rebuild = True
            else:
                inc("sched_join_quarantined_total")
                record("host_join_quarantined", node=node)
        set_gauge("sched_quarantined_hosts",
                  len(self.quarantine.active(now)))

    def apply_rebuild(self) -> bool:
        """Apply an armed mesh rebuild (called at a chunk boundary)."""
        with self._rebuild_lock:
            with self._cv:
                if not self._pending_rebuild:
                    return False
                self._pending_rebuild = False
                alive = len(self._known) or 1
            from . import cluster as _cluster_mod
            cl = _cluster_mod._cluster
            if cl is None:
                return False
            n_row = cl.n_row_shards
            hosts = _fit_hosts(alive, n_row)
            if hosts == cl.mesh.shape[_cluster_mod.HOST_AXIS]:
                record("sched_rebuild_skipped", hosts=hosts)
                return False
            log.warning("scheduler: fenced mesh rebuild -> hosts=%d "
                        "(%d alive)", hosts, alive)
            _cluster_mod.init(hosts=hosts)
            inc("sched_rebuild_total", reason="host_join")
            record("sched_rebuild", hosts=hosts, alive=alive)
            return True

    # ------------------------------------------------------------- introspect
    def describe(self) -> dict:
        with self._cv:
            cap = self.capacity()
            return {
                "capacity_chips": cap,
                "used_chips": self._used_chips,
                "free_chips": cap - self._used_chips,
                "queue_limit": self._queue_limit,
                "elastic": self._elastic,
                "pending_rebuild": self._pending_rebuild,
                "known_hosts": sorted(self._known),
                "fair_share_usage": dict(self._usage),
                "quarantine": self.quarantine.describe(),
                "queued": [{
                    "job": e.job.key, "description": e.job.description,
                    "priority": e.priority, "device_budget": e.budget,
                    "retry_budget": e.retry_budget, "user": e.user,
                    "waiting_s": round(time.time() - e.submit_ts, 3),
                } for e in self._queue],
                "running": [{
                    "job": e.job.key, "description": e.job.description,
                    "priority": e.priority, "chips": e.chips,
                    "user": e.user, "retries": getattr(e.job, "retries", 0),
                } for e in self._running.values()],
            }

    def stop(self) -> None:
        """Stop accepting work; the dispatcher drains what is queued."""
        from . import job as _job_mod
        with self._cv:
            self._shutdown = True
            self._stop_member.set()
            self._cv.notify_all()
        with _job_mod._sched_lock:
            if _job_mod._scheduler is self:
                _job_mod._scheduler = None


# ----------------------------------------------------------------- module api
def _fit_hosts(alive: int, n_row: int) -> int:
    """Largest host-axis size <= alive that divides the row mesh."""
    for h in range(min(alive, n_row), 0, -1):
        if n_row % h == 0:
            return h
    return 1


def _resume_fn(uri: str) -> Callable[[Any], Any]:
    """Driver fn that resumes one journal entry onto the current mesh."""
    def _fn(job):
        from . import recovery
        return recovery.resume_entry(uri, job=job)
    return _fn


def _active() -> Optional[ClusterScheduler]:
    """The live singleton, or None — never constructs (hot paths)."""
    from . import job as _job_mod
    s = _job_mod._scheduler
    return s if isinstance(s, ClusterScheduler) else None


def chunk_fence() -> bool:
    """Per-chunk hook for training drivers: applies an armed elastic
    mesh rebuild at this chunk boundary (True if the mesh was rebuilt —
    the driver's next compile re-traces against the new mesh), then
    yields the device lease so co-resident jobs interleave
    chunk-by-chunk instead of launching collectives concurrently."""
    s = _active()
    rebuilt = False
    if s is not None and s._pending_rebuild:
        rebuilt = s.apply_rebuild()
    DEVICE_LEASE.yield_turn()
    return rebuilt


def on_node_dead(node: str, err: BaseException) -> set:
    """Failure-watchdog hook: requeue the scheduler's in-flight jobs for
    a dead node.  Returns requeued job keys ({} when no scheduler)."""
    s = _active()
    if s is None:
        return set()
    return s.on_node_dead(node, err)


def readmit(block: bool = False) -> list:
    """Re-admit journaled work after a coordinator restart.

    Walks the recovery journal for resumable entries, enriches each with
    the WAL-persisted ``!sched/`` record (priority/budget/tenant survive
    the restart), and re-submits through the scheduler — restart
    re-admits rather than loses jobs.  Returns the re-admitted Jobs
    (``block=True`` joins them first)."""
    from . import recovery
    from .job import Job, scheduler
    s = scheduler()
    metas = {}
    for k in dkv.keys(SCHED_PREFIX):
        rec = dkv.get(k)
        if isinstance(rec, dict) and rec.get("state") in ("queued",
                                                          "running"):
            metas[rec.get("job")] = rec
    jobs = []
    for uri, entry in recovery.journal_entries():
        if entry.get("status") != "running":
            continue
        jobkey = entry.get("job") or ""
        meta = metas.get(jobkey, {})
        job = Job(f"readmit {entry.get('algo', '?')} train",
                  dest_key=entry.get("dest_key"))
        pr = meta.get("priority")
        s.submit(job, _resume_fn(uri),
                 priority=PRIORITY_BUILD if pr is None else pr,
                 device_budget=meta.get("device_budget"),
                 retry_budget=meta.get("retry_budget") or 0,
                 user=meta.get("user"))
        if jobkey and jobkey != job.key:
            try:
                dkv.remove(SCHED_PREFIX + jobkey)   # superseded record
            except Exception:        # noqa: BLE001
                pass
        record("sched_readmit", job=job.key, journal=uri)
        jobs.append(job)
    if block:
        for job in jobs:
            job.join()
    return jobs
