"""Runtime configuration — the H2O.OptArgs / system-property analog.

The reference layers CLI flags, system properties and env vars; here a
single typed env surface (``H2O3_TPU_*``) feeds a process-wide config
read at first use.  ``describe()`` backs the REST /3/About view so
operators can see effective settings.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional


@dataclasses.dataclass
class Config:
    # REST
    port: int = 54321
    # scheduler (runtime/scheduler.py): legacy fixed-pool width (kept for
    # direct JobScheduler construction), chip capacity override (0 = the
    # live mesh's row-shard count), bounded admission queue, default
    # device-budget fraction for jobs submitted without one, elastic
    # membership (host join/leave drives fenced mesh rebuilds), the
    # membership poll cadence, and the flap-quarantine policy (a host may
    # trigger at most sched_quarantine_flaps rebuilds per window)
    scheduler_workers: int = 2
    sched_capacity: int = 0
    sched_queue_limit: int = 64
    sched_default_budget: float = 0.5
    sched_elastic: bool = False
    sched_member_poll_s: float = 1.0
    sched_quarantine_window_s: float = 60.0
    sched_quarantine_flaps: int = 2
    # HBM guardrail share (cluster._check_hbm_budget)
    hbm_guardrail_fraction: float = 0.9
    # logging
    log_level: str = "INFO"
    # extension modules (comma-separated import paths)
    extensions: str = ""
    # internode TLS (PEM paths)
    tls_cert: Optional[str] = None
    tls_key: Optional[str] = None
    # DKV control-plane retry (dkv._rpc): extra attempts after the first,
    # exponential backoff base/cap, and a per-op total-seconds budget
    dkv_retries: int = 5
    dkv_backoff_base_s: float = 0.05
    dkv_backoff_max_s: float = 2.0
    dkv_retry_budget_s: float = 30.0
    # heartbeat stamps get a much shorter budget: one missed stamp beats
    # a 30 s-blocked beat thread (heartbeat._beat)
    hb_dkv_budget_s: float = 2.0
    # coordinator durability (dkv WAL + compacted snapshots): directory
    # (default <H2O3_TPU_RECOVERY_DIR>/dkv; local paths only) and how many
    # WAL records accumulate before a compacted snapshot replaces them
    dkv_wal_dir: Optional[str] = None
    dkv_wal_compact_every: int = 512
    # exactly-once RPC: how many request-ids the coordinator remembers
    dkv_dedup_window: int = 4096
    # coordinator handler hardening: declared-frame cap and the
    # per-connection recv timeout that frees half-open handler threads
    dkv_max_frame_mb: float = 256.0
    dkv_recv_timeout_s: float = 30.0
    # REST shutdown: bounded wait for in-flight request handlers
    rest_drain_timeout_s: float = 5.0
    # in-training progress snapshots (runtime/snapshot.py): min seconds
    # between writes per job (0 = every opportunity), async writer thread
    snapshot_interval_s: float = 30.0
    snapshot_async: bool = True
    # telemetry (runtime/observability.py): master switch for metric/span
    # instrumentation, per-node log file (%h/%p expand to hostname/pid),
    # and how many timeline events each heartbeat stamp ships (0 = none)
    metrics_enabled: bool = True
    log_file: Optional[str] = None
    hb_ship_events: int = 200
    # mesh data plane (runtime/cluster.py + runtime/mapreduce.py):
    # "hosts" axis size (0 = auto: jax.process_count(); single-host values
    # > 1 carve VIRTUAL hosts out of the local devices for CI/laptops) and
    # the cross-shard reduction strategy — "hier" psums within a host's
    # ICI ring then across DCN, "flat" is the one-collective oracle,
    # "check" runs both and raises on divergence, "auto" (default) lets
    # the autotuner pick per mesh geometry (hier with the tuner off)
    mesh_hosts: int = 0
    reduce_mode: str = "auto"
    # cost-model autotuner (runtime/autotune.py): master switch for the
    # per-signature kernel-strategy tuner — "on" (model-seeded decisions
    # + epsilon-greedy measured refinement), "cache_only" (cached + model
    # decisions, never explores), "off" ("auto" knobs resolve to the
    # historical fixed defaults: bit-identical kernels, what tier-1
    # pins); the cache directory override (default
    # <H2O3_TPU_RECOVERY_DIR>/autotune) and the exploration period (every
    # Nth resolve of a model-seeded signature re-measures the runner-up)
    autotune: str = "on"
    autotune_cache_dir: Optional[str] = None
    autotune_explore_every: int = 16
    # device/compiler observability (runtime/xprof.py): true device-phase
    # timing mode — "off" (host dispatch only), "sampled" (block-until-
    # ready every Nth eager dispatch; bounded overhead), "full" (every
    # dispatch) — and the sampled-mode stride
    device_timing: str = "off"
    device_timing_sample: int = 4
    # online scoring plane (serving/): micro-batch tick interval, device
    # batch capacity (one compiled signature — requests pad into it),
    # admission queue depth in ROWS (overflow is rejected, not queued),
    # parity mode ("packed" | "ref" | "check") and traversal impl
    # ("auto" | "xla" | "pallas" | "pallas_interpret")
    serve_tick_ms: float = 2.0
    serve_max_batch: int = 256
    serve_queue_depth: int = 4096
    serve_score_mode: str = "packed"
    serve_impl: str = "auto"
    # per-request serving deadline in ms (0 = none): a request that
    # cannot be dispatched to the device before its deadline is shed
    # with a 503 instead of waiting in the queue — also during SIGTERM
    # drain, so a terminating pod never strands queued requests
    serve_deadline_ms: float = 0.0
    # shard-lineage data plane (frame/lineage.py + runtime/remat.py):
    # master switch for provenance stamping at parse, the op-chain depth
    # past which a registered derived frame checkpoint-materializes, the
    # largest rows()-index recorded as a replayable op, the largest
    # source file stamped at all, the largest frame whose per-shard
    # value hashes are computed at publish (bigger frames keep only the
    # source-byte hashes), and the hot-frame replica threshold (0 = no
    # replicas): frames at or under it keep one DCN-neighbor replica
    # shard in the DKV so recovery is a copy, not a recompute
    lineage_enabled: bool = True
    lineage_max_chain: int = 8
    lineage_max_index: int = 1_000_000
    lineage_max_mb: float = 512.0
    lineage_hash_below_mb: float = 32.0
    replicate_below_mb: float = 0.0
    # streaming ingest plane (ingest/stream.py + the tree drivers'
    # stream= mode): rows that must land before the first training
    # segment starts (0 = one full planned range), the backpressure
    # bound on landed-but-unconsumed rows (0 = unbounded: training is
    # the only consumer and reads in place), the minimum watermark
    # growth — as a fraction of rows already trained on — before a
    # chunk fence cuts a new segment (bounds re-bin/recompile churn),
    # and the watermark poll cadence while training waits for data
    stream_min_rows: int = 0
    stream_buffer_rows: int = 0
    stream_grow_min_frac: float = 0.25
    stream_poll_s: float = 0.05
    # quantize segment row counts down to a multiple of this (0 = off):
    # repeated runs then hit the same padded shapes, so the per-segment
    # scan programs come back from the jit cache instead of recompiling
    stream_round_rows: int = 0

    @staticmethod
    def from_env() -> "Config":
        e = os.environ.get
        return Config(
            port=int(e("H2O3_TPU_PORT", 54321)),
            scheduler_workers=int(e("H2O3_TPU_SCHEDULER_WORKERS", 2)),
            sched_capacity=int(e("H2O3_TPU_SCHED_CAPACITY", 0)),
            sched_queue_limit=int(e("H2O3_TPU_SCHED_QUEUE", 64)),
            sched_default_budget=float(
                e("H2O3_TPU_SCHED_DEFAULT_BUDGET", 0.5)),
            sched_elastic=e("H2O3_TPU_SCHED_ELASTIC", "0")
            not in ("0", "false", "no"),
            sched_member_poll_s=float(e("H2O3_TPU_SCHED_MEMBER_POLL", 1.0)),
            sched_quarantine_window_s=float(
                e("H2O3_TPU_SCHED_QUARANTINE_WINDOW", 60.0)),
            sched_quarantine_flaps=int(
                e("H2O3_TPU_SCHED_QUARANTINE_FLAPS", 2)),
            hbm_guardrail_fraction=float(
                e("H2O3_TPU_HBM_GUARDRAIL", 0.9)),
            log_level=e("H2O3_TPU_LOG_LEVEL", "INFO"),
            extensions=e("H2O3_TPU_EXTENSIONS", ""),
            tls_cert=e("H2O3_TPU_TLS_CERT"),
            tls_key=e("H2O3_TPU_TLS_KEY"),
            dkv_retries=int(e("H2O3_TPU_DKV_RETRIES", 5)),
            dkv_backoff_base_s=float(e("H2O3_TPU_DKV_BACKOFF_BASE", 0.05)),
            dkv_backoff_max_s=float(e("H2O3_TPU_DKV_BACKOFF_MAX", 2.0)),
            dkv_retry_budget_s=float(e("H2O3_TPU_DKV_RETRY_BUDGET", 30.0)),
            hb_dkv_budget_s=float(e("H2O3_TPU_HB_BUDGET", 2.0)),
            dkv_wal_dir=e("H2O3_TPU_DKV_WAL_DIR") or None,
            dkv_wal_compact_every=int(e("H2O3_TPU_DKV_WAL_COMPACT", 512)),
            dkv_dedup_window=int(e("H2O3_TPU_DKV_DEDUP_WINDOW", 4096)),
            dkv_max_frame_mb=float(e("H2O3_TPU_DKV_MAX_FRAME_MB", 256.0)),
            dkv_recv_timeout_s=float(e("H2O3_TPU_DKV_RECV_TIMEOUT", 30.0)),
            rest_drain_timeout_s=float(
                e("H2O3_TPU_REST_DRAIN_TIMEOUT", 5.0)),
            snapshot_interval_s=float(e("H2O3_TPU_SNAPSHOT_INTERVAL", 30.0)),
            snapshot_async=e("H2O3_TPU_SNAPSHOT_ASYNC", "1")
            not in ("0", "false", "no"),
            metrics_enabled=e("H2O3_TPU_METRICS", "1")
            not in ("0", "false", "no"),
            log_file=e("H2O3_TPU_LOG_FILE") or None,
            hb_ship_events=int(e("H2O3_TPU_HB_SHIP_EVENTS", 200)),
            mesh_hosts=int(e("H2O3_TPU_HOSTS", 0)),
            reduce_mode=e("H2O3_TPU_REDUCE_MODE", "auto"),
            autotune=e("H2O3_TPU_AUTOTUNE", "on"),
            autotune_cache_dir=e("H2O3_TPU_AUTOTUNE_CACHE_DIR") or None,
            autotune_explore_every=int(
                e("H2O3_TPU_AUTOTUNE_EXPLORE", 16)),
            device_timing=e("H2O3_TPU_DEVICE_TIMING", "off"),
            device_timing_sample=int(
                e("H2O3_TPU_DEVICE_TIMING_SAMPLE", 4)),
            serve_tick_ms=float(e("H2O3_TPU_SERVE_TICK_MS", 2.0)),
            serve_max_batch=int(e("H2O3_TPU_SERVE_MAX_BATCH", 256)),
            serve_queue_depth=int(e("H2O3_TPU_SERVE_QUEUE", 4096)),
            serve_score_mode=e("H2O3_TPU_SERVE_SCORE_MODE", "packed"),
            serve_impl=e("H2O3_TPU_SERVE_IMPL", "auto"),
            serve_deadline_ms=float(e("H2O3_TPU_SERVE_DEADLINE_MS", 0.0)),
            lineage_enabled=e("H2O3_TPU_LINEAGE", "1")
            not in ("0", "false", "no"),
            lineage_max_chain=int(e("H2O3_TPU_LINEAGE_MAX_CHAIN", 8)),
            lineage_max_index=int(
                e("H2O3_TPU_LINEAGE_MAX_INDEX", 1_000_000)),
            lineage_max_mb=float(e("H2O3_TPU_LINEAGE_MAX_MB", 512.0)),
            lineage_hash_below_mb=float(
                e("H2O3_TPU_LINEAGE_HASH_BELOW_MB", 32.0)),
            replicate_below_mb=float(
                e("H2O3_TPU_REPLICATE_BELOW_MB", 0.0)),
            stream_min_rows=int(e("H2O3_TPU_STREAM_MIN_ROWS", 0)),
            stream_buffer_rows=int(e("H2O3_TPU_STREAM_BUFFER_ROWS", 0)),
            stream_grow_min_frac=float(
                e("H2O3_TPU_STREAM_GROW_MIN_FRAC", 0.25)),
            stream_poll_s=float(e("H2O3_TPU_STREAM_POLL", 0.05)),
            stream_round_rows=int(e("H2O3_TPU_STREAM_ROUND_ROWS", 0)),
        )

    def describe(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("tls_key"):
            d["tls_key"] = "<set>"
        return d


_config: Optional[Config] = None
_lock = threading.Lock()


def config() -> Config:
    global _config
    with _lock:
        if _config is None:
            _config = Config.from_env()
        return _config


def reload() -> Config:
    """Re-read the environment (tests / dynamic reconfiguration)."""
    global _config
    with _lock:
        _config = Config.from_env()
        cfg = _config
    from . import observability
    observability.apply_config(cfg)
    return cfg
