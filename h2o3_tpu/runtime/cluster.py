"""Cluster runtime: the TPU-native analog of H2O's "cloud".

The reference (h2o-core/src/main/java/water/H2O.java, water/Paxos.java:27,
water/HeartBeatThread.java:16) forms a cloud of JVMs via multicast heartbeats
and a mutual-knowledge consensus, then locks membership at the first job.

On TPU the topology is known at launch: a pod slice is gang-scheduled, so no
consensus protocol is needed (SURVEY.md §5 "Distributed communication
backend").  The Cluster here is a thin, explicit object: a
``jax.sharding.Mesh`` over the available devices plus named shardings used by
the data plane.  Multi-process operation uses ``jax.distributed.initialize``
(the analog of flatfile-based clouding); within a process everything is SPMD
over the mesh and all reductions are XLA collectives over ICI instead of the
reference's MRTask RPC tree (water/MRTask.java:739-760).

Axis names:
  * ``"rows"``  — the data axis; Frames are row-sharded over it (the analog of
    H2O chunk distribution, water/fvec/Vec.java:152 ESPC).
  * ``"model"`` — optional second axis for feature/model sharding (the TP
    analog for very wide Gram matrices, SURVEY.md §2.10).
"""

from __future__ import annotations

import dataclasses
import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "rows"
MODEL_AXIS = "model"

_lock = threading.Lock()
_cluster: "Cluster | None" = None


@dataclasses.dataclass
class Cluster:
    """A booted cluster: device mesh + canonical shardings.

    Analog of the reference's ``H2O.CLOUD`` (water/H2O.java) — but instead of
    a membership list plus a key-homing hash (water/Key.java:175-181), data
    placement is expressed as JAX shardings over the mesh.
    """

    mesh: Mesh

    # -- canonical shardings -------------------------------------------------
    @property
    def row_sharding(self) -> NamedSharding:
        """Sharding for 1-D row vectors (one Vec's payload)."""
        return NamedSharding(self.mesh, P(ROW_AXIS))

    @property
    def matrix_sharding(self) -> NamedSharding:
        """Sharding for [rows, features] matrices: rows split, features local."""
        return NamedSharding(self.mesh, P(ROW_AXIS, None))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- geometry ------------------------------------------------------------
    @property
    def n_row_shards(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def row_multiple(self) -> int:
        """Rows are padded to a multiple of this (shards x 8 sublanes)."""
        return self.n_row_shards * 8

    def pad_rows(self, n: int) -> int:
        m = self.row_multiple()
        return ((max(n, 1) + m - 1) // m) * m

    def describe(self) -> dict:
        """Cluster status — the `/3/Cloud` analog (water/api/CloudHandler)."""
        from . import dkv
        return {
            "devices": [str(d) for d in self.mesh.devices.flat],
            "platform": self.mesh.devices.flat[0].platform,
            "mesh_shape": dict(self.mesh.shape),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            # control-plane durability/fencing facts (epoch, WAL, role)
            "control_plane": dkv.wal_stats(),
        }


def init(devices=None, model_axis: int = 1, coordinator: str | None = None,
         num_processes: int | None = None, process_id: int | None = None) -> Cluster:
    """Boot (or return) the cluster — analog of ``h2o.init()``.

    Single-host: builds a mesh over the local devices.  Multi-host: pass
    ``coordinator`` (+ ``num_processes``/``process_id`` or rely on the TPU
    environment) to run ``jax.distributed.initialize`` first; the mesh then
    spans all hosts' devices and collectives ride ICI/DCN.
    """
    global _cluster
    with _lock:
        if _cluster is not None:
            if (devices is None and model_axis == _cluster.mesh.shape[MODEL_AXIS]
                    and coordinator is None):
                return _cluster
            if model_axis == 1 and devices is None and coordinator is None:
                return _cluster
            raise RuntimeError(
                "cluster already booted with a different configuration; "
                "call h2o3_tpu.shutdown() first to re-init")
        if coordinator is not None:
            # `jax.process_count()` would itself initialize the XLA
            # backend, after which jax.distributed.initialize refuses to
            # run — consult the distributed global state instead (callers
            # like the multiprocess tests may have initialized already).
            # num_processes=None stays valid: the TPU environment
            # auto-detects the slice topology.
            try:
                already = jax.distributed.is_initialized()
            except AttributeError:      # older jax: private-state probe
                from jax._src import distributed as _dist
                already = getattr(_dist.global_state, "client",
                                  None) is not None
            if num_processes != 1 and not already:
                jax.distributed.initialize(coordinator_address=coordinator,
                                           num_processes=num_processes,
                                           process_id=process_id)
            # control plane (SURVEY §5): coordinator hosts the DKV service
            # one port above the jax.distributed rendezvous; workers attach.
            from . import dkv
            host, _, port = coordinator.rpartition(":")
            dkv_port = int(port) + 1
            if jax.process_index() == 0:
                dkv.serve(host="0.0.0.0" if host not in
                          ("127.0.0.1", "localhost") else host,
                          port=dkv_port)
            else:
                dkv.attach(host, dkv_port)
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        n = len(devices)
        if model_axis < 1 or n % model_axis:
            raise ValueError(f"model_axis={model_axis} must divide device count {n}")
        dev_grid = np.array(devices).reshape(n // model_axis, model_axis)
        mesh = Mesh(dev_grid, (ROW_AXIS, MODEL_AXIS))
        _cluster = Cluster(mesh=mesh)
    from . import extensions, failure, heartbeat
    extensions.load_all()
    heartbeat.start()
    failure.start()                 # dead-member watchdog: detection ACTS
    return _cluster


def _guardrail_fraction() -> float:
    from .config import config
    return config().hbm_guardrail_fraction


def sample_memory_gauges() -> int:
    """Sample per-device allocator stats into telemetry gauges.

    Rides the same ``memory_stats()`` probe as ``_check_hbm_budget``;
    called from the heartbeat so every stamp ships fresh numbers.
    ``device_memory_bytes{device,kind}`` carries ``in_use``/``limit``
    plus an ``in_use_peak`` high-watermark (the WaterMeter analog).
    Returns how many devices reported stats (CPU backends report none).
    """
    from . import observability as obs
    sampled = 0
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats() or {}
        except Exception:               # noqa: BLE001 — backend-optional
            continue
        in_use = stats.get("bytes_in_use")
        if in_use is None:
            continue
        d = str(dev.id)
        obs.set_gauge("device_memory_bytes", in_use, device=d, kind="in_use")
        obs.gauge("device_memory_bytes", device=d,
                  kind="in_use_peak").set_max(in_use)
        limit = stats.get("bytes_limit")
        if limit:
            obs.set_gauge("device_memory_bytes", limit, device=d,
                          kind="limit")
        peak = stats.get("peak_bytes_in_use")
        if peak:
            obs.gauge("device_memory_bytes", device=d,
                      kind="in_use_peak").set_max(peak)
        sampled += 1
    return sampled


def _check_hbm_budget(nbytes: int, sharding=None, shape=None) -> None:
    """Fail fast with a clear message instead of an opaque XLA OOM.

    The reference spills cold chunks to disk (water/Cleaner.java:12); here
    frames must fit in HBM, so oversized placements get an actionable
    error naming the array and the per-device budget.
    """
    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if not limit:
            return
        if sharding is not None and shape is not None:
            try:
                per_dev = int(np.prod(sharding.shard_shape(tuple(shape)))
                              * max(nbytes // max(int(np.prod(shape)), 1), 1))
            except Exception:
                per_dev = nbytes / max(cluster().n_row_shards, 1)
        else:
            per_dev = nbytes / max(cluster().n_row_shards, 1)
        frac = _guardrail_fraction()
        if in_use + per_dev > frac * limit:
            # pressure: let the Cleaner evict cold frames to host RAM,
            # then re-read the allocator before giving up.  Single-process
            # only: the trigger is process-LOCAL memory_stats, and spilling
            # fetches via collectives — divergent triggers across hosts
            # would deadlock, so multi-host keeps the fail-fast behaviour.
            if jax.process_count() == 1:
                from . import cleaner
                n_shards = max(cluster().n_row_shards, 1)
                deficit = int((in_use + per_dev - frac * limit) * n_shards)
                try:
                    freed = cleaner.spill_until(deficit)
                except Exception:     # noqa: BLE001 — spill is best-effort
                    freed = 0
                if freed > 0:
                    in_use = (dev.memory_stats() or {}).get("bytes_in_use",
                                                            in_use)
        if in_use + per_dev > frac * limit:
            raise MemoryError(
                f"placing {nbytes / 1e9:.2f} GB ({per_dev / 1e9:.2f} GB/"
                f"device) would exceed {frac:.0%} of HBM "
                f"({limit / 1e9:.2f} GB/device, {in_use / 1e9:.2f} GB in "
                f"use). Reduce rows/columns, drop unused frames "
                f"(h2o3_tpu.remove), or add devices to the mesh.")
    except MemoryError:
        raise
    except Exception:
        return                            # stats unavailable: no guardrail


def put_sharded(buf: "np.ndarray", sharding) -> "jax.Array":
    """Place a host buffer onto the mesh under ``sharding``.

    Single-process: plain ``device_put``.  Multi-process SPMD: every process
    holds the same full buffer, so build the global array from per-shard
    callbacks — ``device_put``'s cross-process equality check rejects NaN
    padding (NaN != NaN) and non-addressable shards.
    """
    if hasattr(buf, "nbytes") and not isinstance(buf, jax.Array):
        # already-placed jax.Arrays are counted in bytes_in_use; only
        # fresh host->device placements consume new HBM
        _check_hbm_budget(int(buf.nbytes), sharding,
                          getattr(buf, "shape", None))
    if jax.process_count() == 1:
        return jax.device_put(buf, sharding)
    if isinstance(buf, jax.Array) and not isinstance(buf, np.ndarray):
        # already a (possibly global) device array: reshard collectively
        if buf.sharding == sharding:
            return buf
        return jax.jit(lambda x: x, out_shardings=sharding)(buf)
    buf = np.asarray(buf)
    return jax.make_array_from_callback(buf.shape, sharding,
                                        lambda idx: buf[idx])


def fetch(x) -> np.ndarray:
    """Host numpy copy of a (possibly multi-process global) array.

    Row-sharded arrays span non-addressable devices under multi-process
    SPMD; ``process_allgather`` rides the collective plane to reassemble
    them on every host.
    """
    if not hasattr(x, "sharding") or jax.process_count() == 1 \
            or x.is_fully_addressable or x.sharding.is_fully_replicated:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def cluster() -> Cluster:
    """The booted cluster, booting a default one on first use."""
    if _cluster is None:
        return init()
    return _cluster


def shutdown() -> None:
    global _cluster
    with _lock:
        from . import dkv, failure, heartbeat
        failure.stop()
        heartbeat.stop()
        dkv.detach()        # stop the DKV service / forget the coordinator
        _cluster = None
