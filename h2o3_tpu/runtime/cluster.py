"""Cluster runtime: the TPU-native analog of H2O's "cloud".

The reference (h2o-core/src/main/java/water/H2O.java, water/Paxos.java:27,
water/HeartBeatThread.java:16) forms a cloud of JVMs via multicast heartbeats
and a mutual-knowledge consensus, then locks membership at the first job.

On TPU the topology is known at launch: a pod slice is gang-scheduled, so no
consensus protocol is needed (SURVEY.md §5 "Distributed communication
backend").  The Cluster here is a thin, explicit object: a
``jax.sharding.Mesh`` over the available devices plus named shardings used by
the data plane.  Multi-process operation uses ``jax.distributed.initialize``
(the analog of flatfile-based clouding); within a process everything is SPMD
over the mesh and all reductions are XLA collectives over ICI instead of the
reference's MRTask RPC tree (water/MRTask.java:739-760).

Axis names — the mesh is an explicit ``("hosts", "chips", "model")``
hierarchy so collectives can be staged over the physical topology:
  * ``"hosts"`` — the DCN axis: one slot per host (real hosts under
    multi-process SPMD; VIRTUAL hosts carved out of the local devices via
    ``H2O3_TPU_HOSTS`` / ``init(hosts=...)`` for CI and laptops).
  * ``"chips"`` — the ICI axis: a host's chips, where psums ride the ring.
  * ``"model"`` — optional axis for feature/model sharding (the TP analog
    for very wide Gram matrices, SURVEY.md §2.10).
  * ``ROW_AXIS`` — the data "rows" axis every Frame is sharded over — is
    now the FLATTENED PRODUCT ``("hosts", "chips")``: PartitionSpecs,
    shard_map specs and ``psum`` all accept the tuple, so existing call
    sites keep working unchanged while ``runtime/mapreduce.py`` can stage
    the reduce per physical axis (ICI first, then DCN).
"""

from __future__ import annotations

import dataclasses
import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HOST_AXIS = "hosts"
CHIP_AXIS = "chips"
MODEL_AXIS = "model"
# the flattened data axis: hosts-major product, one name for call sites
ROW_AXES = (HOST_AXIS, CHIP_AXIS)
ROW_AXIS = ROW_AXES

_lock = threading.Lock()
_cluster: "Cluster | None" = None


@dataclasses.dataclass
class Cluster:
    """A booted cluster: device mesh + canonical shardings.

    Analog of the reference's ``H2O.CLOUD`` (water/H2O.java) — but instead of
    a membership list plus a key-homing hash (water/Key.java:175-181), data
    placement is expressed as JAX shardings over the mesh.
    """

    mesh: Mesh

    # -- canonical shardings -------------------------------------------------
    @property
    def row_sharding(self) -> NamedSharding:
        """Sharding for 1-D row vectors (one Vec's payload)."""
        return NamedSharding(self.mesh, P(ROW_AXIS))

    @property
    def matrix_sharding(self) -> NamedSharding:
        """Sharding for [rows, features] matrices: rows split, features local."""
        return NamedSharding(self.mesh, P(ROW_AXIS, None))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- geometry ------------------------------------------------------------
    @property
    def n_row_shards(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in ROW_AXES]))

    @property
    def n_hosts(self) -> int:
        return self.mesh.shape[HOST_AXIS]

    @property
    def n_chips_per_host(self) -> int:
        return self.mesh.shape[CHIP_AXIS]

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def row_multiple(self) -> int:
        """Rows are padded to a multiple of this (shards x 8 sublanes)."""
        return self.n_row_shards * 8

    def pad_rows(self, n: int) -> int:
        m = self.row_multiple()
        return ((max(n, 1) + m - 1) // m) * m

    def describe(self) -> dict:
        """Cluster status — the `/3/Cloud` analog (water/api/CloudHandler)."""
        from . import dkv
        return {
            "devices": [str(d) for d in self.mesh.devices.flat],
            "platform": self.mesh.devices.flat[0].platform,
            "mesh_shape": dict(self.mesh.shape),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            # control-plane durability/fencing facts (epoch, WAL, role)
            "control_plane": dkv.wal_stats(),
        }


def _resolve_hosts(hosts: int | None, n_row: int) -> int:
    """Host-axis size: explicit param > H2O3_TPU_HOSTS > process count > 1.

    Auto-resolved sizes that don't divide the row-shard count degrade to a
    single (flat) host with a telemetry event; an explicit ``hosts=``
    argument that doesn't divide is a caller error.
    """
    explicit = hosts is not None
    if hosts is None:
        from .config import config
        hosts = config().mesh_hosts or jax.process_count() or 1
    if hosts < 1:
        hosts = 1
    if n_row % hosts:
        if explicit:
            raise ValueError(
                f"hosts={hosts} must divide the row-shard count {n_row}")
        from .observability import log, record
        log.warning("mesh: hosts=%d does not divide %d row shards; "
                    "falling back to a single flat host", hosts, n_row)
        record("mesh_hosts_fallback", requested=hosts, n_row_shards=n_row)
        hosts = 1
    return hosts


def _build_mesh(devices: list, hosts: int, model_axis: int) -> Mesh:
    """(hosts, chips, model) grid over ``devices``.

    Real multi-host topologies go through ``create_hybrid_device_mesh`` so
    the chips axis maps onto each host's ICI ring and the hosts axis onto
    DCN.  CPU/virtual devices lack the ``slice_index``/coords attributes it
    needs, so single-host (and any failure) falls back to a process-sorted
    reshape — hosts-major, which still keeps each virtual host's chips
    contiguous.
    """
    n = len(devices)
    chips = n // model_axis // hosts
    if jax.process_count() > 1 and hosts == jax.process_count():
        try:
            from jax.experimental import mesh_utils
            grid = mesh_utils.create_hybrid_device_mesh(
                (1, chips * model_axis), (hosts, 1), devices=devices)
            grid = np.asarray(grid).reshape(hosts, chips, model_axis)
            return Mesh(grid, (HOST_AXIS, CHIP_AXIS, MODEL_AXIS))
        except Exception as e:            # noqa: BLE001 — CPU/virtual mesh
            from .observability import log
            log.warning("mesh: create_hybrid_device_mesh unavailable (%r); "
                        "using process-sorted reshape", e)
    devs = sorted(devices, key=lambda d: (d.process_index, d.id))
    grid = np.array(devs).reshape(hosts, chips, model_axis)
    return Mesh(grid, (HOST_AXIS, CHIP_AXIS, MODEL_AXIS))


def _invalidate_compiled_caches() -> None:
    """Drop compiled programs that closed over a previous mesh.

    The cached tree builders bind the live mesh at trace time via
    ``shard_map``; after a rebuild those executables reference dead
    devices.  Clearing the builder LRUs plus jax's global jit cache forces
    a retrace against the new mesh.  The xprof compile ledger is marked
    first, so every recompile this flush causes is attributed to
    ``recompiles_total{reason="cluster_reinit"}``.
    """
    from . import xprof
    xprof.invalidate("cluster_reinit")
    # the autotuner's per-signature mode decisions bind the mesh geometry
    # the same way the compiled programs do: drop them with the caches,
    # or a rebuilt mesh could be served a choice tuned for the dead one
    from . import autotune
    autotune.invalidate("cluster_reinit")
    for mod_name, names in (
        ("..models.tree.hist", ("make_hist_fn", "make_fine_hist_fn",
                                "make_varbin_hist_fn",
                                "make_subtract_level_fn",
                                "make_batched_level_fn",
                                "make_sparse_level_fn",
                                "make_batched_sparse_level_fn")),
        ("..models.tree.shared", ("make_build_tree_fn", "make_tree_scan_fn",
                                  "make_multinomial_scan_fn")),
    ):
        try:
            import importlib
            mod = importlib.import_module(mod_name, package=__package__)
        except Exception:   # noqa: BLE001 — model layer optional at boot
            continue
        for name in names:
            clear = getattr(getattr(mod, name, None), "cache_clear", None)
            if clear is not None:
                try:
                    clear()
                except Exception:         # noqa: BLE001
                    pass
    try:
        jax.clear_caches()
    except Exception:                     # noqa: BLE001
        pass


def publish_mesh_gauges(cl: "Cluster | None" = None) -> None:
    """(Re-)emit the ``mesh_shape`` gauge, one series per mesh axis.

    Separate helper (rather than inline in ``init``) so tests that reset
    the metric registry can re-emit without re-booting the cluster.
    """
    from . import observability as obs
    cl = cl if cl is not None else _cluster
    if cl is None:
        return
    for axis, size in cl.mesh.shape.items():
        obs.set_gauge("mesh_shape", size, axis=axis)
    obs.set_gauge("mesh_shape", cl.n_devices, axis="total")


def init(devices=None, model_axis: int | None = None,
         coordinator: str | None = None,
         num_processes: int | None = None, process_id: int | None = None,
         hosts: int | None = None) -> Cluster:
    """Boot (or return) the cluster — analog of ``h2o.init()``.

    Single-host: builds a mesh over the local devices.  Multi-host: pass
    ``coordinator`` (+ ``num_processes``/``process_id`` or rely on the TPU
    environment) to run ``jax.distributed.initialize`` first; the mesh then
    spans all hosts' devices and collectives ride ICI/DCN.

    ``hosts`` sizes the DCN axis of the mesh (default: ``H2O3_TPU_HOSTS``,
    else the process count).  Re-calling with a geometry that differs from
    the booted mesh REBUILDS it (with a ``cluster_reinit`` warning event and
    a compiled-cache flush) instead of silently returning the stale mesh.
    """
    global _cluster
    with _lock:
        if _cluster is not None:
            if coordinator is not None:
                raise RuntimeError(
                    "cluster already booted; the distributed control plane "
                    "cannot be re-initialized in-process — call "
                    "h2o3_tpu.shutdown() first")
            cur = _cluster.mesh
            if devices is None and hosts is None and model_axis is None:
                return _cluster           # default call: hand back the boot
            req_devices = list(devices) if devices is not None \
                else list(cur.devices.flat)
            # unspecified axes keep their live size: a partial re-init
            # (say init(hosts=4)) must not implicitly reset the others
            req_model = model_axis if model_axis is not None \
                else cur.shape[MODEL_AXIS]
            n = len(req_devices)
            if req_model < 1 or n % req_model:
                raise ValueError(
                    f"model_axis={req_model} must divide device count {n}")
            req_hosts = _resolve_hosts(hosts, n // req_model)
            if (req_devices == list(cur.devices.flat)
                    and req_model == cur.shape[MODEL_AXIS]
                    and req_hosts == cur.shape[HOST_AXIS]):
                return _cluster           # same geometry re-stated
            # geometry changed: the old behaviour either silently returned
            # the cached mesh or refused — rebuild instead, loudly
            from .observability import log, record
            log.warning("cluster re-init: mesh %s -> devices=%d hosts=%d "
                        "model_axis=%d; rebuilding and flushing compiled "
                        "caches", dict(cur.shape), n, req_hosts, req_model)
            record("cluster_reinit", old_shape=dict(cur.shape),
                   new_devices=n, new_hosts=req_hosts,
                   new_model_axis=req_model)
            _invalidate_compiled_caches()
            _cluster = None
            devices, hosts, model_axis = req_devices, req_hosts, req_model
        if coordinator is not None:
            # `jax.process_count()` would itself initialize the XLA
            # backend, after which jax.distributed.initialize refuses to
            # run — consult the distributed global state instead (callers
            # like the multiprocess tests may have initialized already).
            # num_processes=None stays valid: the TPU environment
            # auto-detects the slice topology.
            try:
                already = jax.distributed.is_initialized()
            except AttributeError:      # older jax: private-state probe
                from jax._src import distributed as _dist
                already = getattr(_dist.global_state, "client",
                                  None) is not None
            if num_processes != 1 and not already:
                jax.distributed.initialize(coordinator_address=coordinator,
                                           num_processes=num_processes,
                                           process_id=process_id)
            # control plane (SURVEY §5): coordinator hosts the DKV service
            # one port above the jax.distributed rendezvous; workers attach.
            from . import dkv
            host, _, port = coordinator.rpartition(":")
            dkv_port = int(port) + 1
            if jax.process_index() == 0:
                dkv.serve(host="0.0.0.0" if host not in
                          ("127.0.0.1", "localhost") else host,
                          port=dkv_port)
            else:
                dkv.attach(host, dkv_port)
        if devices is None:
            devices = jax.devices()
        if model_axis is None:
            model_axis = 1
        devices = list(devices)
        n = len(devices)
        if model_axis < 1 or n % model_axis:
            raise ValueError(f"model_axis={model_axis} must divide device count {n}")
        n_hosts = _resolve_hosts(hosts, n // model_axis)
        mesh = _build_mesh(devices, n_hosts, model_axis)
        _cluster = Cluster(mesh=mesh)
    from . import extensions, failure, heartbeat, xprof
    extensions.load_all()
    heartbeat.start()
    failure.start()                 # dead-member watchdog: detection ACTS
    publish_mesh_gauges(_cluster)
    xprof.install_monitoring_listener()   # /jax/core/compile backstop
    return _cluster


def _guardrail_fraction() -> float:
    from .config import config
    return config().hbm_guardrail_fraction


def sample_memory_gauges() -> int:
    """Sample per-device allocator stats into telemetry gauges.

    Rides the same ``memory_stats()`` probe as ``_check_hbm_budget``;
    called from the heartbeat so every stamp ships fresh numbers.
    ``device_memory_bytes{device,kind}`` carries ``in_use``/``limit``
    plus an ``in_use_peak`` high-watermark (the WaterMeter analog).
    Returns how many devices reported stats (CPU backends report none).
    """
    from . import observability as obs
    sampled = 0
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats() or {}
        except Exception:               # noqa: BLE001 — backend-optional
            continue
        in_use = stats.get("bytes_in_use")
        if in_use is None:
            continue
        d = str(dev.id)
        obs.set_gauge("device_memory_bytes", in_use, device=d, kind="in_use")
        obs.gauge("device_memory_bytes", device=d,
                  kind="in_use_peak").set_max(in_use)
        limit = stats.get("bytes_limit")
        if limit:
            obs.set_gauge("device_memory_bytes", limit, device=d,
                          kind="limit")
        peak = stats.get("peak_bytes_in_use")
        if peak:
            obs.gauge("device_memory_bytes", device=d,
                      kind="in_use_peak").set_max(peak)
        sampled += 1
    return sampled


def _check_hbm_budget(nbytes: int, sharding=None, shape=None) -> None:
    """Fail fast with a clear message instead of an opaque XLA OOM.

    The reference spills cold chunks to disk (water/Cleaner.java:12); here
    frames must fit in HBM, so oversized placements get an actionable
    error naming the array and the per-device budget.
    """
    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return
        stats = dev.memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if not limit:
            return
        if sharding is not None and shape is not None:
            try:
                per_dev = int(np.prod(sharding.shard_shape(tuple(shape)))
                              * max(nbytes // max(int(np.prod(shape)), 1), 1))
            except Exception:
                per_dev = nbytes / max(cluster().n_row_shards, 1)
        else:
            per_dev = nbytes / max(cluster().n_row_shards, 1)
        frac = _guardrail_fraction()
        if in_use + per_dev > frac * limit:
            # pressure: let the Cleaner evict cold frames to host RAM,
            # then re-read the allocator before giving up.  Single-process
            # only: the trigger is process-LOCAL memory_stats, and spilling
            # fetches via collectives — divergent triggers across hosts
            # would deadlock, so multi-host keeps the fail-fast behaviour.
            if jax.process_count() == 1:
                from . import cleaner
                n_shards = max(cluster().n_row_shards, 1)
                deficit = int((in_use + per_dev - frac * limit) * n_shards)
                try:
                    freed = cleaner.spill_until(deficit)
                except Exception:     # noqa: BLE001 — spill is best-effort
                    freed = 0
                if freed > 0:
                    in_use = (dev.memory_stats() or {}).get("bytes_in_use",
                                                            in_use)
        if in_use + per_dev > frac * limit:
            raise MemoryError(
                f"placing {nbytes / 1e9:.2f} GB ({per_dev / 1e9:.2f} GB/"
                f"device) would exceed {frac:.0%} of HBM "
                f"({limit / 1e9:.2f} GB/device, {in_use / 1e9:.2f} GB in "
                f"use). Reduce rows/columns, drop unused frames "
                f"(h2o3_tpu.remove), or add devices to the mesh.")
    except MemoryError:
        raise
    except Exception:
        return                            # stats unavailable: no guardrail


def put_sharded(buf: "np.ndarray", sharding) -> "jax.Array":
    """Place a host buffer onto the mesh under ``sharding``.

    Single-process: plain ``device_put``.  Multi-process SPMD: every process
    holds the same full buffer, so build the global array from per-shard
    callbacks — ``device_put``'s cross-process equality check rejects NaN
    padding (NaN != NaN) and non-addressable shards.
    """
    if hasattr(buf, "nbytes") and not isinstance(buf, jax.Array):
        # already-placed jax.Arrays are counted in bytes_in_use; only
        # fresh host->device placements consume new HBM
        _check_hbm_budget(int(buf.nbytes), sharding,
                          getattr(buf, "shape", None))
    if jax.process_count() == 1:
        return jax.device_put(buf, sharding)
    if isinstance(buf, jax.Array) and not isinstance(buf, np.ndarray):
        # already a (possibly global) device array: reshard collectively
        if buf.sharding == sharding:
            return buf
        return jax.jit(lambda x: x, out_shardings=sharding)(buf)
    buf = np.asarray(buf)
    return jax.make_array_from_callback(buf.shape, sharding,
                                        lambda idx: buf[idx])


def fetch(x) -> np.ndarray:
    """Host numpy copy of a (possibly multi-process global) array.

    Row-sharded arrays span non-addressable devices under multi-process
    SPMD; ``process_allgather`` rides the collective plane to reassemble
    them on every host.
    """
    if not hasattr(x, "sharding") or jax.process_count() == 1 \
            or x.is_fully_addressable or x.sharding.is_fully_replicated:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def cluster() -> Cluster:
    """The booted cluster, booting a default one on first use."""
    if _cluster is None:
        return init()
    return _cluster


def shutdown() -> None:
    global _cluster
    with _lock:
        from . import dkv, failure, heartbeat
        failure.stop()
        heartbeat.stop()
        dkv.detach()        # stop the DKV service / forget the coordinator
        _cluster = None
