"""jax version-compat shims, shared by every shard_map call site.

One shim, three former copies (models/tree/hist.py, runtime/mapreduce.py,
runtime/observability.network_test): jax >= 0.5 exposes ``jax.shard_map``
with the replication checker spelled ``check_vma``; earlier versions ship
``jax.experimental.shard_map.shard_map`` with the same knob spelled
``check_rep``.  Callers here always use the modern ``check_vma`` spelling.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:                       # jax<0.5: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *args, check_vma=None, **kw):
    """``jax.shard_map`` under either spelling of the replication checker."""
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    return _shard_map(f, *args, **kw)
