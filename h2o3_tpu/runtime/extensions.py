"""Extensions SPI — the water/AbstractH2OExtension / RestApiExtension
registration analog.

The reference discovers extensions via ServiceLoader manifests; here an
extension is any importable module (or ``module:function``) listed in
``H2O3_TPU_EXTENSIONS`` (comma-separated) or registered explicitly.  At
cluster init every extension's entry point runs with the runtime module
as its argument — extensions register persist backends
(``persist.register``), REST routes, new estimators, etc.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, List, Optional

# name -> pending init fn (callable), "loaded", or "failed"
_registry: Dict[str, object] = {}
_lock = threading.Lock()


def register(name: str, init_fn: Callable) -> None:
    """Programmatic registration (tests, embedded extensions)."""
    with _lock:
        _registry[name] = init_fn


def load_all() -> List[str]:
    """Import + initialize every configured extension not yet run;
    returns the names initialized by THIS call.

    Called from ``h2o3_tpu.init()``; failures log and skip (a broken
    extension must not take the cluster down), mirroring the reference's
    best-effort extension boot.
    """
    from .config import config
    from .observability import log, record
    import h2o3_tpu
    specs = [s.strip() for s in config().extensions.split(",") if s.strip()]
    with _lock:
        pending = {k: v for k, v in _registry.items() if callable(v)}
        known = set(_registry)
    for spec in specs:
        if spec in known or spec in pending:
            continue
        try:
            mod_name, _, fn_name = spec.partition(":")
            mod = importlib.import_module(mod_name)
            fn = getattr(mod, fn_name) if fn_name else \
                getattr(mod, "init", None)
            if not callable(fn):
                raise AttributeError(
                    f"{spec!r} has no callable entry point "
                    f"({fn_name or 'init'})")
            pending[spec] = fn
        except Exception as e:                 # noqa: BLE001
            log.warning("extension %s failed to import: %r", spec, e)
            with _lock:
                _registry[spec] = "failed"
    initialized = []
    for name, fn in pending.items():
        try:
            fn(h2o3_tpu)
            initialized.append(name)
            record("extension_loaded", name=name)
            status: object = "loaded"
        except Exception as e:                 # noqa: BLE001
            log.warning("extension %s failed to initialize: %r", name, e)
            status = "failed"
        with _lock:
            _registry[name] = status
    return initialized


def loaded() -> List[str]:
    """Names of successfully initialized extensions (REST /3/About)."""
    with _lock:
        return sorted(k for k, v in _registry.items() if v == "loaded")


def status(name: str) -> Optional[str]:
    with _lock:
        v = _registry.get(name)
        return v if isinstance(v, str) else ("pending" if v else None)
