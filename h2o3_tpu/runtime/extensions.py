"""Extensions SPI — the water/AbstractH2OExtension / RestApiExtension
registration analog.

The reference discovers extensions via ServiceLoader manifests; here an
extension is any importable module (or ``module:function``) listed in
``H2O3_TPU_EXTENSIONS`` (comma-separated) or registered explicitly.  At
cluster init every extension's entry point runs with the runtime module
as its argument — extensions register persist backends
(``persist.register``), REST routes, new estimators, etc.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, List

_loaded: Dict[str, object] = {}
_lock = threading.Lock()


def register(name: str, init_fn: Callable) -> None:
    """Programmatic registration (tests, embedded extensions)."""
    with _lock:
        _loaded[name] = init_fn


def load_all() -> List[str]:
    """Import + initialize every configured extension; returns names.

    Called from ``h2o3_tpu.init()``; failures log and skip (a broken
    extension must not take the cluster down), mirroring the reference's
    best-effort extension boot.
    """
    from .config import config
    from .observability import log, record
    import h2o3_tpu
    specs = [s.strip() for s in config().extensions.split(",") if s.strip()]
    with _lock:
        pending = dict(_loaded)
    for spec in specs:
        if spec in pending or spec in _loaded and _loaded[spec] is None:
            continue
        try:
            mod_name, _, fn_name = spec.partition(":")
            mod = importlib.import_module(mod_name)
            pending[spec] = getattr(mod, fn_name) if fn_name else \
                getattr(mod, "init", None)
        except Exception as e:                 # noqa: BLE001
            log.warning("extension %s failed to import: %r", spec, e)
    initialized = []
    for name, fn in pending.items():
        try:
            if callable(fn):
                fn(h2o3_tpu)
            initialized.append(name)
            record("extension_loaded", name=name)
        except Exception as e:                 # noqa: BLE001
            log.warning("extension %s failed to initialize: %r", name, e)
    with _lock:
        for name in initialized:
            _loaded[name] = None               # mark done
    return initialized


def loaded() -> List[str]:
    with _lock:
        return sorted(_loaded)
