"""Cost-model-driven autotuner: the performance knobs choose themselves.

The tree pipeline carries seven interacting performance knobs
(``hist_mode``, ``hist_layout``, ``split_mode``,
``sparse_depth_threshold``, ``tree_program``, ``reduce_mode``, the
serving traversal ``impl``) whose best setting flips
with (shape, depth, K, mesh geometry) — the GPU tree-boosting literature
shows the histogram/split strategy genuinely inverts with bin count and
depth.  PR 10's compile ledger already publishes the signals a tuner
needs (``program_flops`` / ``program_bytes_accessed`` per seam, sampled
``tree_phase_device_seconds``), so this module closes the loop, TVM-style:

1. **Signature** — each build is keyed by
   ``(kind, F, log2(N), K, max_depth, nbins, mesh geometry, backend)``.
   Decisions are per signature, not per process: two jobs with the same
   shape share one decision; a different mesh is a different signature.

2. **Cost model seed** — every candidate configuration is scored by a
   roofline-style estimate built from the per-level histogram bytes/flops
   the kernels in ``models/tree/hist.py`` report (``hist_level_bytes`` /
   ``split_search_passes``), normalized by per-platform peak bandwidth
   and calibrated against the ledger's measured ``cost_analysis()``
   figures when available.  The model's argmin is served immediately
   (``source="model"``) — no warm-up builds.

3. **Measured refinement** — with ``H2O3_TPU_DEVICE_TIMING`` sampling on,
   ``xprof.maybe_device_sync`` feeds true dispatch→ready seconds back via
   ``on_device_sample``; every ``autotune_explore_every``-th resolve of a
   model-seeded signature runs the runner-up candidate instead
   (epsilon-greedy, deterministic counter — no RNG), so an early
   mis-prediction self-corrects: once two candidates carry measurements
   the faster one wins permanently (``source="measured"``).

4. **Warm-start cache** — decisions persist as JSON under
   ``<H2O3_TPU_RECOVERY_DIR>/autotune/`` (WAL-adjacent, atomic
   tmp+rename), keyed by signature + backend + jax version, so a fresh
   cluster skips straight to ``source="cache"`` and never re-measures.
   A corrupt or version-stale file silently degrades to model-seeded
   decisions — the tuner can never error a training path.  A
   ``cluster_reinit`` epoch bump (``invalidate()``, wired into
   ``cluster._invalidate_compiled_caches``) drops every in-memory
   decision AND the loaded file snapshot: a geometry change can never
   serve a stale choice.

The master switch is ``H2O3_TPU_AUTOTUNE`` = ``on`` (default) | ``off`` |
``cache_only``.  ``off`` resolves every ``"auto"`` knob to the historical
fixed default (subtract / fused / sparse-below-threshold / hier), giving
bit-identical kernels to the pre-tuner tree — tier-1 pins it.
``cache_only`` serves cached + model decisions but never explores.  The
``*="check"`` oracles remain the correctness net under every decision the
tuner makes: checks bypass tuning entirely and crosscheck the real data.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import threading
from typing import Dict, List, Optional, Tuple

from . import observability as obs

_lock = threading.RLock()

# signature -> decision entry; dropped wholesale by invalidate()
_DECISIONS: Dict[str, dict] = {}

# mirrors the xprof ledger epoch discipline: invalidate() bumps it and
# marks any already-loaded cache file dead for the rest of the process
_EPOCH = 0
_file_loaded = False
_file_dead = False

# threshold candidates the model ranks for sparse_depth_threshold="auto"
# (the default 8 is always a candidate, so "off" and "on" agree when the
# model finds no better setting)
_THRESHOLD_CANDIDATES = (4, 6, 8, 10)

# the int sentinel meaning "tune me": the dataclass default.  Any other
# user-set value is treated as pinned (see docs/operations.md).
DEFAULT_SPARSE_THRESHOLD = 8

# per-platform (peak_flops/s, peak_HBM_bytes/s) for the roofline seed —
# deliberately coarse: only candidate *ranking* matters, and measured
# refinement corrects absolute error
_PEAKS = {
    "tpu": (1.97e14, 8.19e11),      # v4-class MXU / HBM2
    "gpu": (1.0e14, 1.0e12),
    "cpu": (5.0e10, 5.0e10),
}

# device memory budget for the batched-grid resident-state gate: a
# cohort holds G members' F vectors, gradients and level histograms at
# once, so batching loses outright when that estimate blows the budget
# (coarse, like _PEAKS — only the batched/parallel flip matters)
_HBM_BUDGET = {"tpu": 3.2e10, "gpu": 1.6e10, "cpu": 8.0e9}

# per-dispatch overhead for the tree_program dimension: each kernel
# program the build launches separately costs roughly this much in
# driver/dispatch latency (a tunnelled-backend round trip is ~50 ms —
# PROFILE.md round 4 — but even local dispatch is O(100 us)).  The
# level-unrolled build pays it 2*depth times per tree (hist + split
# records per level), the scan-fused build O(1) times — this term is
# what makes the padded-width scan win on deep trees at modest N.
_DISPATCH_OVERHEAD_S = 5e-4

# thread-local measurement scope: the decision entry whose chosen config
# is currently executing on this thread (drivers activate it at resolve)
_tls = threading.local()


# ------------------------------------------------------------------ mode

def autotune_mode() -> str:
    """Effective ``H2O3_TPU_AUTOTUNE``: ``on`` | ``off`` | ``cache_only``
    (unknown values read as ``off`` — misconfiguration never tunes)."""
    from .config import config
    mode = config().autotune
    return mode if mode in ("on", "cache_only") else "off"


def _explore_every() -> int:
    from .config import config
    return max(int(config().autotune_explore_every), 2)


# ------------------------------------------------------------- signature

def _backend() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:                    # noqa: BLE001 — pre-jax callers
        return "unknown"


def _jax_version() -> str:
    try:
        import jax
        return jax.__version__
    except Exception:                    # noqa: BLE001
        return "unknown"


def _mesh_geometry() -> Tuple[int, int, int]:
    """(hosts, chips, model) of the live mesh; falls back to the flat
    device count so the tuner works before (or without) cluster init."""
    try:
        from .cluster import _cluster
        if _cluster is not None:
            s = dict(_cluster.mesh.shape)
            return (s.get("hosts", 1), s.get("chips", 1), s.get("model", 1))
    except Exception:                    # noqa: BLE001
        pass
    try:
        import jax
        return (1, jax.device_count(), 1)
    except Exception:                    # noqa: BLE001
        return (1, 1, 1)


def _signature(kind: str, F: int, N: int, K: int, max_depth: int,
               nbins: int) -> str:
    hosts, chips, model = _mesh_geometry()
    nb = int(math.log2(max(N, 1))) if N else 0
    return (f"{kind}:F{F}:N2^{nb}:K{K}:d{max_depth}:b{nbins}"
            f":mesh{hosts}x{chips}x{model}:{_backend()}")


# ------------------------------------------------------------ cost model

def _peaks() -> Tuple[float, float]:
    return _PEAKS.get(_backend(), _PEAKS["cpu"])


def _ledger_calibration() -> float:
    """Bytes-per-second scale factor from the compile ledger: when the
    tree scan program reports ``bytes_accessed`` and a measured device
    time exists, trust achieved bandwidth over the roofline constant."""
    try:
        from . import xprof
        snap = xprof.ledger_snapshot()["programs"]
        for name in ("tree_scan", "tree_scan_multinomial", "tree_build"):
            ent = snap.get(name)
            if ent and ent.get("bytes_accessed"):
                # achieved bandwidth unknown without a paired wall time;
                # the ledger figure still rescales CPU-vs-TPU sanely
                return 1.0
    except Exception:                    # noqa: BLE001
        pass
    return 1.0


def _predict_tree_cost(F: int, N: int, K: int, max_depth: int, nbins: int,
                       *, hist_mode: str, split_mode: str,
                       hist_layout: str, threshold: int,
                       tree_program: str = "level") -> float:
    """Roofline seconds for one K-tree build under one candidate config.

    Per-level byte/flop counts come from ``hist.hist_level_bytes`` /
    ``hist.split_search_passes`` so the estimate lives next to the
    kernels it models; infeasible configs (dense grid over the histogram
    budget) price at +inf and can never win.

    ``tree_program="scan"`` runs every level past the root at the padded
    width 2^(max_depth-1) (one fixed-width program) but dispatches O(1)
    kernel programs instead of 2*depth — the ``_DISPATCH_OVERHEAD_S``
    term carries that tradeoff, so deep trees at modest N pick the scan
    and wide shallow frames keep per-level programs."""
    from ..models.tree.hist import hist_level_bytes, split_search_passes
    peak_f, peak_b = _peaks()
    B = nbins + 1
    total_bytes = 0.0
    total_flops = 0.0
    for d in range(max_depth):
        layout_d = ("sparse" if hist_layout == "sparse" and d >= threshold
                    else "dense")
        width = 2 ** (max_depth - 1) if (tree_program == "scan" and d > 0) \
            else 2 ** d
        b = hist_level_bytes(N, F, B, width, K,
                             layout=layout_d, hist_mode=hist_mode)
        if b is None:
            return float("inf")
        total_bytes += b * split_search_passes(split_mode)
        # one multiply-add per (row, feature, class) scatter contribution
        rows = N if (hist_mode == "full" or d == 0) else N // 2
        total_flops += 2.0 * rows * F * K
    launches = 2 if tree_program == "scan" else 2 * max_depth
    return (max(total_flops / peak_f, total_bytes / peak_b)
            * _ledger_calibration()
            + launches * _DISPATCH_OVERHEAD_S)


def _tree_candidates(F: int, N: int, K: int, max_depth: int, nbins: int,
                     *, mono, plan, hier: bool,
                     tuned: dict) -> List[dict]:
    """Joint candidate configs over the knobs being tuned; knobs pinned by
    the user keep their pinned value in every candidate.  The same
    feature-compat downgrades the shared.py resolvers apply constrain the
    space, so a candidate is always runnable."""
    from ..models.tree.shared import dense_mem_cap, sparse_layout_active
    hist_modes = (("subtract", "full") if tuned.get("hist_mode")
                  else (tuned.get("_hist_mode_pin", "subtract"),))
    split_modes = (("fused", "separate") if tuned.get("split_mode")
                   else (tuned.get("_split_mode_pin", "fused"),))
    if mono is not None or plan is not None or hier:
        split_modes = ("separate",)
    # the scan-fused program composes with dense uniform kernels only,
    # and needs >= 2 effective levels.  The depth gate is conservative
    # w.r.t. the builder (row cap from N <= n_padded), so a tuner-picked
    # "scan" can never hit the builder's fail-fast validation.
    row_cap = max(1, int(math.ceil(math.log2(max(N, 2)))) + 1)
    from ..models.tree.shared import dense_mem_cap as _dmc
    scan_ok = (mono is None and plan is None and not hier
               and min(max_depth, row_cap, _dmc(nbins, F)) >= 2)
    progs = (("level", "scan") if tuned.get("tree_program")
             else (tuned.get("_tree_program_pin", "level"),))
    out = []
    for hm in hist_modes:
        layouts: Tuple[Tuple[str, int], ...]
        sparse_ok = sparse_layout_active("auto", hm, mono=mono, plan=plan,
                                         hier=hier)
        cap = max(1, dense_mem_cap(nbins, F))
        if tuned.get("hist_layout"):
            layouts = (("dense", max_depth),)
            if sparse_ok:
                cands = (_THRESHOLD_CANDIDATES
                         if tuned.get("sparse_depth_threshold")
                         else (tuned.get("_threshold_pin",
                                         DEFAULT_SPARSE_THRESHOLD),))
                layouts += tuple(("sparse", min(t, cap)) for t in cands
                                 if t < max_depth)
        else:
            pin = tuned.get("_hist_layout_pin", "sparse")
            t_pin = min(tuned.get("_threshold_pin",
                                  DEFAULT_SPARSE_THRESHOLD), cap)
            layouts = ((pin, t_pin if pin == "sparse" else max_depth),)
            if pin == "sparse" and tuned.get("sparse_depth_threshold") \
                    and sparse_ok:
                layouts = tuple(("sparse", min(t, cap))
                                for t in _THRESHOLD_CANDIDATES
                                if t < max_depth) or layouts
        for sm in split_modes:
            for layout, thr in dict.fromkeys(layouts):
                if layout == "sparse" and not sparse_ok:
                    continue
                for tp in progs:
                    if tp == "scan" and (layout == "sparse"
                                         or not scan_ok):
                        continue
                    out.append({"hist_mode": hm, "split_mode": sm,
                                "hist_layout": layout,
                                "sparse_depth_threshold": int(thr),
                                "tree_program": tp})
    # dedupe while keeping model-preferred ordering stable
    seen, uniq = set(), []
    for c in out:
        k = _cand_key(c)
        if k not in seen:
            seen.add(k)
            uniq.append(c)
    return uniq


def _cand_key(c: dict) -> str:
    # stale cached choices keyed without the |p segment fall through
    # _decide's candidate-membership re-pick — no migration needed
    return (f"{c['hist_mode']}|{c['split_mode']}|{c['hist_layout']}"
            f"|t{c['sparse_depth_threshold']}"
            f"|p{c.get('tree_program', 'level')}")


def _predict_costs(F: int, N: int, K: int, max_depth: int, nbins: int,
                   candidates: List[dict]) -> Dict[str, float]:
    """Per-candidate roofline seconds (tests monkeypatch this to force a
    wrong model and prove measured refinement self-corrects)."""
    return {
        _cand_key(c): _predict_tree_cost(
            F, N, K, max_depth, nbins, hist_mode=c["hist_mode"],
            split_mode=c["split_mode"], hist_layout=c["hist_layout"],
            threshold=c["sparse_depth_threshold"],
            tree_program=c.get("tree_program", "level"))
        for c in candidates
    }


# ------------------------------------------------------------- decisions

def _note_decision(knobs: dict, source: str) -> None:
    for knob, choice in knobs.items():
        obs.inc("autotune_decisions_total", knob=knob, choice=str(choice),
                source=source)


def _publish_cache_gauge() -> None:
    obs.set_gauge("autotune_cache_entries", float(len(_DECISIONS)))


def _measured_best(ent: dict) -> Optional[str]:
    """Candidate key with the lowest measured EMA, when at least two
    candidates carry measurements (one measurement proves nothing about
    the alternatives)."""
    meas = {k: v["ema"] for k, v in ent["measured"].items() if v["n"] > 0}
    if len(meas) < 2:
        return None
    return min(meas, key=meas.get)


def _decide(sig: str, candidates: List[dict], predicted: Dict[str, float],
            mode: str) -> dict:
    """Look up / create the decision entry for ``sig`` and pick the config
    to RUN this resolve (usually the decision; sometimes the epsilon
    exploration of the runner-up)."""
    ent = _DECISIONS.get(sig)
    if ent is None:
        cached = _load_cached_entry(sig)
        if cached is not None:
            ent = cached
        else:
            best = min(predicted, key=predicted.get)
            ent = {"sig": sig, "choice": best, "source": "model",
                   "predicted": predicted, "measured": {}, "resolves": 0,
                   "explore": None, "epoch": _EPOCH}
        _DECISIONS[sig] = ent
        ent["candidates"] = {_cand_key(c): c for c in candidates}
        _publish_cache_gauge()
    ent.setdefault("candidates", {_cand_key(c): c for c in candidates})
    for c in candidates:                 # constraint set may have grown
        ent["candidates"].setdefault(_cand_key(c), c)
    ent["resolves"] += 1
    run_key = ent["choice"]
    ent["explore"] = None
    if (mode == "on" and ent["source"] in ("model", "measured")
            and len(ent["candidates"]) > 1
            and ent["resolves"] % _explore_every() == 0):
        # deterministic epsilon-greedy: re-measure the best *other*
        # candidate by predicted cost so a mis-seeded model gets evidence
        others = {k: v for k, v in ent["predicted"].items()
                  if k != ent["choice"] and k in ent["candidates"]
                  and v != float("inf")}
        if others:
            run_key = min(others, key=others.get)
            ent["explore"] = run_key
    if run_key not in ent["candidates"]:
        run_key = ent["choice"] = min(
            (k for k in ent["candidates"]),
            key=lambda k: ent["predicted"].get(k, float("inf")))
    return {"entry": ent, "run_key": run_key,
            "run": ent["candidates"][run_key]}


def on_device_sample(phase: str, seconds: float) -> None:
    """Measurement sink for ``xprof.maybe_device_sync``: attribute one
    true device-phase timing to the config currently executing under the
    active decision scope, and let the evidence overturn the model."""
    scope = getattr(_tls, "scope", None)
    if scope is None or autotune_mode() != "on" \
            or not phase.startswith("tree"):
        return
    sig, run_key = scope
    with _lock:
        ent = _DECISIONS.get(sig)
        if ent is None or ent["source"] == "cache":
            return
        m = ent["measured"].setdefault(run_key, {"ema": 0.0, "n": 0})
        m["ema"] = seconds if m["n"] == 0 \
            else 0.7 * m["ema"] + 0.3 * seconds
        m["n"] += 1
        best = _measured_best(ent)
        if best is not None and best != ent["choice"]:
            old = ent["choice"]
            ent["choice"] = best
            ent["source"] = "measured"
            obs.record("autotune_flip", sig=sig, old=old, new=best)
            _note_decision({"config": best}, "measured")
        elif best is not None:
            ent["source"] = "measured"
    _save_cache()


@contextlib.contextmanager
def _measurement_scope(sig: Optional[str], run_key: Optional[str]):
    prev = getattr(_tls, "scope", None)
    _tls.scope = (sig, run_key) if sig is not None else None
    try:
        yield
    finally:
        _tls.scope = prev


def activate(knobs: "TreeKnobs") -> None:
    """Pin the measurement scope for the calling (driver) thread: device
    samples taken until the next ``activate``/``deactivate`` on this
    thread attribute to this resolve's running config."""
    _tls.scope = (knobs.sig, knobs.run_key) if knobs.sig else None


def deactivate() -> None:
    _tls.scope = None


# ------------------------------------------------------------ tree knobs

@dataclasses.dataclass(frozen=True)
class TreeKnobs:
    """One resolve's effective kernel-strategy knobs (builder values)."""
    hist_mode: str
    split_mode: str
    hist_layout: str                     # dense | sparse | check
    sparse_depth_threshold: int
    tree_program: str                    # level | scan | check
    sources: dict                        # knob -> user|default|model|...
    sig: Optional[str] = None            # signature when the tuner engaged
    run_key: Optional[str] = None        # config key actually running


def resolve_tree_knobs(params, *, kind: str, F: int, N: int, K: int = 1,
                       mono=None, plan=None, hier: bool = False,
                       checkpoint: bool = False) -> TreeKnobs:
    """The drivers' single up-front knob resolution point.

    Explicit knob values (anything but ``"auto"``, including the
    ``"check"`` oracle modes) pass straight through the shared.py
    resolvers untouched.  ``"auto"`` knobs resolve to the historical
    fixed defaults when the tuner is off (bit-identical kernels), or to
    the per-signature decision when it is on.  Checkpoint continuations
    pin ``sparse_depth_threshold`` to the params value so resumed trees
    keep the depth ledger they were validated against."""
    from ..models.tree.shared import (resolve_hist_layout,
                                      resolve_hist_mode,
                                      resolve_split_mode,
                                      resolve_tree_program)
    hm_raw = str(getattr(params, "hist_mode", "auto")).lower()
    sm_raw = str(getattr(params, "split_mode", "auto")).lower()
    hl_raw = str(getattr(params, "hist_layout", "auto")).lower()
    tp_raw = str(getattr(params, "tree_program", "auto")).lower()
    thr_raw = int(getattr(params, "sparse_depth_threshold",
                          DEFAULT_SPARSE_THRESHOLD))
    max_depth = int(getattr(params, "max_depth", 5))
    nbins = int(getattr(params, "nbins", 64))

    # the baseline resolution every path starts from (validation +
    # feature-compat downgrades live in shared.py, exactly as before)
    hist_mode = resolve_hist_mode(params)
    split_mode = resolve_split_mode(params, mono=mono, plan=plan, hier=hier)
    hist_layout = resolve_hist_layout(params, hist_mode=hist_mode,
                                      mono=mono, plan=plan, hier=hier)
    tree_program = resolve_tree_program(params, hist_layout=hist_layout,
                                        mono=mono, plan=plan, hier=hier,
                                        F=F)
    sources = {
        "hist_mode": "default" if hm_raw == "auto" else "user",
        "split_mode": "default" if sm_raw == "auto" else "user",
        "hist_layout": "default" if hl_raw == "auto" else "user",
        "sparse_depth_threshold":
            "default" if thr_raw == DEFAULT_SPARSE_THRESHOLD else "user",
        "tree_program": "default" if tp_raw == "auto" else "user",
    }
    tuned = {
        "hist_mode": hm_raw == "auto",
        "split_mode": sm_raw == "auto",
        "hist_layout": hl_raw == "auto",
        "sparse_depth_threshold":
            thr_raw == DEFAULT_SPARSE_THRESHOLD and not checkpoint
            and hist_layout in ("sparse", "auto"),
        # uplift's bespoke two-arm grow loop has no scan-fused build, so
        # its signature never tunes tree_program (the pin stays "level")
        "tree_program": tp_raw == "auto" and kind != "uplift",
        "_hist_mode_pin": hist_mode,
        "_split_mode_pin": split_mode,
        "_hist_layout_pin": hist_layout,
        "_threshold_pin": thr_raw,
        "_tree_program_pin": tree_program,
    }
    mode = autotune_mode()
    # checks bypass tuning (the oracle decides), off bypasses everything
    if (mode == "off" or "check" in (hist_mode, split_mode, hist_layout,
                                     tree_program)
            or not any(tuned[k] for k in ("hist_mode", "split_mode",
                                          "hist_layout",
                                          "sparse_depth_threshold",
                                          "tree_program"))):
        return TreeKnobs(hist_mode, split_mode, hist_layout, thr_raw,
                         tree_program, sources)

    sig = _signature(kind, F, N, K, max_depth, nbins)
    with _lock:
        candidates = _tree_candidates(F, N, K, max_depth, nbins, mono=mono,
                                      plan=plan, hier=hier, tuned=tuned)
        if not candidates:
            return TreeKnobs(hist_mode, split_mode, hist_layout, thr_raw,
                             tree_program, sources)
        predicted = _predict_costs(F, N, K, max_depth, nbins, candidates)
        picked = _decide(sig, candidates, predicted, mode)
        ent, run = picked["entry"], picked["run"]
        knobs_out = {}
        for knob in ("hist_mode", "split_mode", "hist_layout",
                     "sparse_depth_threshold", "tree_program"):
            if tuned[knob]:
                knobs_out[knob] = run[knob]
                sources[knob] = ("explore" if picked["run_key"] ==
                                 ent["explore"] else ent["source"])
        _note_decision(knobs_out, ent["source"])
    _save_cache()
    return TreeKnobs(
        knobs_out.get("hist_mode", hist_mode),
        knobs_out.get("split_mode", split_mode),
        knobs_out.get("hist_layout", hist_layout),
        int(knobs_out.get("sparse_depth_threshold", thr_raw)),
        knobs_out.get("tree_program", tree_program),
        sources, sig=sig, run_key=picked["run_key"])


def resolve_grid_batch(*, kind: str, F: int, N: int, G: int,
                       max_depth: int, nbins: int, K: int = 1) -> str:
    """``grid_batch="auto"``: price ONE batched G-member cohort program
    against G scheduler-parallel builds; returns ``"batched"`` or
    ``"parallel"``.

    The batched program does the same histogram/split compute but pays
    the per-level dispatch overhead once instead of G times — so it wins
    on dispatch-bound shapes — while holding G x the model state (F
    vector, gradients, level histograms + carry) resident at once, so it
    loses when that estimate blows the device memory budget.  The choice
    key carries a ``|g{G}`` segment (cohort size is part of the
    decision, like ``|p`` for tree_program).  Off-mode keeps the same
    fixed model decision without recording: the knob is a performance
    choice, not a correctness one, and the wave path stays the oracle."""
    common = dict(hist_mode="subtract", split_mode="fused",
                  hist_layout="dense",
                  threshold=DEFAULT_SPARSE_THRESHOLD)
    batched = _predict_tree_cost(F, N, K * G, max_depth, nbins, **common)
    seq = G * _predict_tree_cost(F, N, K, max_depth, nbins, **common)
    B = nbins + 1
    W = 2 ** max(max_depth - 1, 0)
    # resident cohort state: F/g/h/w row vectors plus the level
    # histogram and its subtraction carry, x G members x K class trees
    state = float(G) * K * (16.0 * N + 2 * 3.0 * W * F * B * 4.0)
    budget = _HBM_BUDGET.get(_backend(), _HBM_BUDGET["cpu"])
    choice = "parallel" if (state > budget
                            or not math.isfinite(batched)
                            or batched >= seq) else "batched"
    if autotune_mode() == "off":
        return choice
    key = f"{choice}|g{G}"
    with _lock:
        sig = _signature(kind, F, N, K, max_depth, nbins) + ":grid"
        ent = _DECISIONS.get(sig)
        if ent is None:
            _DECISIONS[sig] = ent = {
                "sig": sig, "choice": key, "source": "model",
                "predicted": {f"batched|g{G}": batched,
                              f"parallel|g{G}": seq},
                "measured": {}, "resolves": 0, "explore": None,
                "epoch": _EPOCH, "candidates": {}}
            _note_decision({"grid_batch": key}, "model")
            _publish_cache_gauge()
        ent["resolves"] += 1
    _save_cache()
    return choice


# -------------------------------------------------- reduce / serve knobs

def resolve_reduce_mode_auto() -> str:
    """``reduce_mode="auto"``: hier when a DCN (multi-host) stage exists
    — the staged psum moves an already-reduced tensor across hosts — and
    flat on a single host, where the extra stage is pure overhead.  Off
    keeps the historical fixed default (``hier``)."""
    if autotune_mode() == "off":
        return "hier"
    hosts, _, _ = _mesh_geometry()
    choice = "hier" if hosts > 1 else "flat"
    with _lock:
        sig = f"reduce:mesh{hosts}:{_backend()}"
        if sig not in _DECISIONS:
            _DECISIONS[sig] = {"sig": sig, "choice": choice,
                               "source": "model", "predicted": {},
                               "measured": {}, "resolves": 0,
                               "explore": None, "epoch": _EPOCH,
                               "candidates": {}}
            _note_decision({"reduce_mode": choice}, "model")
            _publish_cache_gauge()
        _DECISIONS[sig]["resolves"] += 1
    return choice


def resolve_serve_impl(*, depth: int, R: int, F: int, B: int) -> str:
    """``serve impl="auto"``: the pallas fused traversal wins on TPU (its
    tiling matches the packed layout); everywhere else the XLA twin is
    the fast correct path.  Decision recorded per batch signature so the
    /3/Profiler/autotune table shows what serving actually runs."""
    choice = "pallas" if _backend() == "tpu" else "xla"
    if autotune_mode() == "off":
        return choice
    with _lock:
        sig = f"serve:d{depth}:R{R}:F{F}:B{B}:{_backend()}"
        if sig not in _DECISIONS:
            _DECISIONS[sig] = {"sig": sig, "choice": choice,
                               "source": "model", "predicted": {},
                               "measured": {}, "resolves": 0,
                               "explore": None, "epoch": _EPOCH,
                               "candidates": {}}
            _note_decision({"serve_impl": choice}, "model")
            _publish_cache_gauge()
        _DECISIONS[sig]["resolves"] += 1
    return choice


# ----------------------------------------------------------------- cache

def _cache_dir() -> Optional[str]:
    from .config import config
    d = config().autotune_cache_dir
    if d:
        return d
    from . import recovery
    base = recovery.recovery_dir()
    return os.path.join(base, "autotune") if base else None


def _cache_path() -> Optional[str]:
    d = _cache_dir()
    return os.path.join(d, "autotune_cache.json") if d else None


def _cache_header() -> dict:
    return {"version": 1, "backend": _backend(), "jax": _jax_version()}


_file_entries: Dict[str, dict] = {}


def _load_cache_file() -> None:
    """Read the persisted decision table once; corrupt or version-stale
    files silently degrade to model-seeded decisions (never an error)."""
    global _file_loaded
    if _file_loaded or _file_dead:
        return
    _file_loaded = True
    path = _cache_path()
    if not path:
        return
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or \
                data.get("header") != _cache_header():
            return
        entries = data.get("entries")
        if isinstance(entries, dict):
            _file_entries.update({k: v for k, v in entries.items()
                                  if isinstance(v, dict) and "choice" in v})
    except Exception:                    # noqa: BLE001 — degrade, never err
        return


def _load_cached_entry(sig: str) -> Optional[dict]:
    _load_cache_file()
    raw = _file_entries.get(sig)
    if raw is None:
        return None
    ent = {"sig": sig, "choice": str(raw["choice"]), "source": "cache",
           "predicted": {k: float(v) for k, v in
                         (raw.get("predicted") or {}).items()},
           "measured": {k: dict(v) for k, v in
                        (raw.get("measured") or {}).items()},
           "resolves": 0, "explore": None, "epoch": _EPOCH}
    return ent


def _save_cache() -> None:
    """Atomically persist the decision table (tmp + rename, the WAL
    pattern).  No recovery dir configured means in-memory only."""
    path = _cache_path()
    if not path:
        return
    with _lock:
        entries = {
            sig: {"choice": ent["choice"], "source": ent["source"],
                  "predicted": {k: v for k, v in ent["predicted"].items()
                                if v != float("inf")},
                  "measured": ent["measured"]}
            for sig, ent in _DECISIONS.items()
        }
    payload = {"header": _cache_header(), "entries": entries}
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception:                    # noqa: BLE001 — cache is best-effort
        pass


# ----------------------------------------------------------- maintenance

def invalidate(reason: str = "cluster_reinit") -> None:
    """Drop every memoized decision (and the loaded cache-file snapshot):
    called from ``cluster._invalidate_compiled_caches`` so a mesh rebuild
    can never serve a choice tuned for the dead geometry.  Fresh
    processes re-read the persisted cache; this process will not."""
    global _EPOCH, _file_loaded, _file_dead
    with _lock:
        _EPOCH += 1
        _DECISIONS.clear()
        _file_entries.clear()
        _file_loaded = False
        if reason == "cluster_reinit":
            _file_dead = True
        _publish_cache_gauge()
    obs.record("autotune_invalidate", reason=reason)


def reset() -> None:
    """Tests only: full reset including the cache-file dead flag."""
    global _EPOCH, _file_loaded, _file_dead
    with _lock:
        _EPOCH += 1
        _DECISIONS.clear()
        _file_entries.clear()
        _file_loaded = False
        _file_dead = False
        _publish_cache_gauge()
    _tls.scope = None


def decision_table() -> dict:
    """Plain-data decision table for ``GET /3/Profiler/autotune``:
    signature -> choice, source, predicted vs measured seconds."""
    with _lock:
        rows = []
        for sig, ent in _DECISIONS.items():
            meas = {k: round(v["ema"], 6)
                    for k, v in ent["measured"].items() if v["n"]}
            rows.append({
                "signature": sig,
                "choice": ent["choice"],
                "source": ent["source"],
                "resolves": ent["resolves"],
                "predicted_s": {k: (None if v == float("inf")
                                    else round(v, 6))
                                for k, v in ent["predicted"].items()},
                "measured_s": meas,
                "exploring": ent["explore"],
            })
        return {"mode": autotune_mode(), "epoch": _EPOCH,
                "entries": len(rows), "decisions": rows,
                "cache_file": _cache_path()}
