"""In-training progress snapshots — bounded-rework crash recovery.

Reference gap: ``hex/faulttolerance/Recovery.java:72-81`` replays only the
*work description* after a cluster restart, so an interrupted 500-tree GBM
restarts from tree 0.  Here the long-running builders periodically persist
a lightweight snapshot (model-so-far + progress cursor) next to their
recovery-journal entry; ``recovery.resume()`` reloads the snapshot and
continues through the existing ``checkpoint`` continuation machinery
(models/tree/shared.py resolve_checkpoint, deeplearning's weight restore),
bounding retrained work by the snapshot cadence instead of the job length.

Contract (all three properties are load-bearing):

- **throttled** — at most one write per ``H2O3_TPU_SNAPSHOT_INTERVAL``
  seconds per job (default 30; 0 = every opportunity, used by tests), so
  snapshot cost never competes with training throughput.  The payload
  builder is only invoked when a write is actually due.
- **async** — the pickle is built on the training thread (cheap: model
  metadata, kilobytes-to-megabytes), the persist write happens on a
  single daemon writer thread (``H2O3_TPU_SNAPSHOT_ASYNC=0`` forces
  synchronous writes for deterministic tests).
- **best-effort** — a failed snapshot write must NEVER fail training.
  Every exception is swallowed into a log line; the journal keeps
  pointing at the previous complete snapshot, so a write torn by a
  crash is invisible to ``resume()``.

Write ordering: snapshot file first (generation-numbered name), then the
journal entry is re-pointed at it, then the previous generation is
deleted — the journal never references a partial file.
"""

from __future__ import annotations

import pickle
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_last_write: Dict[str, float] = {}      # journal uri -> monotonic ts
_gen: Dict[str, int] = {}               # journal uri -> generation counter
_worker: Optional[threading.Thread] = None
_queue: "queue.Queue" = queue.Queue()
_idle = threading.Event()
_idle.set()


def reset() -> None:
    """Forget throttle/generation state (tests)."""
    flush()
    with _lock:
        _last_write.clear()
        _gen.clear()


def _due(journal_uri: str, interval: Optional[float] = None) -> bool:
    from .config import config
    if interval is None or interval < 0:
        interval = config().snapshot_interval_s
    with _lock:
        now = time.monotonic()
        if now - _last_write.get(journal_uri, -1e18) < interval:
            return False
        _last_write[journal_uri] = now
        return True


def _snapshot_uri(journal_uri: str) -> str:
    with _lock:
        g = _gen[journal_uri] = _gen.get(journal_uri, 0) + 1
    base, _, name = journal_uri.rpartition("/")
    stem = name[: -len(".json")] if name.endswith(".json") else name
    return f"{base}/snap_{stem[len('job_'):] or stem}_{g}.bin"


def model_state_bytes(model, extra_output: Optional[dict] = None) -> bytes:
    """Pickle a model-so-far in exactly ``Model.save``'s on-disk format
    (so ``Model.load`` reads it back), with ``extra_output`` overriding
    output fields the builder has not finalized yet.  The snapshot gets
    a ``<key>_snap`` key so loading it never clobbers the real model."""
    import jax
    import numpy as np
    state = model.__dict__.copy()
    state.pop("_interval_metrics", None)   # transient scoring cache
    out = dict(state.get("output") or {})
    out.update(extra_output or {})
    out.pop("stacked", None)            # rebuilt lazily after load
    state["output"] = out
    state["key"] = f"{model.key}_snap"
    state = jax.tree.map(
        lambda v: np.asarray(v) if isinstance(v, jax.Array) else v, state)
    return pickle.dumps((type(model), state))


def maybe_snapshot(job, model, cursor: dict,
                   state_fn: Callable[[], dict]) -> Optional[str]:
    """Builder-facing entry point: persist a progress snapshot when due.

    ``job.journal_uri`` (set by the training driver when
    ``H2O3_TPU_RECOVERY_DIR`` is active) gates the whole feature — no
    journal, no snapshots.  ``state_fn`` returns the output-dict override
    for the model-so-far (only called when a write is due — it may cost a
    device fetch).  ``cursor`` is the journaled progress record; its
    optional ``resume_params`` dict is applied onto the journaled params
    by ``resume()`` (e.g. deeplearning's remaining-epoch count).
    Never raises.  Returns the snapshot URI when a write was queued.
    """
    journal_uri = getattr(job, "journal_uri", None) if job is not None \
        else None
    if not journal_uri:
        return None
    from .observability import log
    try:
        interval = float(getattr(model.params, "snapshot_interval", -1.0))
        if not _due(journal_uri, interval):
            return None
        extra = state_fn()
        payload = model_state_bytes(model, extra)
    except Exception as e:                 # noqa: BLE001 — best-effort
        log.warning("snapshot build for %s failed: %r", journal_uri, e)
        return None
    uri = _snapshot_uri(journal_uri)
    task = (uri, payload, journal_uri, dict(cursor), time.time())
    from .config import config
    if config().snapshot_async:
        _ensure_worker()
        _idle.clear()
        _queue.put(task)
    else:
        _write_task(task)
    return uri


def progress(job, cursor: dict) -> None:
    """Cursor-only journal update (no model payload) for builders whose
    in-progress state is not yet a loadable model (GLM lambda path).
    Throttled and best-effort like ``maybe_snapshot``."""
    journal_uri = getattr(job, "journal_uri", None) if job is not None \
        else None
    if not journal_uri or not _due(journal_uri):
        return
    from . import recovery
    recovery.journal_update_snapshot(journal_uri, None, dict(cursor))


def flush(timeout: float = 30.0) -> None:
    """Block until queued writes have drained (tests / orderly shutdown)."""
    deadline = time.time() + timeout
    while not _idle.is_set() and time.time() < deadline:
        _idle.wait(0.05)


def _ensure_worker() -> None:
    global _worker
    with _lock:
        if _worker is not None and _worker.is_alive():
            return
        _worker = threading.Thread(target=_drain, daemon=True,
                                   name="snapshot-writer")
        _worker.start()


def _drain() -> None:
    while True:
        task = _queue.get()
        try:
            _write_task(task)
        except Exception:                  # noqa: BLE001 — never die
            pass
        finally:
            if _queue.empty():
                _idle.set()


def _write_task(task) -> None:
    uri, payload, journal_uri, cursor, queued_ts = task
    from . import failure, recovery
    from .observability import log, observe, record
    t0 = time.time()
    # lag = queue dwell before the writer picked the task up; a growing
    # lag means the async writer is falling behind the snapshot cadence
    lag = max(t0 - queued_ts, 0.0)
    try:
        failure.maybe_inject("snapshot_write")
        from .. import persist
        with persist.open_write(uri) as f:
            f.write(payload)
        prev = recovery.journal_update_snapshot(journal_uri, uri, cursor)
        observe("snapshot_lag_seconds", lag)
        observe("snapshot_write_seconds", time.time() - t0)
        record("snapshot_write", uri=uri, bytes=len(payload),
               cursor=cursor, lag_s=round(lag, 4),
               duration_s=round(time.time() - t0, 4))
        if prev and prev != uri:
            try:
                persist.delete(prev)
            except Exception:              # noqa: BLE001
                pass
    except Exception as e:                 # noqa: BLE001 — best-effort
        log.warning("snapshot write %s failed: %r", uri, e)


def load_model(uri: str):
    """Load a snapshot back into a Model (DKV-registered under its
    ``_snap`` key) — resume()'s side of the contract."""
    from ..models.base import Model
    return Model.load(uri)
