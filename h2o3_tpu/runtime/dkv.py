"""DKV: the keyed object store for frames, models and jobs.

Reference: ``water/DKV.java:52`` / ``water/Key.java:44`` — a cluster-wide
distributed hash map where every Frame/Vec/Chunk/Model/Job lives under a Key
homed to a node, coherent via invalidates, backed by Cliff Click's
NonBlockingHashMap (water/nbhm/).

TPU-native redesign: bulk payloads (column data) are ``jax.Array``s whose
placement is already expressed by shardings — the JAX runtime is the
"distributed" part.  What remains is the *control-plane* index: a name ->
object map, served over DCN by a small TCP service on the coordinator host
(SURVEY.md §5 two-plane design: XLA collectives on ICI for compute, host
TCP for control; this replaces the reference's UDP/RPC + Paxos).  In the
multi-process SPMD world every process executes the same program, so
device-backed objects (frames, models) exist everywhere by construction;
the coordinator service carries the *metadata* plane — key listings, job
status, small host objects — and gives non-zero processes and external
clients (REST) a consistent view.  The API mirrors DKV.get/put/remove.

Well-known ``!``-prefixed (plain, WAL-durable) key families: ``!hb/``
heartbeat stamps, ``!failures/`` dead-member records, ``!sched/``
scheduling records, ``!lineage/<frame>`` shard-provenance records and
``!replica/<frame>/<shard>`` hot-frame replica shards
(frame/lineage.py), and ``!serve/<model>`` journaled serving publishes
(serving/batcher.py).

Crash-recoverable coordinator (the reference survives coordinator loss via
Paxos membership + UDP retransmit; the TCP control plane needs all three
explicitly):

* **Durability** — when a local recovery dir is configured, every
  plain-host-data mutation is appended to a write-ahead log
  (``<dir>/dkv/wal_<gen>.log``, crc32-framed, flushed per record) and
  periodically compacted into a snapshot (``snap_<gen>.pkl``);
  ``serve()`` rehydrates snapshot+WAL, so a restarted coordinator comes
  back knowing its keys, job records, and ``make_key`` counter.
* **Epoch fencing** — each ``serve()`` incarnation takes a monotonic
  epoch (persisted in ``EPOCH`` when durable, wall-clock-seeded when
  not) stamped into every RPC response.  Clients track the highest seen
  epoch: a *bump* means the coordinator restarted — they re-push their
  locally-originated plain keys (the SPMD store is the source of truth);
  a *lower* epoch means a stale incarnation is still answering and the
  response is refused (retried until the live one answers).
* **Exactly-once RPC** — the retry loop is at-least-once over transport,
  so mutating ops carry a client-generated request id; the coordinator
  keeps a dedup window (rebuilt from the WAL across restarts) and
  answers a retried op from it instead of re-applying — a dropped
  *response* can no longer double-apply ``incr`` or burn ``make_key``
  counters.
"""

from __future__ import annotations

import collections
import contextlib
import os
import pickle
import socket
import socketserver
import ssl
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

_store: Dict[str, Any] = {}
_lock = threading.RLock()
_counter = 0

# coordinator service state
_remote: Optional[Tuple[str, int]] = None     # set on non-coordinator procs
_server: Optional["_DKVServer"] = None
_client_ssl: Optional[ssl.SSLContext] = None

# epoch fencing: this incarnation's epoch (coordinator) / highest seen (client)
_epoch = 0
_seen_epoch = 0
_epoch_lock = threading.Lock()
_repushing = False
_local_plain: set = set()       # plain keys this process originated

# durability: write-ahead log + compacted snapshots (coordinator only)
_wal_f = None
_wal_gen = 0
_wal_records = 0
_wal_bytes = 0
_restored = 0

# exactly-once: request-id -> response value (bounded, WAL-rebuilt)
_MUTATING = frozenset({"put", "remove", "cas", "incr", "make_key"})
_dedup: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
_nonce = f"{os.getpid():x}.{os.urandom(3).hex()}"
_req_seq = 0

_budget_tls = threading.local()


class StaleCoordinatorError(ConnectionError):
    """A response arrived from an older coordinator incarnation than this
    client has already talked to — split-brain protection: the response
    is refused and the op retried until the live incarnation answers."""


def is_coordinator() -> bool:
    """Is this process currently serving the DKV control plane?"""
    return _server is not None


def _tls_contexts():
    """Optional internode TLS (h2o-security internal_security analog).

    Set H2O3_TPU_TLS_CERT / H2O3_TPU_TLS_KEY (PEM paths) on every process
    to wrap the DCN control plane in TLS; the cert doubles as the trust
    anchor (private-CA / self-signed deployment model, like the
    reference's keystore-based internal security).  Returns
    (server_ctx, client_ctx) or (None, None).
    """
    from .config import config
    cert = config().tls_cert
    key = config().tls_key
    if not cert:
        return None, None
    srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    srv.load_cert_chain(cert, key or None)
    cli = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    cli.check_hostname = False
    cli.load_verify_locations(cert)
    return srv, cli


def _is_plain(value: Any, depth: int = 0) -> bool:
    """True when value is safely picklable host data (no device arrays)."""
    import numpy as np
    if depth > 6:
        return False
    if value is None or isinstance(value, (str, bytes, int, float, bool,
                                           np.generic, np.ndarray)):
        return True
    if isinstance(value, (list, tuple, set)):
        return all(_is_plain(v, depth + 1) for v in value)
    if isinstance(value, dict):
        return all(isinstance(k, str) and _is_plain(v, depth + 1)
                   for k, v in value.items())
    return False


def make_key(prefix: str) -> str:
    """Fresh unique key — analog of Key.make() (water/Key.java:44).

    Always the LOCAL counter, even when attached to a coordinator: SPMD
    processes execute the same program line-for-line, so local counters
    stay in lock-step and every process derives the SAME name for the same
    logical object — a coordinator counter would hand each process a
    different key for one model.
    """
    global _counter
    with _lock:
        _counter += 1
        key = f"{prefix}_{_counter}"
        _wal_append({"op": "counter", "n": _counter})
        return key


def put(key: str, value: Any) -> str:
    plain = _is_plain(value)
    with _lock:
        is_new = key not in _store
        _store[key] = value
        if plain:
            _local_plain.add(key)
            _wal_append({"op": "put", "key": key, "value": value})
    if is_new:                           # upserts of pre-existing keys are
        from . import scope              # NOT scope-owned temporaries
        scope.track(key)
    if _remote is not None and plain:
        _rpc("put", key=key, value=value)
    return key


def get(key: str) -> Optional[Any]:
    with _lock:
        v = _store.get(key)
    if v is None and _remote is not None:
        v = _rpc("get", key=key)
    return v


def remove(key: str) -> None:
    with _lock:
        _store.pop(key, None)
        _local_plain.discard(key)
        _wal_append({"op": "remove", "key": key})
    if _remote is not None:
        _rpc("remove", key=key)


def keys(prefix: str = "") -> List[str]:
    with _lock:
        local = {k for k in _store if k.startswith(prefix)}
    if _remote is not None:
        local.update(_rpc("keys", prefix=prefix))
    return sorted(local)


def clear() -> None:
    with _lock:
        _store.clear()
        _local_plain.clear()


def local_size() -> int:
    """Local key count only — no coordinator round trip (heartbeat)."""
    with _lock:
        return len(_store)


# ------------------------------------------------------------- atomic ops
def cas(key: str, expected: Any, new: Any) -> bool:
    """Compare-and-set — the water/Atomic/TAtomic analog for control-plane
    state (grid bookkeeping, counters).  Equality-compared; atomic under
    the store lock locally, executed coordinator-side when attached."""
    if _remote is not None:
        return bool(_rpc("cas", key=key, expected=expected, new=new))
    with _lock:
        if _store.get(key) == expected:
            _store[key] = new
            if _is_plain(new):
                _wal_append({"op": "put", "key": key, "value": new})
            return True
        return False


def incr(key: str, delta: float = 1.0) -> float:
    """Atomic numeric increment; missing keys start at 0."""
    if _remote is not None:
        return float(_rpc("incr", key=key, delta=delta))
    with _lock:
        v = float(_store.get(key, 0.0)) + delta
        _store[key] = v
        _wal_append({"op": "put", "key": key, "value": v})
        return v


# --------------------------------------------------------------------------
# Coordinator service: length-prefixed pickle RPC over TCP (the control
# plane of SURVEY.md §5 — DCN traffic, never device payloads).
#
# Coherence contract: SPMD processes stay coherent BY CONSTRUCTION (every
# process executes the same put/remove at the same program point); the
# coordinator index is the authoritative view for EXTERNAL readers (REST
# clients, tooling).  There is deliberately no cross-process invalidation
# push — a coordinator-side mutation by an external writer is visible to a
# worker only for keys the worker never stored locally (its get() falls
# through to the coordinator).  This mirrors the reference's stance that
# clients are coordinators of record, not peers (water/DKV.java caching is
# likewise only coherent among cluster members).
# --------------------------------------------------------------------------

def _recvall(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("DKV peer closed connection")
        buf += chunk
    return buf


def _rpc_once(payload: bytes) -> dict:
    """One TCP/TLS round trip to the coordinator (no retry)."""
    with socket.create_connection(_remote, timeout=60) as raw:
        s = _client_ssl.wrap_socket(raw, server_hostname=_remote[0]) \
            if _client_ssl is not None else raw
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        n = struct.unpack("<Q", _recvall(s, 8))[0]
        resp = pickle.loads(_recvall(s, n))
        if s is not raw:
            s.close()
    return resp


def _next_req_id() -> str:
    global _req_seq
    with _lock:
        _req_seq += 1
        return f"{_nonce}:{_req_seq}"


@contextlib.contextmanager
def retry_budget(seconds: float):
    """Cap this thread's DKV retry budget for the enclosed ops.

    Heartbeat stamps use this: one missed stamp is better than a beat
    thread blocked for the full 30 s default budget."""
    prev = getattr(_budget_tls, "seconds", None)
    _budget_tls.seconds = seconds
    try:
        yield
    finally:
        _budget_tls.seconds = prev


def _note_epoch(ep: int) -> None:
    """Fence a response's coordinator epoch.

    Lower than already seen ⇒ stale incarnation still answering: refuse
    (StaleCoordinatorError is transport-class, so the op retries).
    Higher than already seen ⇒ the coordinator restarted: re-push this
    process's locally-originated plain keys — the SPMD store is the
    source of truth and the new incarnation may have lost writes that
    landed after its last WAL record (or have no WAL at all)."""
    global _seen_epoch, _repushing
    if not ep:
        return
    do_repush = False
    with _epoch_lock:
        if _seen_epoch and ep < _seen_epoch:
            raise StaleCoordinatorError(
                f"DKV response from stale coordinator epoch {ep} "
                f"(already saw {_seen_epoch})")
        if _seen_epoch and ep > _seen_epoch and not _repushing:
            _repushing = True
            do_repush = True
        old, _seen_epoch = _seen_epoch, max(_seen_epoch, ep)
    if do_repush:
        try:
            _repush(old, ep)
        finally:
            with _epoch_lock:
                _repushing = False


def _repush(old: int, new: int) -> None:
    with _lock:
        items = [(k, _store[k]) for k in sorted(_local_plain)
                 if k in _store and _is_plain(_store[k])]
    from .observability import log, record
    record("dkv_epoch_bump", old_epoch=old, new_epoch=new,
           repushed=len(items))
    log.warning("DKV coordinator epoch bump %d -> %d (restart detected); "
                "re-pushing %d locally-originated keys", old, new,
                len(items))
    for k, v in items:
        try:
            _rpc("put", key=k, value=v)
        except Exception as e:           # noqa: BLE001 — best-effort heal
            log.warning("DKV re-push of %r failed: %r", k, e)
    # the re-pushed heartbeat stamp carries the metrics snapshot that was
    # current at the LAST beat; stamp again now so the new coordinator
    # incarnation sees fresh telemetry immediately (no gap while the beat
    # thread sleeps out its interval)
    try:
        from . import heartbeat
        heartbeat.reship()
    except Exception as e:               # noqa: BLE001 — telemetry only
        log.warning("post-bump telemetry re-ship failed: %r", e)


def _rpc(op: str, **kw) -> Any:
    """Coordinator RPC with per-op retry: exponential backoff + jitter
    under a retry budget.

    A transient coordinator hiccup (restart, connection reset, listen
    backlog overflow) used to kill the first heartbeat/journal/job RPC
    that hit it — the reference survives these via UDP retransmit; the
    TCP control plane needs explicit retries.  Only transport errors are
    retried; an error REPORTED by the coordinator (``resp["err"]``) is
    authoritative and raises immediately.  Knobs: ``H2O3_TPU_DKV_RETRIES``
    (extra attempts, default 5), ``H2O3_TPU_DKV_BACKOFF_BASE`` /
    ``H2O3_TPU_DKV_BACKOFF_MAX`` (seconds, default 0.05/2.0), and
    ``H2O3_TPU_DKV_RETRY_BUDGET`` (total seconds across one op's
    retries, default 30; ``retry_budget()`` caps it per thread).

    Retry makes transport at-least-once, so mutating ops carry a request
    id generated ONCE per logical op — every retry resends the same id
    and the coordinator's dedup window makes the retry idempotent
    (exactly-once).  Every response is epoch-fenced via ``_note_epoch``.

    Telemetry: the active trace context rides the envelope (``trace``
    key), so the coordinator's handler span joins the caller's trace;
    client latency lands in ``dkv_rpc_seconds{op,side,retried}``.
    """
    import random

    from .config import config
    from . import observability as obs
    if op in _MUTATING:
        kw.setdefault("req_id", _next_req_id())
    trace_ctx = obs.current_trace()
    if trace_ctx:
        kw["trace"] = trace_ctx
    payload = pickle.dumps({"op": op, **kw},
                           protocol=pickle.HIGHEST_PROTOCOL)
    cfg = config()
    budget = getattr(_budget_tls, "seconds", None)
    if budget is None:
        budget = cfg.dkv_retry_budget_s
    deadline = time.time() + budget
    attempt = 0
    t0 = time.perf_counter()
    with obs.span("dkv_rpc", op=op):
        while True:
            try:
                from . import failure
                failure.maybe_inject("dkv_rpc")
                resp = _rpc_once(payload)
                # a drop HERE models a lost response: the server has
                # already applied the op, so the retry must hit the
                # dedup window
                failure.maybe_inject("dkv_rpc_resp")
                _note_epoch(resp.get("epoch", 0))
                break
            except (ConnectionError, TimeoutError,
                    ssl.SSLError, OSError) as e:
                attempt += 1
                now = time.time()
                if attempt > cfg.dkv_retries or now >= deadline:
                    obs.inc("dkv_rpc_failures", op=op)
                    raise
                from .observability import log, record
                sleep = min(cfg.dkv_backoff_base_s * (2 ** (attempt - 1)),
                            cfg.dkv_backoff_max_s)
                sleep *= 0.5 + random.random()      # jitter in [0.5x, 1.5x)
                sleep = min(sleep, max(deadline - now, 0.01))
                record("dkv_retry", op=op, attempt=attempt, error=repr(e))
                log.warning("DKV %s RPC failed (%r); retry %d/%d in %.2fs",
                            op, e, attempt, cfg.dkv_retries, sleep)
                time.sleep(sleep)
        obs.observe("dkv_rpc_seconds", time.perf_counter() - t0,
                    op=op, side="client",
                    retried="true" if attempt else "false")
        if resp.get("err"):
            raise RuntimeError(f"DKV coordinator error: {resp['err']}")
    return resp.get("value")


# ------------------------------------------------------ durability (WAL)
#
# File layout under <durable dir> (default <H2O3_TPU_RECOVERY_DIR>/dkv):
#   wal_<gen>.log   crc32+length-framed pickled mutation records, flushed
#                   per record (survives process SIGKILL; machine loss is
#                   out of scope — the reference's Paxos doesn't survive
#                   that either)
#   snap_<gen>.pkl  compacted snapshot of the plain store + counter +
#                   dedup window, written every dkv_wal_compact_every
#                   records via tmp+rename (never torn)
#   EPOCH           this coordinator's incarnation counter
#
# Record ops are normalized to replayable primitives: put / remove /
# counter (cas success and incr become the resulting put; the make_key
# counter becomes its high-water mark), each carrying the request id +
# response so the exactly-once dedup window survives a restart too.

def _durable_dir() -> Optional[str]:
    from .config import config
    d = config().dkv_wal_dir
    if not d:
        from . import recovery
        base = recovery.recovery_dir()
        if base:
            d = os.path.join(base, "dkv")
    if not d or "://" in d:              # WAL needs a local appendable path
        return None
    return d


def _wal_open(d: str) -> None:
    global _wal_f
    _wal_f = open(os.path.join(d, f"wal_{_wal_gen}.log"), "ab")


def _close_wal() -> None:
    global _wal_f, _wal_records, _wal_bytes, _wal_gen, _restored
    if _wal_f is not None:
        try:
            _wal_f.close()
        except OSError:
            pass
    _wal_f = None
    _wal_records = _wal_bytes = _wal_gen = _restored = 0


def _wal_append(rec: dict) -> None:
    """Append one normalized mutation record (caller holds ``_lock``).

    No-op off-coordinator / non-durable.  Best-effort by design: a full
    disk degrades durability, it must not fail the control plane."""
    global _wal_records, _wal_bytes
    if _wal_f is None:
        return
    try:
        blob = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        _wal_f.write(struct.pack("<II", zlib.crc32(blob), len(blob)) + blob)
        _wal_f.flush()
        _wal_records += 1
        _wal_bytes += len(blob) + 8
        from .observability import count
        count("dkv_wal_records")
        count("dkv_wal_bytes", len(blob) + 8)
        from .config import config
        if _wal_records >= config().dkv_wal_compact_every:
            _compact()
    except Exception as e:               # noqa: BLE001
        from .observability import log
        log.warning("DKV WAL append failed: %r", e)


def _mutation_record(op: str, req: dict, value: Any) -> Optional[dict]:
    """Normalize an APPLIED mutation to a replayable WAL record (or None
    when nothing durable changed).  Caller holds ``_lock``."""
    rid = req.get("req_id")
    if op == "put" and _is_plain(req["value"]):
        return {"op": "put", "key": req["key"], "value": req["value"],
                "rid": rid, "resp": value}
    if op == "remove":
        return {"op": "remove", "key": req["key"], "rid": rid, "resp": None}
    if op == "cas" and value and _is_plain(req["new"]):
        return {"op": "put", "key": req["key"], "value": req["new"],
                "rid": rid, "resp": True}
    if op == "incr":
        return {"op": "put", "key": req["key"], "value": value,
                "rid": rid, "resp": value}
    if op == "make_key":
        return {"op": "counter", "n": _counter, "rid": rid, "resp": value}
    return None


def _trim_dedup() -> None:
    from .config import config
    cap = config().dkv_dedup_window
    while len(_dedup) > cap:
        _dedup.popitem(last=False)


def _compact() -> None:
    """Fold the WAL into a fresh snapshot generation (caller holds
    ``_lock``); old generation files are reaped only after the new
    snapshot is durably in place."""
    global _wal_gen, _wal_records, _wal_bytes, _wal_f
    d = os.path.dirname(_wal_f.name)
    old_gen, new_gen = _wal_gen, _wal_gen + 1
    snap = {"store": {k: v for k, v in _store.items() if _is_plain(v)},
            "counter": _counter, "epoch": _epoch, "dedup": dict(_dedup)}
    tmp = os.path.join(d, f"snap_{new_gen}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(snap, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(d, f"snap_{new_gen}.pkl"))
    old_wal = _wal_f.name
    _wal_f.close()
    _wal_gen, _wal_records, _wal_bytes = new_gen, 0, 0
    _wal_open(d)
    for stale in (old_wal, os.path.join(d, f"snap_{old_gen}.pkl")):
        try:
            os.remove(stale)
        except OSError:
            pass
    from .observability import count, log, record
    count("dkv_wal_compactions")
    record("dkv_wal", event="compact", gen=new_gen,
           keys=len(snap["store"]))
    log.info("DKV WAL compacted into snapshot gen %d (%d plain keys)",
             new_gen, len(snap["store"]))


def _rehydrate(d: str) -> Tuple[int, int]:
    """Rebuild durable control-plane state: latest snapshot + WAL replay.

    In-memory state wins per key — an in-process re-serve is not a
    crash, its live values are newer than the disk's.  A torn WAL tail
    (crash mid-write) is truncated so later appends stay replayable.
    Returns (restored_key_count, epoch_hint).  Caller holds ``_lock``."""
    global _counter, _wal_gen, _wal_records, _wal_bytes, _restored
    import re as _re

    from .observability import log
    gens = set()
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    for n in names:
        m = _re.fullmatch(r"(?:snap|wal)_(\d+)\.(?:pkl|log)", n)
        if m:
            gens.add(int(m.group(1)))
    if not gens:
        _wal_gen = 0
        return 0, 0
    gen = max(gens)
    state: Dict[str, Any] = {}
    dedup: Dict[str, Any] = {}
    counter = 0
    epoch_hint = 0
    snap_path = os.path.join(d, f"snap_{gen}.pkl")
    if os.path.exists(snap_path):
        try:
            with open(snap_path, "rb") as f:
                snap = pickle.load(f)
            state.update(snap.get("store", {}))
            dedup.update(snap.get("dedup", {}))
            counter = int(snap.get("counter", 0))
            epoch_hint = int(snap.get("epoch", 0))
        except Exception as e:           # noqa: BLE001
            log.warning("DKV snapshot %s unreadable: %r", snap_path, e)
    wal_path = os.path.join(d, f"wal_{gen}.log")
    nrec = nbytes = 0
    if os.path.exists(wal_path):
        try:
            with open(wal_path, "rb") as f:
                blob = f.read()
        except OSError:
            blob = b""
        off = 0
        while off + 8 <= len(blob):
            crc, ln = struct.unpack_from("<II", blob, off)
            body = blob[off + 8: off + 8 + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                break                    # torn/corrupt tail
            try:
                rec = pickle.loads(body)
            except Exception:            # noqa: BLE001
                break
            op = rec.get("op")
            if op == "put":
                state[rec["key"]] = rec["value"]
            elif op == "remove":
                state.pop(rec["key"], None)
            elif op == "counter":
                counter = max(counter, int(rec["n"]))
            if rec.get("rid"):
                dedup[rec["rid"]] = rec.get("resp")
            off += 8 + ln
            nrec += 1
            nbytes += 8 + ln
        if off < len(blob):
            log.warning("DKV WAL %s: torn tail at byte %d truncated "
                        "(%d records replayed)", wal_path, off, nrec)
            try:
                with open(wal_path, "r+b") as f:
                    f.truncate(off)
            except OSError:
                pass
    restored = 0
    for k, v in state.items():
        if k not in _store:
            _store[k] = v
            restored += 1
    _counter = max(_counter, counter)
    for rid, resp in dedup.items():
        _dedup.setdefault(rid, resp)
    _trim_dedup()
    _wal_gen, _wal_records, _wal_bytes = gen, nrec, nbytes
    _restored = restored
    return restored, epoch_hint


def _bump_epoch(d: Optional[str], hint: int = 0) -> int:
    """Take the next coordinator incarnation epoch.

    Durable dirs persist it in EPOCH (monotonic across restarts);
    without one the wall clock seeds it, so a restarted coordinator
    *process* still presents a higher epoch than its predecessor."""
    global _epoch
    prev = max(_epoch, hint)
    if d:
        try:
            with open(os.path.join(d, "EPOCH")) as f:
                prev = max(prev, int(f.read().strip() or 0))
        except (OSError, ValueError):
            pass
    else:
        prev = max(prev, int(time.time()))
    _epoch = prev + 1
    if d:
        try:
            tmp = os.path.join(d, "EPOCH.tmp")
            with open(tmp, "w") as f:
                f.write(str(_epoch))
            os.replace(tmp, os.path.join(d, "EPOCH"))
        except OSError as e:
            from .observability import log
            log.warning("DKV epoch persist failed: %r", e)
    return _epoch


def wal_stats() -> dict:
    """Control-plane durability/fencing facts — the /3/Recovery and
    /3/Cloud operator view."""
    with _lock:
        return {
            "role": ("coordinator" if _server is not None
                     else "worker" if _remote is not None else "local"),
            "epoch": _epoch,
            "seen_epoch": _seen_epoch,
            "durable": _wal_f is not None,
            "durable_dir": (os.path.dirname(_wal_f.name)
                            if _wal_f is not None else None),
            "wal_gen": _wal_gen,
            "wal_records": _wal_records,
            "wal_bytes": _wal_bytes,
            "restored_keys": _restored,
            "dedup_entries": len(_dedup),
        }


# ----------------------------------------------------------- the service

def _apply_op(op: str, req: dict) -> Any:
    """Apply one op against the local store (caller holds ``_lock``).
    Shared by the coordinator handler and nothing else — the local API
    keeps its inline fast paths — so handler semantics live in one
    place."""
    global _counter
    if op == "put":
        _store[req["key"]] = req["value"]
        return req["key"]
    if op == "get":
        return _store.get(req["key"])
    if op == "remove":
        _store.pop(req["key"], None)
        return None
    if op == "keys":
        return sorted(k for k in _store if k.startswith(req["prefix"]))
    if op == "cas":
        if _store.get(req["key"]) == req["expected"]:
            _store[req["key"]] = req["new"]
            return True
        return False
    if op == "incr":
        v = float(_store.get(req["key"], 0.0)) + req["delta"]
        _store[req["key"]] = v
        return v
    if op == "make_key":
        _counter += 1
        return f"{req['prefix']}_{_counter}"
    if op == "ping":
        return "pong"
    raise ValueError(f"unknown DKV op {op!r}")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        from .config import config
        cfg = config()
        try:
            from . import failure
            failure.maybe_inject("dkv_handle")
            # a half-open client must not pin this thread forever
            self.request.settimeout(cfg.dkv_recv_timeout_s)
            n = struct.unpack("<Q", _recvall(self.request, 8))[0]
            if n > cfg.dkv_max_frame_mb * (1 << 20):
                raise ValueError(
                    f"DKV frame of {n} bytes exceeds the "
                    f"{cfg.dkv_max_frame_mb:g} MB cap "
                    f"(H2O3_TPU_DKV_MAX_FRAME_MB)")
            req = pickle.loads(_recvall(self.request, n))
            op = req["op"]
            rid = req.get("req_id")
            # adopt the caller's trace context (if any) so the handler
            # span lands in the same tree as the client's dkv_rpc span
            from . import observability as obs
            t0 = time.perf_counter()
            dedup_hit = False
            with obs.trace_context(req.get("trace")), \
                    obs.span("dkv_handle", op=op):
                with _lock:
                    if rid is not None and rid in _dedup:
                        value = _dedup[rid]      # retried op: already applied
                        dedup_hit = True
                        obs.count("dkv_dedup_hits")
                    else:
                        value = _apply_op(op, req)
                        if op in _MUTATING:
                            rec = _mutation_record(op, req, value)
                            if rec is not None:
                                _wal_append(rec)
                            if rid is not None:
                                _dedup[rid] = value
                                _trim_dedup()
            obs.observe("dkv_handle_seconds", time.perf_counter() - t0,
                        op=op, side="server",
                        dedup_hit="true" if dedup_hit else "false")
            resp = {"value": value, "epoch": _epoch}
        except Exception as e:          # noqa: BLE001 — reported to client
            resp = {"err": repr(e), "epoch": _epoch}
        payload = pickle.dumps(resp, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            self.request.sendall(struct.pack("<Q", len(payload)) + payload)
        except OSError:
            pass


class _DKVServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    ssl_context: Optional[ssl.SSLContext] = None

    def get_request(self):
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(sock, server_side=True)
        return sock, addr


def serve(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the coordinator DKV service; returns the bound port.

    Each call that actually (re)starts the service is a new incarnation:
    it rehydrates the durable snapshot+WAL (when a local recovery dir is
    configured), takes the next epoch, and stamps it into every
    response."""
    global _server
    if _server is not None:
        if port in (0, _server.server_address[1]):
            return _server.server_address[1]
        # explicit re-serve on a different port: restart the service
        _server.shutdown()
        _server.server_close()            # release the listen socket too
        _server = None
    d = _durable_dir()
    restored, hint = 0, 0
    with _lock:
        _close_wal()
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                restored, hint = _rehydrate(d)
                _wal_open(d)
            except Exception as e:       # noqa: BLE001 — serve regardless
                from .observability import log
                log.warning("DKV durability disabled (%r)", e)
                d = None
        epoch = _bump_epoch(d, hint)
    _server = _DKVServer((host, port), _Handler)
    srv_ctx, _ = _tls_contexts()
    _server.ssl_context = srv_ctx
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="dkv-coordinator")
    t.start()
    from .observability import log, record
    record("coordinator_restart", epoch=epoch, restored_keys=restored,
           durable=bool(d), port=_server.server_address[1])
    log.info("DKV coordinator serving on port %d (epoch %d, durable=%s, "
             "%d keys restored)", _server.server_address[1], epoch,
             bool(d), restored)
    return _server.server_address[1]


def attach(host: str, port: int, timeout: float = 60.0) -> None:
    """Point this process's DKV at the coordinator service (with retry)."""
    global _remote, _client_ssl, _seen_epoch
    _, _client_ssl = _tls_contexts()
    _seen_epoch = 0                      # fencing restarts per attachment
    _remote = (host, port)
    deadline = time.time() + timeout
    while True:
        try:
            _rpc("ping")
            return
        except (ConnectionError, OSError):
            if time.time() > deadline:
                _remote = None
                raise
            time.sleep(0.2)


def detach() -> None:
    global _remote, _server, _client_ssl, _seen_epoch
    _remote = None
    _client_ssl = None    # a later plaintext attach must not reuse stale TLS
    _seen_epoch = 0
    if _server is not None:
        _server.shutdown()
        _server.server_close()            # release the listen socket too
        _server = None
    with _lock:
        _close_wal()
    from .observability import close_log_file
    close_log_file()                      # release the per-node log file
