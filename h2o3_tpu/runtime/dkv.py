"""DKV: the keyed object store for frames, models and jobs.

Reference: ``water/DKV.java:52`` / ``water/Key.java:44`` — a cluster-wide
distributed hash map where every Frame/Vec/Chunk/Model/Job lives under a Key
homed to a node, coherent via invalidates, backed by Cliff Click's
NonBlockingHashMap (water/nbhm/).

TPU-native redesign: bulk payloads (column data) are ``jax.Array``s whose
placement is already expressed by shardings — the JAX runtime is the
"distributed" part.  What remains is the *control-plane* index: a name ->
object map, served over DCN by a small TCP service on the coordinator host
(SURVEY.md §5 two-plane design: XLA collectives on ICI for compute, host
TCP for control; this replaces the reference's UDP/RPC + Paxos).  In the
multi-process SPMD world every process executes the same program, so
device-backed objects (frames, models) exist everywhere by construction;
the coordinator service carries the *metadata* plane — key listings, job
status, small host objects — and gives non-zero processes and external
clients (REST) a consistent view.  The API mirrors DKV.get/put/remove.
"""

from __future__ import annotations

import os
import pickle
import socket
import socketserver
import ssl
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_store: Dict[str, Any] = {}
_lock = threading.RLock()
_counter = 0

# coordinator service state
_remote: Optional[Tuple[str, int]] = None     # set on non-coordinator procs
_server: Optional["_DKVServer"] = None
_client_ssl: Optional[ssl.SSLContext] = None


def _tls_contexts():
    """Optional internode TLS (h2o-security internal_security analog).

    Set H2O3_TPU_TLS_CERT / H2O3_TPU_TLS_KEY (PEM paths) on every process
    to wrap the DCN control plane in TLS; the cert doubles as the trust
    anchor (private-CA / self-signed deployment model, like the
    reference's keystore-based internal security).  Returns
    (server_ctx, client_ctx) or (None, None).
    """
    from .config import config
    cert = config().tls_cert
    key = config().tls_key
    if not cert:
        return None, None
    srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    srv.load_cert_chain(cert, key or None)
    cli = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    cli.check_hostname = False
    cli.load_verify_locations(cert)
    return srv, cli


def _is_plain(value: Any, depth: int = 0) -> bool:
    """True when value is safely picklable host data (no device arrays)."""
    import numpy as np
    if depth > 6:
        return False
    if value is None or isinstance(value, (str, bytes, int, float, bool,
                                           np.generic, np.ndarray)):
        return True
    if isinstance(value, (list, tuple, set)):
        return all(_is_plain(v, depth + 1) for v in value)
    if isinstance(value, dict):
        return all(isinstance(k, str) and _is_plain(v, depth + 1)
                   for k, v in value.items())
    return False


def make_key(prefix: str) -> str:
    """Fresh unique key — analog of Key.make() (water/Key.java:44).

    Always the LOCAL counter, even when attached to a coordinator: SPMD
    processes execute the same program line-for-line, so local counters
    stay in lock-step and every process derives the SAME name for the same
    logical object — a coordinator counter would hand each process a
    different key for one model.
    """
    global _counter
    with _lock:
        _counter += 1
        return f"{prefix}_{_counter}"


def put(key: str, value: Any) -> str:
    with _lock:
        is_new = key not in _store
        _store[key] = value
    if is_new:                           # upserts of pre-existing keys are
        from . import scope              # NOT scope-owned temporaries
        scope.track(key)
    if _remote is not None and _is_plain(value):
        _rpc("put", key=key, value=value)
    return key


def get(key: str) -> Optional[Any]:
    with _lock:
        v = _store.get(key)
    if v is None and _remote is not None:
        v = _rpc("get", key=key)
    return v


def remove(key: str) -> None:
    with _lock:
        _store.pop(key, None)
    if _remote is not None:
        _rpc("remove", key=key)


def keys(prefix: str = "") -> List[str]:
    with _lock:
        local = {k for k in _store if k.startswith(prefix)}
    if _remote is not None:
        local.update(_rpc("keys", prefix=prefix))
    return sorted(local)


def clear() -> None:
    with _lock:
        _store.clear()


def local_size() -> int:
    """Local key count only — no coordinator round trip (heartbeat)."""
    with _lock:
        return len(_store)


# ------------------------------------------------------------- atomic ops
def cas(key: str, expected: Any, new: Any) -> bool:
    """Compare-and-set — the water/Atomic/TAtomic analog for control-plane
    state (grid bookkeeping, counters).  Equality-compared; atomic under
    the store lock locally, executed coordinator-side when attached."""
    if _remote is not None:
        return bool(_rpc("cas", key=key, expected=expected, new=new))
    with _lock:
        if _store.get(key) == expected:
            _store[key] = new
            return True
        return False


def incr(key: str, delta: float = 1.0) -> float:
    """Atomic numeric increment; missing keys start at 0."""
    if _remote is not None:
        return float(_rpc("incr", key=key, delta=delta))
    with _lock:
        v = float(_store.get(key, 0.0)) + delta
        _store[key] = v
        return v


# --------------------------------------------------------------------------
# Coordinator service: length-prefixed pickle RPC over TCP (the control
# plane of SURVEY.md §5 — DCN traffic, never device payloads).
#
# Coherence contract: SPMD processes stay coherent BY CONSTRUCTION (every
# process executes the same put/remove at the same program point); the
# coordinator index is the authoritative view for EXTERNAL readers (REST
# clients, tooling).  There is deliberately no cross-process invalidation
# push — a coordinator-side mutation by an external writer is visible to a
# worker only for keys the worker never stored locally (its get() falls
# through to the coordinator).  This mirrors the reference's stance that
# clients are coordinators of record, not peers (water/DKV.java caching is
# likewise only coherent among cluster members).
# --------------------------------------------------------------------------

def _recvall(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("DKV peer closed connection")
        buf += chunk
    return buf


def _rpc_once(payload: bytes) -> dict:
    """One TCP/TLS round trip to the coordinator (no retry)."""
    with socket.create_connection(_remote, timeout=60) as raw:
        s = _client_ssl.wrap_socket(raw, server_hostname=_remote[0]) \
            if _client_ssl is not None else raw
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        n = struct.unpack("<Q", _recvall(s, 8))[0]
        resp = pickle.loads(_recvall(s, n))
        if s is not raw:
            s.close()
    return resp


def _rpc(op: str, **kw) -> Any:
    """Coordinator RPC with per-op retry: exponential backoff + jitter
    under a retry budget.

    A transient coordinator hiccup (restart, connection reset, listen
    backlog overflow) used to kill the first heartbeat/journal/job RPC
    that hit it — the reference survives these via UDP retransmit; the
    TCP control plane needs explicit retries.  Only transport errors are
    retried; an error REPORTED by the coordinator (``resp["err"]``) is
    authoritative and raises immediately.  Knobs: ``H2O3_TPU_DKV_RETRIES``
    (extra attempts, default 5), ``H2O3_TPU_DKV_BACKOFF_BASE`` /
    ``H2O3_TPU_DKV_BACKOFF_MAX`` (seconds, default 0.05/2.0), and
    ``H2O3_TPU_DKV_RETRY_BUDGET`` (total seconds across one op's
    retries, default 30).
    """
    import random

    from .config import config
    payload = pickle.dumps({"op": op, **kw},
                           protocol=pickle.HIGHEST_PROTOCOL)
    cfg = config()
    deadline = time.time() + cfg.dkv_retry_budget_s
    attempt = 0
    while True:
        try:
            from . import failure
            failure.maybe_inject("dkv_rpc")
            resp = _rpc_once(payload)
            break
        except (ConnectionError, TimeoutError, ssl.SSLError, OSError) as e:
            attempt += 1
            now = time.time()
            if attempt > cfg.dkv_retries or now >= deadline:
                raise
            from .observability import log, record
            sleep = min(cfg.dkv_backoff_base_s * (2 ** (attempt - 1)),
                        cfg.dkv_backoff_max_s)
            sleep *= 0.5 + random.random()          # jitter in [0.5x, 1.5x)
            sleep = min(sleep, max(deadline - now, 0.01))
            record("dkv_retry", op=op, attempt=attempt, error=repr(e))
            log.warning("DKV %s RPC failed (%r); retry %d/%d in %.2fs",
                        op, e, attempt, cfg.dkv_retries, sleep)
            time.sleep(sleep)
    if resp.get("err"):
        raise RuntimeError(f"DKV coordinator error: {resp['err']}")
    return resp.get("value")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        global _counter
        try:
            n = struct.unpack("<Q", _recvall(self.request, 8))[0]
            req = pickle.loads(_recvall(self.request, n))
            op = req["op"]
            if op == "put":
                with _lock:
                    _store[req["key"]] = req["value"]
                value = req["key"]
            elif op == "get":
                with _lock:
                    value = _store.get(req["key"])
            elif op == "remove":
                with _lock:
                    _store.pop(req["key"], None)
                value = None
            elif op == "keys":
                with _lock:
                    value = sorted(k for k in _store
                                   if k.startswith(req["prefix"]))
            elif op == "cas":
                with _lock:
                    if _store.get(req["key"]) == req["expected"]:
                        _store[req["key"]] = req["new"]
                        value = True
                    else:
                        value = False
            elif op == "incr":
                with _lock:
                    value = float(_store.get(req["key"], 0.0)) \
                        + req["delta"]
                    _store[req["key"]] = value
            elif op == "make_key":
                with _lock:
                    _counter += 1
                    value = f"{req['prefix']}_{_counter}"
            elif op == "ping":
                value = "pong"
            else:
                raise ValueError(f"unknown DKV op {op!r}")
            resp = {"value": value}
        except Exception as e:          # noqa: BLE001 — reported to client
            resp = {"err": repr(e)}
        payload = pickle.dumps(resp, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            self.request.sendall(struct.pack("<Q", len(payload)) + payload)
        except OSError:
            pass


class _DKVServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    ssl_context: Optional[ssl.SSLContext] = None

    def get_request(self):
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(sock, server_side=True)
        return sock, addr


def serve(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the coordinator DKV service; returns the bound port."""
    global _server
    if _server is not None:
        if port in (0, _server.server_address[1]):
            return _server.server_address[1]
        # explicit re-serve on a different port: restart the service
        _server.shutdown()
        _server.server_close()            # release the listen socket too
        _server = None
    _server = _DKVServer((host, port), _Handler)
    srv_ctx, _ = _tls_contexts()
    _server.ssl_context = srv_ctx
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="dkv-coordinator")
    t.start()
    return _server.server_address[1]


def attach(host: str, port: int, timeout: float = 60.0) -> None:
    """Point this process's DKV at the coordinator service (with retry)."""
    global _remote, _client_ssl
    _, _client_ssl = _tls_contexts()
    _remote = (host, port)
    deadline = time.time() + timeout
    while True:
        try:
            _rpc("ping")
            return
        except (ConnectionError, OSError):
            if time.time() > deadline:
                _remote = None
                raise
            time.sleep(0.2)


def detach() -> None:
    global _remote, _server
    _remote = None
    if _server is not None:
        _server.shutdown()
        _server.server_close()            # release the listen socket too
        _server = None
