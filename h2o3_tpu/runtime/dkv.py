"""DKV: the keyed object store for frames, models and jobs.

Reference: ``water/DKV.java:52`` / ``water/Key.java:44`` — a cluster-wide
distributed hash map where every Frame/Vec/Chunk/Model/Job lives under a Key
homed to a node, coherent via invalidates, backed by Cliff Click's
NonBlockingHashMap (water/nbhm/).

TPU-native redesign: bulk payloads (column data) are ``jax.Array``s whose
placement is already expressed by shardings — the JAX runtime is the
"distributed" part.  What remains is the *control-plane* index: a name ->
object map on the coordinator host.  Single-process now; the multi-host
version replicates this index over the control-plane channel (SURVEY.md §5:
"DKV stays in TPU-VM host RAM").  The API mirrors DKV.get/put/remove.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_store: Dict[str, Any] = {}
_lock = threading.RLock()
_counter = 0


def make_key(prefix: str) -> str:
    """Fresh unique key — analog of Key.make() (water/Key.java:44)."""
    global _counter
    with _lock:
        _counter += 1
        return f"{prefix}_{_counter}"


def put(key: str, value: Any) -> str:
    with _lock:
        _store[key] = value
    return key


def get(key: str) -> Optional[Any]:
    with _lock:
        return _store.get(key)


def remove(key: str) -> None:
    with _lock:
        _store.pop(key, None)


def keys(prefix: str = "") -> List[str]:
    with _lock:
        return sorted(k for k in _store if k.startswith(prefix))


def clear() -> None:
    with _lock:
        _store.clear()
