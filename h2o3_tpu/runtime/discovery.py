"""Pod-native worker discovery — the k8s headless-service clouding analog.

Reference: ``h2o-k8s/src/main/java/water/k8s/H2OCluster.java`` +
``KubernetesDnsDiscovery``: pods resolve a headless service's DNS A
records until the expected cluster size is seen, then form the cloud
from the discovered addresses.

TPU-native redesign: discovery only needs to produce the THREE values
``jax.distributed.initialize`` wants — coordinator address, process
count, and this process's index — because XLA's runtime handles the
actual rendezvous.  Two modes:

* **Indexed** (preferred on k8s): an Indexed Job / StatefulSet gives each
  pod a stable ordinal (env ``H2O3_TPU_POD_INDEX``, e.g. from the
  ``batch.kubernetes.io/job-completion-index`` annotation) and ordinal-0's
  stable DNS name is the coordinator.  No polling races.
* **DNS-poll**: resolve the headless service's A records until
  ``expected`` addresses are stable, sort them, coordinator = lowest,
  process_id = rank of this pod's own address (H2OCluster's mechanism).
"""

from __future__ import annotations

import os
import socket
import time
from typing import List, Optional, Tuple


def _own_addresses() -> set:
    """Every IP this host answers to (for rank lookup in DNS mode)."""
    out = {"127.0.0.1"}
    try:
        host = socket.gethostname()
        out.add(socket.gethostbyname(host))
        for info in socket.getaddrinfo(host, None, socket.AF_INET):
            out.add(info[4][0])
    except OSError:
        pass
    try:                      # routeable source address (no packet sent)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        out.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    return out


def resolve_service(service: str, expected: Optional[int] = None,
                    timeout_s: float = 300.0,
                    poll_s: float = 2.0) -> List[str]:
    """Poll DNS A records for ``service`` until ``expected`` distinct
    addresses appear and are stable for one extra poll (k8s propagates
    records as pods turn Ready)."""
    deadline = time.monotonic() + timeout_s
    last: List[str] = []
    stable = 0
    while time.monotonic() < deadline:
        try:
            addrs = sorted({info[4][0] for info in socket.getaddrinfo(
                service, None, socket.AF_INET)})
        except OSError:
            addrs = []
        if addrs and (expected is None or len(addrs) >= expected):
            if addrs == last:
                stable += 1
                if stable >= 1:
                    return addrs
            else:
                stable = 0
            last = addrs
        time.sleep(poll_s)
    raise TimeoutError(
        f"discovery: {service!r} resolved {len(last)} addresses "
        f"(expected {expected}) within {timeout_s}s")


def discover(service: str, port: int = 8476,
             expected: Optional[int] = None,
             index_env: str = "H2O3_TPU_POD_INDEX",
             timeout_s: float = 300.0) -> Tuple[str, int, int]:
    """-> (coordinator_address, num_processes, process_id).

    Indexed mode when ``index_env`` is set (coordinator = ordinal 0's
    stable DNS name ``<service-stem>-0.<service>``); DNS-poll mode
    otherwise.  ``expected`` defaults to env ``H2O3_TPU_CLUSTER_SIZE``.
    """
    if expected is None and os.environ.get("H2O3_TPU_CLUSTER_SIZE"):
        expected = int(os.environ["H2O3_TPU_CLUSTER_SIZE"])
    idx = os.environ.get(index_env)
    if idx is not None:
        if expected is None:
            raise ValueError(
                "indexed discovery needs the cluster size "
                "(expected= or H2O3_TPU_CLUSTER_SIZE)")
        # Pod DNS names are <pod-name>.<subdomain>, and Indexed Job /
        # StatefulSet pods are named <workload>-<ordinal> — the workload
        # stem comes from THIS pod's own hostname (strip our ordinal),
        # NOT from the service name (the service is usually named
        # differently, e.g. job "h2o3-tpu" behind service
        # "h2o3-tpu-coordinator").
        stem = os.environ.get("H2O3_TPU_POD_STEM")
        if not stem:
            host = socket.gethostname().split(".", 1)[0]
            suffix = f"-{idx}"
            if not host.endswith(suffix):
                raise RuntimeError(
                    f"indexed discovery: hostname {host!r} does not end "
                    f"with ordinal suffix {suffix!r}; set "
                    "H2O3_TPU_POD_STEM to the workload name")
            stem = host[: -len(suffix)]
        coord = f"{stem}-0.{service}:{port}"
        return coord, expected, int(idx)
    addrs = resolve_service(service, expected=expected,
                            timeout_s=timeout_s)
    own = _own_addresses()
    ranks = [i for i, a in enumerate(addrs) if a in own]
    if not ranks:
        raise RuntimeError(
            f"discovery: none of this host's addresses {sorted(own)} "
            f"appear in {service!r} records {addrs}")
    return f"{addrs[0]}:{port}", len(addrs), ranks[0]


def from_flatfile(path: str, expected: Optional[int] = None,
                  timeout_s: float = 300.0, poll_s: float = 2.0,
                  own_port: Optional[int] = None) -> Tuple[str, int, int]:
    """Assisted clustering: form the cloud from a flatfile of members.

    Reference: ``h2o-clustering`` — an external agent (operator,
    controller) POSTs a flatfile of ``host:port`` lines to each node,
    which then clouds from it (AssistedClusteringEndpoint).  Mesh-at-
    launch analog: the launcher polls ``path`` until ``expected`` member
    lines exist (the agent writes the file), sorts them, and derives the
    same (coordinator, size, rank) triple the DNS modes produce —
    rank = position of one of this host's own addresses.
    """
    if expected is None and os.environ.get("H2O3_TPU_CLUSTER_SIZE"):
        expected = int(os.environ["H2O3_TPU_CLUSTER_SIZE"])
    deadline = time.monotonic() + timeout_s
    members: List[str] = []
    prev: Optional[List[str]] = None
    while time.monotonic() < deadline:
        try:
            with open(path) as fh:
                members = sorted({ln.strip() for ln in fh
                                  if ln.strip()
                                  and not ln.lstrip().startswith("#")})
        except OSError:
            members = []
        if members and (expected is None or len(members) >= expected):
            if members == prev:
                break           # stable across two polls: the agent's
            prev = members      # write may be mid-flight (non-atomic)
        else:
            prev = None
        time.sleep(poll_s)
    else:
        raise TimeoutError(
            f"flatfile {path!r} has {len(members)} members "
            f"(expected {expected}) after {timeout_s}s")
    own = _own_addresses() | {socket.gethostname(),
                              socket.gethostname().split(".", 1)[0]}
    ranks = [i for i, m in enumerate(members)
             if m.rsplit(":", 1)[0] in own]
    if not ranks:
        raise RuntimeError(
            f"flatfile {path!r}: none of this host's addresses "
            f"{sorted(own)} appear in {members}")
    if len(ranks) > 1:
        # several members on this host (multi-process-per-host layout):
        # this process's member line is the one carrying its own port
        if own_port is None:
            raise RuntimeError(
                f"flatfile {path!r} lists {len(ranks)} members on this "
                "host; pass own_port to disambiguate the rank")
        ranks = [i for i in ranks
                 if ":" in members[i]
                 and members[i].rsplit(":", 1)[1] == str(own_port)]
        if len(ranks) != 1:
            raise RuntimeError(
                f"flatfile {path!r}: port {own_port} matches "
                f"{len(ranks)} members on this host")
    return members[0], len(members), ranks[0]


def init_from_discovery(service: str, port: int = 8476,
                        expected: Optional[int] = None,
                        model_axis: int = 1, **kw):
    """One-call pod boot: discover, then ``cluster.init`` multi-host."""
    from .cluster import init
    coord, n, pid = discover(service, port=port, expected=expected, **kw)
    return init(coordinator=coord, num_processes=n, process_id=pid,
                model_axis=model_axis)
