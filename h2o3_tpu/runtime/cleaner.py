"""Cleaner — HBM-pressure eviction of cold frames to host RAM.

Reference: ``water/Cleaner.java`` sweeps the K/V store and writes cold
chunks to disk when the memory manager signals pressure.  Here the
scarce tier is HBM: when a new placement would blow the guardrail
(cluster._check_hbm_budget), ``spill_until`` evicts whole frames —
least-recently-used first, by Frame._atime — to host numpy until enough
HBM is projected free.  Spilled frames restore transparently on the
next ``.data`` access (frame/vec.py).
"""

from __future__ import annotations

from typing import Iterable


def spill_until(needed: int, exclude: Iterable[str] = ()) -> int:
    """Evict LRU frames until ~``needed`` bytes are freed; returns freed.

    Best-effort: freed bytes are the arrays' nbytes, a proxy for the
    allocator's view; the guardrail re-checks real memory_stats after.
    """
    from . import dkv
    from .observability import log, record
    from ..frame.frame import Frame
    skip = set(exclude)
    frames = []
    for key in dkv.keys():
        if key in skip:
            continue
        v = dkv.get(key)
        if isinstance(v, Frame) and any(vec._device is not None
                                        for vec in v.vecs):
            atime = max([getattr(v, "_atime", 0.0)] +
                        [vec._atime for vec in v.vecs])
            frames.append((atime, key, v))
    freed = 0
    for _, key, fr in sorted(frames, key=lambda t: t[0]):
        if freed >= needed:
            break
        got = fr.spill()
        freed += got
        log.info("cleaner: spilled frame %s (%.1f MB) to host RAM",
                 key, got / 1e6)
        record("spill", frame=key, bytes=got)
    return freed
