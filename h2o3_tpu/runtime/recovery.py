"""Generic job resurrection — training-job journal + resume.

Reference: h2o's auto-recovery (AutoML's recovery dir generalized):
interrupted training should be re-runnable after a cluster restart.
When ``H2O3_TPU_RECOVERY_DIR`` is set (any persist URI), every
ModelBuilder.train writes a journal entry (algo, params, frame key)
before fitting and marks it done after; ``resume()`` re-trains every
entry still marked running, provided its training frame has been
re-imported under the same key (the reference's contract too — data is
not journaled, only the work description).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional


def _dir() -> Optional[str]:
    return os.environ.get("H2O3_TPU_RECOVERY_DIR") or None


def _entry_uri(base: str, job_key: str) -> str:
    return f"{base.rstrip('/')}/job_{job_key}.json"


def _write_entry(uri: str, entry: dict) -> None:
    from .. import persist
    with persist.open_write(uri) as f:
        f.write(json.dumps(entry).encode())


def journal_start(builder, frame, job=None, params=None) -> Optional[str]:
    """Record a training job about to run; returns the entry URI."""
    base = _dir()
    if not base:
        return None
    from .observability import log
    # only JSON-clean params are journaled: a repr-stringified callable
    # or array would resume into a silently broken builder
    params, skipped = {}, []
    for k, v in dataclasses.asdict(params or builder.params).items():
        if hasattr(v, "item"):
            v = v.item()
        try:
            json.dumps(v)
            params[k] = v
        except TypeError:
            skipped.append(k)
    entry = {
        "algo": type(builder).__name__,
        "params": params,
        "skipped_params": skipped,
        "frame_key": getattr(frame, "key", None),
        # import provenance: lets resume() re-import the data itself
        # after a coordinator restart (frames are not journaled, their
        # source URIs are — Recovery.java:72-81 contract, automated)
        "frame_source": getattr(frame, "source_uri", None),
        "status": "running",
    }
    job = job or builder.job
    uri = _entry_uri(base, job.key if job else "unkeyed")
    try:
        _write_entry(uri, entry)
        if skipped:
            log.warning("recovery journal for %s skips non-serializable "
                        "params %s", entry["algo"], skipped)
        return uri
    except Exception as e:                     # noqa: BLE001 — best-effort
        log.warning("recovery journal write failed: %r", e)
        return None


def journal_done(uri: Optional[str]) -> None:
    """Mark a journal entry finished (entry removed — job completed)."""
    if not uri:
        return
    from .. import persist
    try:
        persist.delete(uri)
    except Exception:                          # noqa: BLE001
        pass


def journal_fail(uri: Optional[str], error: str) -> None:
    """Re-mark an entry failed: cancelled or deterministically failing
    jobs must NOT be resurrected — only process-death leaves 'running'."""
    if not uri:
        return
    from .. import persist
    try:
        with persist.open_read(uri) as f:
            entry = json.loads(f.read().decode())
        entry["status"] = "failed"
        entry["error"] = error[:500]
        _write_entry(uri, entry)
    except Exception:                          # noqa: BLE001
        pass


def resume(recovery_dir: Optional[str] = None) -> List[str]:
    """Re-train every journaled job still marked running.

    The training frame must already be back in the DKV under its
    original key (re-import with the same destination_frame).  Returns
    the keys of the models produced; entries whose frame is missing are
    left in the journal and reported via the log.
    """
    from .. import persist
    from . import dkv
    from .observability import log
    base = recovery_dir or _dir()
    if not base:
        return []
    import h2o3_tpu.models as models
    done: List[str] = []
    for uri in persist.list_uris(f"{base.rstrip('/')}/job_*.json"):
        try:
            with persist.open_read(uri) as f:
                entry = json.loads(f.read().decode())
        except Exception as e:                 # noqa: BLE001
            log.warning("recovery: unreadable journal entry %s: %r", uri, e)
            continue
        if entry.get("status") != "running":
            continue
        frame = dkv.get(entry.get("frame_key") or "")
        if frame is None and entry.get("frame_source"):
            # automated re-import from the journaled source URI
            from ..frame.parse import import_file
            try:
                frame = import_file(entry["frame_source"],
                                    destination_frame=entry["frame_key"])
                log.info("recovery: re-imported %r from %r",
                         entry.get("frame_key"), entry["frame_source"])
            except Exception as e:             # noqa: BLE001
                log.warning("recovery: re-import of %r failed: %r",
                            entry.get("frame_source"), e)
        if frame is None:
            log.warning("recovery: frame %r not re-imported; skipping %s",
                        entry.get("frame_key"), uri)
            continue
        cls = getattr(models, entry["algo"], None)
        if cls is None:
            log.warning("recovery: unknown algo %r in %s",
                        entry["algo"], uri)
            continue
        params = {k: v for k, v in entry["params"].items()
                  if v is not None}
        try:
            model = cls(**params).train(frame)
        except Exception as e:                 # noqa: BLE001
            log.warning("recovery: resumed %s failed (%r); marking "
                        "failed", uri, e)
            journal_fail(uri, repr(e))
            continue
        done.append(model.key)
        persist.delete(uri)
    return done
