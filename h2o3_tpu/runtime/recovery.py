"""Generic job resurrection — training-job journal + resume.

Reference: h2o's auto-recovery (AutoML's recovery dir generalized):
interrupted training should be re-runnable after a cluster restart.
When ``H2O3_TPU_RECOVERY_DIR`` is set (any persist URI), every
ModelBuilder.train writes a journal entry (algo, params, frame key)
before fitting and marks it done after; ``resume()`` re-trains every
entry still marked running.  The reference's contract is that data is
not journaled, only the work description; here the shard-lineage layer
(frame/lineage.py + runtime/remat.py) goes further: a missing training
frame is first re-materialized from its lineage record — lost shards
only, replica copy → ranged re-parse → op replay — and only when no
lineage can prove a correct rebuild does ``resume_entry`` fall back to
a full re-import of the journaled source URI.

Beyond the reference: long-running builders also persist in-training
progress snapshots (runtime/snapshot.py) and the journal entry tracks
the latest one (``snapshot_uri`` + ``snapshot_cursor``).  ``resume()``
reloads the snapshot and continues through the builder's ``checkpoint``
continuation machinery instead of re-training from zero — an
interrupted 500-tree GBM restarts from the last snapshotted tree, with
rework bounded by the snapshot cadence.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional


def _dir() -> Optional[str]:
    return os.environ.get("H2O3_TPU_RECOVERY_DIR") or None


def recovery_dir() -> Optional[str]:
    """The configured recovery base URI (journal, snapshots, and — for
    local paths — the coordinator's DKV write-ahead log under dkv/)."""
    return _dir()


def _entry_uri(base: str, job_key: str) -> str:
    return f"{base.rstrip('/')}/job_{job_key}.json"


def _write_entry(uri: str, entry: dict) -> None:
    from .. import persist
    with persist.open_write(uri) as f:
        f.write(json.dumps(entry).encode())


def _read_entry(uri: str) -> dict:
    from .. import persist
    with persist.open_read(uri) as f:
        return json.loads(f.read().decode())


def journal_start(builder, frame, job=None, params=None) -> Optional[str]:
    """Record a training job about to run; returns the entry URI."""
    base = _dir()
    if not base:
        return None
    from .observability import log
    # only JSON-clean params are journaled: a repr-stringified callable
    # or array would resume into a silently broken builder
    jparams, skipped = {}, []
    for k, v in dataclasses.asdict(
            params if params is not None else builder.params).items():
        if hasattr(v, "item"):
            v = v.item()
        try:
            json.dumps(v)
            jparams[k] = v
        except TypeError:
            skipped.append(k)
    entry = {
        "algo": type(builder).__name__,
        "params": jparams,
        "skipped_params": skipped,
        "frame_key": getattr(frame, "key", None),
        # import provenance: lets resume() re-import the data itself
        # after a coordinator restart (frames are not journaled, their
        # source URIs are — Recovery.java:72-81 contract, automated)
        "frame_source": getattr(frame, "source_uri", None),
        "status": "running",
    }
    job = job or builder.job
    if job is not None:
        # job identity lets scheduler.readmit() re-join the entry with
        # its WAL-persisted !sched/ scheduling record after a restart
        entry["job"] = job.key
        entry["dest_key"] = job.dest_key
    uri = _entry_uri(base, job.key if job else "unkeyed")
    try:
        _write_entry(uri, entry)
        if skipped:
            log.warning("recovery journal for %s skips non-serializable "
                        "params %s", entry["algo"], skipped)
        return uri
    except Exception as e:                     # noqa: BLE001 — best-effort
        log.warning("recovery journal write failed: %r", e)
        return None


def journal_done(uri: Optional[str]) -> None:
    """Mark a journal entry finished (entry removed — job completed).
    Its progress snapshot, now superseded by the real model, goes too."""
    if not uri:
        return
    from .. import persist
    try:
        snap = _read_entry(uri).get("snapshot_uri")
        if snap:
            persist.delete(snap)
    except Exception:                          # noqa: BLE001
        pass
    try:
        persist.delete(uri)
    except Exception:                          # noqa: BLE001
        pass


def journal_fail(uri: Optional[str], error: str) -> None:
    """Re-mark an entry failed: cancelled or deterministically failing
    jobs must NOT be resurrected — only process-death leaves 'running'."""
    if not uri:
        return
    try:
        entry = _read_entry(uri)
        entry["status"] = "failed"
        entry["error"] = error[:500]
        _write_entry(uri, entry)
    except Exception:                          # noqa: BLE001
        pass


def journal_update_snapshot(uri: Optional[str], snapshot_uri: Optional[str],
                            cursor: dict) -> Optional[str]:
    """Point a journal entry at its latest progress snapshot (called by
    the snapshot writer; ``snapshot_uri=None`` records a cursor-only
    progress update).  Returns the PREVIOUS snapshot uri so the caller
    can delete it once the journal references the new one."""
    if not uri:
        return None
    import time
    try:
        entry = _read_entry(uri)
        prev = entry.get("snapshot_uri")
        if snapshot_uri is not None:
            entry["snapshot_uri"] = snapshot_uri
        entry["snapshot_cursor"] = cursor
        entry["snapshot_ts"] = time.time()
        _write_entry(uri, entry)
        return prev
    except Exception:                          # noqa: BLE001 — best-effort
        return None


def journal_status(recovery_dir: Optional[str] = None) -> List[dict]:
    """Journal + snapshot state for every entry — the ``/3/Recovery``
    status view (entries in 'running' state are resumable)."""
    from .. import persist
    base = recovery_dir or _dir()
    if not base:
        return []
    out = []
    for uri in persist.list_uris(f"{base.rstrip('/')}/job_*.json"):
        try:
            entry = _read_entry(uri)
        except Exception as e:                 # noqa: BLE001
            out.append({"entry_uri": uri, "error": repr(e)})
            continue
        out.append({
            "entry_uri": uri,
            "algo": entry.get("algo"),
            "status": entry.get("status"),
            "frame_key": entry.get("frame_key"),
            "frame_source": entry.get("frame_source"),
            "snapshot_uri": entry.get("snapshot_uri"),
            "snapshot_cursor": entry.get("snapshot_cursor"),
            "snapshot_ts": entry.get("snapshot_ts"),
            "error": entry.get("error"),
            "downgrade": entry.get("downgrade"),
        })
    return out


def _load_snapshot_prior(entry: dict, uri: str):
    """Best-effort snapshot reload for one journal entry: returns the
    prior Model (DKV-registered) or None, never raises."""
    from .observability import log
    snap = entry.get("snapshot_uri")
    if not snap:
        return None
    try:
        from .snapshot import load_model
        prior = load_model(snap)
        log.info("recovery: resuming %s from snapshot %s (cursor=%s)",
                 entry.get("algo"), snap, entry.get("snapshot_cursor"))
        return prior
    except Exception as e:                     # noqa: BLE001
        log.warning("recovery: snapshot %s unusable (%r); %s restarts "
                    "from scratch", snap, e, uri)
        return None


def journal_entries(recovery_dir: Optional[str] = None) -> List[tuple]:
    """Readable journal entries as ``(uri, entry)`` pairs."""
    from .. import persist
    from .observability import log
    base = recovery_dir or _dir()
    if not base:
        return []
    out: List[tuple] = []
    for uri in persist.list_uris(f"{base.rstrip('/')}/job_*.json"):
        try:
            out.append((uri, _read_entry(uri)))
        except Exception as e:                 # noqa: BLE001
            log.warning("recovery: unreadable journal entry %s: %r", uri, e)
    return out


def resume_entry(uri: str, entry: Optional[dict] = None, job=None):
    """Resume ONE journal entry; returns the retrained Model.

    Returns None when the entry is not resumable (already finished,
    frame not re-importable, unknown algo) — unless ``job`` is given, in
    which case those conditions raise so the carrying job fails loudly.
    Training errors always propagate; the caller decides between
    ``journal_fail`` (deterministic failure) and another retry.

    With ``job`` the SAME Job object carries the retrained run — the
    scheduler's degraded-mode requeue and post-restart ``readmit()``
    paths use this so callers blocked in ``job.join()`` still receive
    the model.  The builder's own driver runs under that job, so journal
    bookkeeping, snapshots and a possible second resume keep working.
    """
    from .. import persist
    from . import dkv, failure, remat
    from .observability import inc, log, record
    import h2o3_tpu.models as models
    if entry is None:
        entry = _read_entry(uri)
    if entry.get("status") != "running":
        return None
    fkey = entry.get("frame_key") or ""
    frame = dkv.get(fkey)
    if frame is not None and fkey and failure.any_dead():
        # degraded-mode requeue: the frame object survived but a dead
        # host's shards did not — lineage repairs only those (the frame
        # stays usable as the copy source for survivor shards)
        try:
            repaired = remat.repair(fkey, remat.lost_host_indices())
            if repaired is not None:
                frame = repaired
        except remat.RematError as e:
            log.warning("recovery: shard repair of %r failed (%r); "
                        "falling back to full re-import", fkey, e)
            record("remat_fallback", frame=fkey, error=repr(e)[:200])
            frame = None
    if frame is None and fkey:
        # lineage-first rebuild: the only automated path for derived
        # frames (their journaled frame_source is None)
        try:
            frame = remat.repair(fkey)
            if frame is not None:
                log.info("recovery: re-materialized %r from lineage", fkey)
        except remat.RematError as e:
            log.warning("recovery: lineage rebuild of %r failed (%r); "
                        "falling back to source re-import", fkey, e)
            record("remat_fallback", frame=fkey, error=repr(e)[:200])
            frame = None
    if frame is None and entry.get("frame_source"):
        # automated re-import from the journaled source URI
        from ..frame.parse import import_file
        try:
            frame = import_file(entry["frame_source"],
                                destination_frame=entry["frame_key"])
            log.info("recovery: re-imported %r from %r",
                     entry.get("frame_key"), entry["frame_source"])
        except Exception as e:                 # noqa: BLE001
            log.warning("recovery: re-import of %r failed: %r",
                        entry.get("frame_source"), e)
            # surface the downgrade: this resume is about to be skipped
            # (or fail loudly under a job) — operators must see it
            import time as _time
            inc("recovery_reimport_failed_total")
            record("recovery_reimport_failed", entry=uri,
                   frame=entry.get("frame_key"),
                   source=entry.get("frame_source"), error=repr(e)[:200])
            entry["downgrade"] = {"reimport_failed": True,
                                  "error": repr(e)[:200],
                                  "ts": _time.time()}
            try:
                _write_entry(uri, entry)
            except Exception:                  # noqa: BLE001
                pass
    if frame is None:
        log.warning("recovery: frame %r not re-imported; skipping %s",
                    entry.get("frame_key"), uri)
        if job is not None:
            raise RuntimeError(
                f"recovery: frame {entry.get('frame_key')!r} not "
                f"available for {uri}")
        return None
    cls = getattr(models, entry["algo"], None)
    if cls is None:
        log.warning("recovery: unknown algo %r in %s", entry["algo"], uri)
        if job is not None:
            raise RuntimeError(
                f"recovery: unknown algo {entry['algo']!r} in {uri}")
        return None
    params = {k: v for k, v in entry["params"].items()
              if v is not None}
    prior = _load_snapshot_prior(entry, uri)
    cursor = entry.get("snapshot_cursor") or {}
    if prior is None and params.get("checkpoint") \
            and dkv.get(params["checkpoint"]) is None:
        # a resumed run that died again before its first snapshot
        # journaled a checkpoint key that no longer resolves —
        # fall back to a from-scratch retrain instead of failing
        log.warning("recovery: journaled checkpoint %r not in DKV; "
                    "%s restarts from scratch",
                    params["checkpoint"], uri)
        params.pop("checkpoint")
    if prior is not None:
        params["checkpoint"] = prior.key
        # builder-specific continuation adjustments journaled with
        # the cursor (e.g. deeplearning's remaining epochs)
        for k, v in (cursor.get("resume_params") or {}).items():
            params[k] = v
        record("resume_from_snapshot", entry=uri,
               snapshot=entry.get("snapshot_uri"), cursor=cursor)
    builder = cls(**params)
    if job is None:
        model = builder.train(frame)
    else:
        builder._validate(frame)
        di = builder._make_datainfo(frame)
        builder.job = job
        if not job.dest_key:
            job.dest_key = dkv.make_key(builder.algo)
        model = builder._make_driver(frame, di, None)(job)
    if prior is not None:
        model.output["resumed_from_snapshot"] = {
            "snapshot_uri": entry.get("snapshot_uri"),
            "cursor": cursor}
        try:
            dkv.remove(prior.key)
            persist.delete(entry["snapshot_uri"])
        except Exception:                      # noqa: BLE001
            pass
    try:
        # the retrained run journaled (and cleaned up) under its own job
        # key; the original entry is superseded either way
        persist.delete(uri)
    except Exception:                          # noqa: BLE001
        pass
    return model


def resume(recovery_dir: Optional[str] = None) -> List[str]:
    """Re-train every journaled job still marked running.

    The training frame must already be back in the DKV under its
    original key (re-import with the same destination_frame) — or carry
    a journaled ``frame_source``, which is re-imported automatically.
    Entries with a progress snapshot continue from it via the builder's
    ``checkpoint`` machinery.  Returns the keys of the models produced;
    entries whose frame is missing are left in the journal and reported
    via the log.
    """
    from .observability import log
    base = recovery_dir or _dir()
    if not base:
        return []
    done: List[str] = []
    for uri, entry in journal_entries(base):
        if entry.get("status") != "running":
            continue
        try:
            model = resume_entry(uri, entry=entry)
        except Exception as e:                 # noqa: BLE001
            log.warning("recovery: resumed %s failed (%r); marking "
                        "failed", uri, e)
            journal_fail(uri, repr(e))
            continue
        if model is not None:
            done.append(model.key)
    return done
