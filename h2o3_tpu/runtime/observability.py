"""Observability: logging facade, event timeline, and profiling hooks.

Reference: ``water/TimeLine.java:22`` (per-node ring buffer of runtime
events, surfaced by ``water/api/TimelineHandler.java:12``), ``water/util/
Log.java`` (logging facade with per-node files), and the MRProfile timings.

TPU redesign: a process-local ring buffer of (ts, kind, fields) events
covers the coordinator control plane (jobs, parses, scoring, rapids);
device-side profiling delegates to ``jax.profiler`` traces, which capture
the XLA/TPU timeline far better than any hand-rolled counter could.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional

_LOG_RING = collections.deque(maxlen=2000)
_EVENTS = collections.deque(maxlen=2000)
_lock = threading.Lock()


class _RingHandler(logging.Handler):
    def emit(self, record):
        with _lock:
            _LOG_RING.append(self.format(record))


log = logging.getLogger("h2o3_tpu")
if not log.handlers:
    _h = _RingHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    log.addHandler(_h)
    if os.environ.get("H2O3_TPU_LOG_STDERR"):
        log.addHandler(logging.StreamHandler())
    from .config import config
    log.setLevel(config().log_level)


def record(kind: str, **fields) -> None:
    """Append a timeline event (water.TimeLine.record analog)."""
    with _lock:
        _EVENTS.append({"ts": time.time(), "kind": kind, **fields})


_COUNTERS: collections.Counter = collections.Counter()


def count(name: str, delta: int = 1) -> None:
    """Bump a monotonic named counter.

    For high-rate stats (DKV WAL records/bytes, dedup hits) that would
    churn the timeline ring if each were an event; surfaced alongside
    the ring on /3/Timeline."""
    with _lock:
        _COUNTERS[name] += delta


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_COUNTERS)


def timeline_events(limit: int = 500) -> List[Dict]:
    with _lock:
        return list(_EVENTS)[-limit:]


def recent_logs(limit: int = 500) -> List[str]:
    with _lock:
        return list(_LOG_RING)[-limit:]


@contextlib.contextmanager
def span(kind: str, **fields):
    """Timed event: records start/duration — the MRProfile analog for
    coordinator-side phases."""
    t0 = time.time()
    try:
        yield
    finally:
        record(kind, duration_s=round(time.time() - t0, 4), **fields)


def start_device_trace(logdir: str) -> None:
    """Begin a jax.profiler trace (TensorBoard-viewable device timeline)."""
    import jax
    jax.profiler.start_trace(logdir)
    record("profiler_start", logdir=logdir)


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()
    record("profiler_stop")


def jstack() -> List[Dict]:
    """All-thread stack dump — water/api/JStackHandler (water.util.JStack)
    rendered for a Python runtime: one traceback per live thread."""
    import sys
    import threading
    import traceback
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        t = by_id.get(tid)
        out.append({
            "thread_id": tid,
            "name": t.name if t else f"thread-{tid}",
            "daemon": bool(t.daemon) if t else None,
            "traces": traceback.format_stack(frame),
        })
    return out


def network_test(sizes=(1_024, 1_048_576, 16_777_216)) -> List[Dict]:
    """Collective-bandwidth micro-bench — water/api/NetworkTestHandler.

    The reference times point-to-point UDP/TCP between cloud members;
    the mesh analog is an all-reduce (psum) across every device at a few
    payload sizes, which is exactly the traffic training generates.
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:                   # jax<0.5: experimental namespace
        from jax.experimental.shard_map import shard_map
    from .cluster import cluster, ROW_AXIS

    cl = cluster()
    rows = cl.mesh.shape[ROW_AXIS]
    results = []
    for size in sizes:
        n = max(size // 4, rows)
        n = (n // rows) * rows
        x = jnp.ones((n,), jnp.float32)

        def allred(v):
            return jax.lax.psum(v, ROW_AXIS)

        f = jax.jit(shard_map(allred, mesh=cl.mesh,
                              in_specs=P(ROW_AXIS), out_specs=P()))
        np_out = f(x)
        _ = float(np_out[0])                  # warmup + compile sync
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            out = f(x)
        _ = float(out[0])                     # fetch = sync point
        dt = (time.perf_counter() - t0) / reps
        results.append({
            "bytes": int(n * 4),
            "collective": "psum",
            "seconds": dt,
            "gbytes_per_sec": (n * 4 / max(dt, 1e-12)) / 1e9,
        })
    return results
