"""Observability: logging facade, event timeline, and profiling hooks.

Reference: ``water/TimeLine.java:22`` (per-node ring buffer of runtime
events, surfaced by ``water/api/TimelineHandler.java:12``), ``water/util/
Log.java`` (logging facade with per-node files), and the MRProfile timings.

TPU redesign: a process-local ring buffer of (ts, kind, fields) events
covers the coordinator control plane (jobs, parses, scoring, rapids);
device-side profiling delegates to ``jax.profiler`` traces, which capture
the XLA/TPU timeline far better than any hand-rolled counter could.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional

_LOG_RING = collections.deque(maxlen=2000)
_EVENTS = collections.deque(maxlen=2000)
_lock = threading.Lock()


class _RingHandler(logging.Handler):
    def emit(self, record):
        with _lock:
            _LOG_RING.append(self.format(record))


log = logging.getLogger("h2o3_tpu")
if not log.handlers:
    _h = _RingHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    log.addHandler(_h)
    if os.environ.get("H2O3_TPU_LOG_STDERR"):
        log.addHandler(logging.StreamHandler())
    from .config import config
    log.setLevel(config().log_level)


def record(kind: str, **fields) -> None:
    """Append a timeline event (water.TimeLine.record analog)."""
    with _lock:
        _EVENTS.append({"ts": time.time(), "kind": kind, **fields})


def timeline_events(limit: int = 500) -> List[Dict]:
    with _lock:
        return list(_EVENTS)[-limit:]


def recent_logs(limit: int = 500) -> List[str]:
    with _lock:
        return list(_LOG_RING)[-limit:]


@contextlib.contextmanager
def span(kind: str, **fields):
    """Timed event: records start/duration — the MRProfile analog for
    coordinator-side phases."""
    t0 = time.time()
    try:
        yield
    finally:
        record(kind, duration_s=round(time.time() - t0, 4), **fields)


def start_device_trace(logdir: str) -> None:
    """Begin a jax.profiler trace (TensorBoard-viewable device timeline)."""
    import jax
    jax.profiler.start_trace(logdir)
    record("profiler_start", logdir=logdir)


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()
    record("profiler_stop")
