"""Observability: cluster telemetry plane — metrics, traces, logs, events.

Reference: ``water/TimeLine.java:22`` (per-node ring buffer of runtime
events, surfaced by ``water/api/TimelineHandler.java:12``), ``water/util/
Log.java`` (logging facade with per-node files), the MRProfile timings,
and ``WaterMeterCpuTicksHandler`` (per-node metering).

TPU redesign, four planes in one module:

* **events** — a process-local ring of (ts, kind, fields) dicts covering
  the control plane; ``span()`` wraps a timed unit of work and records
  failures (``ok``/``error``) instead of swallowing them.
* **metrics** — a registry of monotonic counters, gauges, and fixed-
  bucket latency histograms keyed by ``(name, labels)``.  Histogram
  buckets are log-spaced and IDENTICAL in every process, so per-node
  snapshots merge by plain summation.  ``metrics_wire()`` serializes the
  registry onto the heartbeat stamp; the coordinator's ``/metrics``
  route merges every node's snapshot into one Prometheus exposition.
* **traces** — hierarchical spans with ``trace_id``/``span_id``/parent
  that ride the DKV RPC envelope (``current_trace()`` on the client,
  ``trace_context()`` on the handler), stitching coordinator phases,
  worker work, and DKV calls into one tree (``trace_forest()``).
* **device** — delegates to ``jax.profiler`` traces, which capture the
  XLA/TPU timeline far better than any hand-rolled counter could; the
  host-side spans here time dispatch, never device execution.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

_LOG_RING = collections.deque(maxlen=2000)
_EVENTS = collections.deque(maxlen=2000)
_lock = threading.Lock()

# master switch (H2O3_TPU_METRICS / config().metrics_enabled): the
# instrumentation fast-path — span()/observe()/inc()/set_gauge() return
# immediately when off, which is what bench_pieces.py obs measures
_enabled = True


def set_enabled(on: bool) -> bool:
    """Flip the telemetry master switch; returns the previous state."""
    global _enabled
    prev, _enabled = _enabled, bool(on)
    return prev


def enabled() -> bool:
    return _enabled


def node_name() -> str:
    """This process's telemetry identity — same formula as heartbeat's."""
    import socket
    return f"{socket.gethostname()}:{os.getpid()}"


# ------------------------------------------------------------------ logging

class _RingHandler(logging.Handler):
    def emit(self, record):
        with _lock:
            _LOG_RING.append(self.format(record))


_LOG_FORMAT = logging.Formatter(
    "%(asctime)s %(levelname)s %(name)s: %(message)s")
_file_handler: Optional[logging.FileHandler] = None

log = logging.getLogger("h2o3_tpu")
if not log.handlers:
    _h = _RingHandler()
    _h.setFormatter(_LOG_FORMAT)
    log.addHandler(_h)
    if os.environ.get("H2O3_TPU_LOG_STDERR"):
        log.addHandler(logging.StreamHandler())
    from .config import config
    log.setLevel(config().log_level)


def open_log_file(path: Optional[str] = None) -> Optional[str]:
    """Attach the per-node log-file handler (water/util/Log.java analog).

    ``path`` defaults to ``H2O3_TPU_LOG_FILE``; ``%h``/``%p`` expand to
    hostname/pid so every member of a multi-process cloud gets its own
    file from one shared env value.  Re-opening replaces the previous
    handler; returns the resolved path (None when unconfigured)."""
    global _file_handler
    if path is None:
        from .config import config
        path = config().log_file
    if not path:
        return None
    import socket
    path = path.replace("%h", socket.gethostname()) \
               .replace("%p", str(os.getpid()))
    close_log_file()
    h = logging.FileHandler(path)
    h.setFormatter(_LOG_FORMAT)
    log.addHandler(h)
    _file_handler = h
    return path


def close_log_file() -> None:
    """Detach + close the log-file handler (dkv.detach / shutdown)."""
    global _file_handler
    if _file_handler is not None:
        log.removeHandler(_file_handler)
        try:
            _file_handler.close()
        except Exception:                # noqa: BLE001
            pass
        _file_handler = None


if os.environ.get("H2O3_TPU_LOG_FILE"):
    open_log_file()


def apply_config(cfg) -> None:
    """Re-apply config-driven telemetry state (config.reload)."""
    global _enabled
    log.setLevel(cfg.log_level)
    _enabled = bool(cfg.metrics_enabled)
    if cfg.log_file:
        open_log_file(cfg.log_file)
    else:
        close_log_file()


# ------------------------------------------------------------------- events

def record(kind: str, **fields) -> None:
    """Append a timeline event (water.TimeLine.record analog)."""
    with _lock:
        _EVENTS.append({"ts": time.time(), "kind": kind, **fields})


def timeline_events(limit: int = 500) -> List[Dict]:
    with _lock:
        return list(_EVENTS)[-int(limit):]


def recent_logs(limit: int = 500) -> List[str]:
    with _lock:
        return list(_LOG_RING)[-int(limit):]


def events_wire(limit: int = 200) -> List[Dict]:
    """Bounded event tail for the heartbeat stamp — per-node /3/Timeline
    sections and cross-process trace stitching read these back."""
    return timeline_events(limit)


# ------------------------------------------------------------------ metrics
#
# Registry keyed by (name, sorted (label, value) tuple).  All three types
# are cluster-mergeable: counters and histogram buckets by summation,
# gauges by last-writer (each node's gauge is a distinct labeled series).

# log-spaced latency buckets (seconds), ~100 us .. 500 s.  FIXED: every
# process shares the same edges, so shipped histograms merge by summing
# the bucket counts — never change these without a wire-format bump.
LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    round(f * 10.0 ** e, 10)
    for e in range(-4, 3) for f in (1.0, 2.5, 5.0))

_LabelKey = Tuple[Tuple[str, str], ...]
_REGISTRY: "collections.OrderedDict[Tuple[str, _LabelKey], Any]" = \
    collections.OrderedDict()


class Counter:
    """Monotonic counter."""
    kind = "counter"

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: _LabelKey):
        self.name, self.labels, self.value = name, labels, 0.0

    def inc(self, delta: float = 1.0) -> None:
        with _lock:
            self.value += delta

    def wire(self) -> dict:
        return {"n": self.name, "l": dict(self.labels), "t": "c",
                "v": self.value}


class Gauge:
    """Last-value (or high-watermark) gauge."""
    kind = "gauge"

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: _LabelKey):
        self.name, self.labels, self.value = name, labels, 0.0

    def set(self, value: float) -> None:
        with _lock:
            self.value = float(value)

    def set_max(self, value: float) -> None:
        """Watermark semantics: keep the max ever seen."""
        with _lock:
            self.value = max(self.value, float(value))

    def wire(self) -> dict:
        return {"n": self.name, "l": dict(self.labels), "t": "g",
                "v": self.value}


class Histogram:
    """Fixed-bucket latency histogram, mergeable by summation.

    ``counts[i]`` counts observations <= ``buckets[i]``; the final slot
    is the +Inf overflow.  Cumulative conversion happens only at render
    time (Prometheus ``le`` buckets are cumulative)."""
    kind = "histogram"

    __slots__ = ("name", "labels", "buckets", "counts", "sum", "count")

    def __init__(self, name: str, labels: _LabelKey,
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS):
        self.name, self.labels = name, labels
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        import bisect
        i = bisect.bisect_left(self.buckets, value)
        with _lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def wire(self) -> dict:
        return {"n": self.name, "l": dict(self.labels), "t": "h",
                "b": list(self.buckets), "c": list(self.counts),
                "s": self.sum, "n_obs": self.count}


def _series(cls, name: str, labels: Dict[str, Any], **kw):
    key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
    with _lock:
        m = _REGISTRY.get(key)
    if m is None:
        m = cls(name, key[1], **kw)
        with _lock:
            m = _REGISTRY.setdefault(key, m)
    return m


def counter(name: str, **labels) -> Counter:
    return _series(Counter, name, labels)


def gauge(name: str, **labels) -> Gauge:
    return _series(Gauge, name, labels)


def histogram(name: str, buckets: Tuple[float, ...] = LATENCY_BUCKETS,
              **labels) -> Histogram:
    return _series(Histogram, name, labels, buckets=buckets)


def inc(name: str, delta: float = 1.0, **labels) -> None:
    if _enabled:
        counter(name, **labels).inc(delta)


def set_gauge(name: str, value: float, **labels) -> None:
    if _enabled:
        gauge(name, **labels).set(value)


def observe(name: str, value: float, **labels) -> None:
    """Record one latency/size observation into a labeled histogram."""
    if _enabled:
        histogram(name, **labels).observe(value)


def metrics_wire() -> List[dict]:
    """Serialize the registry for the heartbeat stamp (plain data only)."""
    with _lock:
        series = list(_REGISTRY.values())
    return [m.wire() for m in series]


def reset_metrics() -> None:
    """Drop every registered series (tests)."""
    with _lock:
        _REGISTRY.clear()


def merge_wire(per_node: Dict[str, List[dict]]) -> List[dict]:
    """Merge per-node wire snapshots into one cluster view: every series
    gains a ``node`` label; identical fixed buckets mean a PromQL
    ``sum by (le)`` (or ``merge_histograms`` here) is exact."""
    out: List[dict] = []
    for node, series in sorted(per_node.items()):
        for s in series or []:
            s2 = dict(s)
            s2["l"] = {**s.get("l", {}), "node": node}
            out.append(s2)
    return out


def merge_histograms(series: Iterable[dict]) -> Optional[dict]:
    """Sum same-bucket histogram wire records (the mergeability contract
    the fixed log-spaced edges exist for)."""
    acc: Optional[dict] = None
    for s in series:
        if s.get("t") != "h":
            continue
        if acc is None:
            acc = {"n": s["n"], "l": {}, "t": "h", "b": list(s["b"]),
                   "c": list(s["c"]), "s": s["s"], "n_obs": s["n_obs"]}
            continue
        if list(s["b"]) != acc["b"]:
            raise ValueError(f"histogram {s['n']!r}: bucket edges differ")
        acc["c"] = [a + b for a, b in zip(acc["c"], s["c"])]
        acc["s"] += s["s"]
        acc["n_obs"] += s["n_obs"]
    return acc


# ------------------------------------------------------------ prometheus

def _prom_name(name: str) -> str:
    import re
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_labels(labels: Dict[str, str], extra: Optional[dict] = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    inner = ",".join(
        '%s="%s"' % (_prom_name(k),
                     str(v).replace("\\", r"\\").replace('"', r'\"'))
        for k, v in sorted(merged.items()))
    return "{%s}" % inner


def _render_series(lines: List[str], s: dict) -> None:
    name = _prom_name(s["n"])
    labels = s.get("l", {})
    if s["t"] == "h":
        cum = 0
        edges = list(s["b"]) + [float("inf")]
        for edge, c in zip(edges, s["c"]):
            cum += c
            le = "+Inf" if edge == float("inf") else repr(float(edge))
            lines.append(f"{name}_bucket{_prom_labels(labels, {'le': le})}"
                         f" {cum}")
        lines.append(f"{name}_sum{_prom_labels(labels)} {s['s']}")
        lines.append(f"{name}_count{_prom_labels(labels)} {s['n_obs']}")
    else:
        lines.append(f"{name}{_prom_labels(labels)} {s['v']}")


def render_prometheus(cluster: bool = True) -> str:
    """Prometheus text exposition (the GET /metrics body).

    Local series are labeled with this process's node name; with
    ``cluster=True`` every heartbeat stamp's shipped snapshot is merged
    in too (other nodes' series appear under their own ``node`` label),
    so one coordinator scrape covers the whole cloud.  The flat
    ``count()`` counters are exported as ``h2o3_events_total{kind=...}``.
    """
    me = node_name()
    per_node: Dict[str, List[dict]] = {me: metrics_wire()}
    with _lock:
        flat = dict(_COUNTERS)
    for k, v in sorted(flat.items()):
        per_node[me].append({"n": "h2o3_events_total",
                             "l": {"kind": k}, "t": "c", "v": v})
    if cluster:
        try:
            for node, stamp in cluster_stamps().items():
                if node != me and isinstance(stamp, dict):
                    per_node[node] = stamp.get("metrics") or []
        except Exception:                 # noqa: BLE001 — local-only view
            pass
    merged = merge_wire(per_node)
    by_name: "collections.OrderedDict[str, list]" = collections.OrderedDict()
    for s in merged:
        by_name.setdefault(s["n"], []).append(s)
    prom_type = {"c": "counter", "g": "gauge", "h": "histogram"}
    lines: List[str] = []
    for name, series in by_name.items():
        lines.append(f"# TYPE {_prom_name(name)} "
                     f"{prom_type.get(series[0]['t'], 'untyped')}")
        for s in series:
            _render_series(lines, s)
    return "\n".join(lines) + "\n"


def cluster_stamps() -> Dict[str, dict]:
    """node -> heartbeat stamp (with shipped metrics/events), via DKV."""
    from . import dkv, heartbeat
    out: Dict[str, dict] = {}
    for key in dkv.keys(heartbeat.PREFIX):
        stamp = dkv.get(key)
        if isinstance(stamp, dict):
            out[key[len(heartbeat.PREFIX):]] = stamp
    return out


# ------------------------------------------------------------ flat counters

_COUNTERS: collections.Counter = collections.Counter()


def count(name: str, delta: int = 1) -> None:
    """Bump a flat monotonic named counter.

    For high-rate stats (DKV WAL records/bytes, dedup hits) that would
    churn the timeline ring if each were an event; surfaced alongside
    the ring on /3/Timeline and as ``h2o3_events_total`` on /metrics."""
    with _lock:
        _COUNTERS[name] += delta


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_COUNTERS)


# ------------------------------------------------------------------- traces

_trace_ctx: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("h2o3_tpu_trace", default=None)
_ID_NONCE = f"{os.getpid():x}{os.urandom(3).hex()}"
_id_seq = 0


def _new_id() -> str:
    global _id_seq
    with _lock:
        _id_seq += 1
        return f"{_ID_NONCE}.{_id_seq:x}"


def current_trace() -> Optional[Dict[str, str]]:
    """The active trace context, as injected into RPC envelopes:
    ``{"trace_id": ..., "span_id": ...}`` or None outside any trace."""
    ctx = _trace_ctx.get()
    return dict(ctx) if ctx else None


@contextlib.contextmanager
def trace_context(wire: Optional[Dict[str, str]]):
    """Adopt a remote trace context (the RPC handler side): spans opened
    inside become children of the caller's span, sharing its trace_id."""
    if not wire or not wire.get("trace_id"):
        yield
        return
    token = _trace_ctx.set({"trace_id": str(wire["trace_id"]),
                            "span_id": str(wire.get("span_id", ""))})
    try:
        yield
    finally:
        _trace_ctx.reset(token)


@contextlib.contextmanager
def _timed_event(kind: str, root: bool, fields: dict):
    if not _enabled:
        yield
        return
    t0 = time.time()
    parent = _trace_ctx.get()
    ids: Dict[str, str] = {}
    token = None
    if root or parent is not None:
        trace_id = parent["trace_id"] if parent else _new_id()
        span_id = _new_id()
        ids = {"trace_id": trace_id, "span_id": span_id}
        if parent and parent.get("span_id"):
            ids["parent_span"] = parent["span_id"]
        token = _trace_ctx.set({"trace_id": trace_id, "span_id": span_id})
    error = None
    try:
        yield
    except BaseException as e:
        error = type(e).__name__
        raise
    finally:
        if token is not None:
            _trace_ctx.reset(token)
        ev = dict(fields)
        ev.update(ids)
        ev["ok"] = error is None
        if error is not None:
            ev["error"] = error
        record(kind, duration_s=round(time.time() - t0, 4), **ev)


def span(kind: str, **fields):
    """Timed event — the MRProfile analog for coordinator-side phases.

    Failures record too (``ok=False`` + ``error=<ExcType>``), so chaos-
    injected faults are visible on the timeline instead of vanishing.
    Inside an active trace the event carries trace/span/parent ids and
    becomes a node of that trace's tree; outside one it is a plain
    timed event (no id allocation on untraced hot paths)."""
    return _timed_event(kind, False, fields)


def trace(kind: str, **fields):
    """Root span: like ``span`` but always allocates ids, starting a new
    trace when none is active (jobs open one per training run)."""
    return _timed_event(kind, True, fields)


def trace_forest(events: Iterable[dict]) -> List[dict]:
    """Stitch span events (local + shipped) into trees by trace_id.

    Returns one dict per trace: ``{"trace_id", "spans": [roots]}`` where
    each span node carries its event fields plus ``children``.  Spans
    whose parent is missing from the window (ring rollover, un-shipped
    remote parent) surface as roots rather than being dropped."""
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("trace_id") and e.get("span_id"):
            by_trace.setdefault(e["trace_id"], []).append(dict(e))
    forest = []
    for trace_id, spans in by_trace.items():
        nodes = {s["span_id"]: s for s in spans}
        for s in spans:
            s["children"] = []
        roots = []
        for s in sorted(spans, key=lambda s: s.get("ts", 0.0)):
            parent = nodes.get(s.get("parent_span"))
            if parent is not None and parent is not s:
                parent["children"].append(s)
            else:
                roots.append(s)
        forest.append({"trace_id": trace_id, "spans": roots})
    forest.sort(key=lambda t: (t["spans"][0].get("ts", 0.0)
                               if t["spans"] else 0.0))
    return forest


# ----------------------------------------------------------- device traces

_profiler_active = False


def profiler_active() -> bool:
    """Whether a device trace started HERE is currently capturing."""
    with _lock:
        return _profiler_active


def start_device_trace(logdir: str) -> bool:
    """Begin a jax.profiler trace (TensorBoard-viewable device timeline).

    Idempotent: a second start while a capture is live (including one
    jax.profiler reports out-of-band) records a ``profiler_noop`` event
    and returns False instead of propagating ``RuntimeError`` — the REST
    profiler route must never 500 a double-click.  Returns whether a new
    capture actually started; ``profiler_active`` gauges 1 while one is
    live (shipped in node snapshots like every other gauge)."""
    global _profiler_active
    import jax
    with _lock:
        active = _profiler_active
    if active:
        record("profiler_noop", op="start", reason="already_active")
        return False
    try:
        jax.profiler.start_trace(logdir)
    except RuntimeError as e:
        record("profiler_noop", op="start", reason="jax_runtime",
               error=str(e)[:200])
        return False
    with _lock:
        _profiler_active = True
    set_gauge("profiler_active", 1.0)
    record("profiler_start", logdir=logdir)
    return True


def stop_device_trace() -> bool:
    """Stop the live device trace; a stop with no capture running records
    ``profiler_noop`` and returns False (idempotent, like start)."""
    global _profiler_active
    import jax
    with _lock:
        active = _profiler_active
    if not active:
        record("profiler_noop", op="stop", reason="not_active")
        return False
    try:
        jax.profiler.stop_trace()
    except RuntimeError as e:
        record("profiler_noop", op="stop", reason="jax_runtime",
               error=str(e)[:200])
        return False
    finally:
        with _lock:
            _profiler_active = False
        set_gauge("profiler_active", 0.0)
    record("profiler_stop")
    return True


# ------------------------------------------------------------- diagnostics

def jstack() -> List[Dict]:
    """All-thread stack dump — water/api/JStackHandler (water.util.JStack)
    rendered for a Python runtime: one traceback per live thread."""
    import sys
    import threading
    import traceback
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        t = by_id.get(tid)
        out.append({
            "thread_id": tid,
            "name": t.name if t else f"thread-{tid}",
            "daemon": bool(t.daemon) if t else None,
            "traces": traceback.format_stack(frame),
        })
    return out


def network_test(sizes=(1_024, 1_048_576, 16_777_216)) -> List[Dict]:
    """Collective-bandwidth micro-bench — water/api/NetworkTestHandler.

    The reference times point-to-point UDP/TCP between cloud members; the
    mesh analog is an all-reduce (psum) at a few payload sizes, which is
    exactly the traffic training generates.  Each size is timed per mesh
    stage — the host-local ``"chips"`` ring (ICI), the cross-host
    ``"hosts"`` axis (DCN), and the flat product axis — so the report
    separates intra-host from inter-host bandwidth; every timing also
    lands in the ``collective_seconds{axis,op}`` histogram.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .cluster import CHIP_AXIS, HOST_AXIS, ROW_AXES, ROW_AXIS, cluster
    from .compat import shard_map

    cl = cluster()
    rows = cl.n_row_shards
    stages = [("rows", ROW_AXES)]
    if cl.mesh.shape[CHIP_AXIS] > 1:
        stages.append(("chips", CHIP_AXIS))
    if cl.mesh.shape[HOST_AXIS] > 1:
        stages.append(("hosts", HOST_AXIS))
    results = []
    for size in sizes:
        n = max(size // 4, rows)
        n = (n // rows) * rows
        x = jnp.ones((n,), jnp.float32)
        for axis_label, axis in stages:
            def allred(v, _axis=axis):
                return jax.lax.psum(v, _axis)

            # out spec stays row-sharded: a single-stage psum still varies
            # over the other row axis, so no replication can be claimed
            f = jax.jit(shard_map(allred, mesh=cl.mesh,
                                  in_specs=P(ROW_AXIS),
                                  out_specs=P(ROW_AXIS),
                                  check_vma=False))
            np_out = f(x)
            _ = float(np_out[0])              # warmup + compile sync
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                out = f(x)
            _ = float(out[0])                 # fetch = sync point
            dt = (time.perf_counter() - t0) / reps
            observe("collective_seconds", dt, axis=axis_label, op="psum")
            results.append({
                "bytes": int(n * 4),
                "collective": "psum",
                "axis": axis_label,
                "seconds": dt,
                "gbytes_per_sec": (n * 4 / max(dt, 1e-12)) / 1e9,
            })
    return results
