"""Failure detection that ACTS, plus deliberate fault injection.

Reference: ``water/HeartBeatThread.java:145`` detects a "dirt-napping"
node (missed heartbeats) but only *reports* it; the data plane cannot
survive member loss (Paxos.java:31-33) and recovery is job-level via
``hex/faulttolerance/Recovery.java:72-81`` after a full cluster restart.

TPU-native design: same two tiers, but the detector acts.  A watchdog
thread polls the heartbeat view; when a member decays to ``dead`` it

1. records a ``node_dead`` timeline event and a ``!failures/<node>`` DKV
   record (visible to REST/tooling),
2. aborts every RUNNING local job with :class:`NodeFailedError` — the
   SPMD collectives that job is blocked in can never complete once a
   gang member is gone, so joiners are released immediately with a clear
   error instead of hanging,
3. leaves the job's recovery-journal entry in ``running`` state, so
   ``runtime.recovery.resume()`` resurrects it after the cluster
   restarts (the reference's auto-recovery contract).

Fault injection (SURVEY.md §5 explicitly asks the rebuild to add hooks
the reference lacks): ``H2O3_TPU_FAULT_INJECT`` holds a comma-separated
list of ``point:proc:nth[:action[:arg][:repeat]]`` specs.  ``proc`` is a
jax process index, or the literal ``coordinator`` to select whichever
process is serving the DKV control plane (usable before device init —
no jax import on that path).  ``action``:

- ``kill`` (default) — ``os._exit(137)`` at every hit from the nth on,
- ``raise`` — raise :class:`InjectedFault` (a deterministic failure the
  journal must mark ``failed``, never resurrect),
- ``delay:<ms>`` — sleep, modelling a slow worker / network stall,
- ``dkv_drop`` — raise ``ConnectionError``, modelling a transient
  control-plane RPC drop (the DKV client's retry loop must absorb it).

Non-kill actions fire ``repeat`` times (default 1) starting at the nth
hit, so a transient fault heals and retry paths can be proven to
converge.  Injection points: ``tree_chunk``, ``ktree_round``,
``dl_iter``, ``dkv_rpc``, ``dkv_rpc_resp`` (after the server applied —
models a LOST RESPONSE, the exactly-once dedup case), ``dkv_handle``
(top of the coordinator's connection handler — with
``:coordinator:<nth>:kill`` it hard-kills the coordinator at the nth
handled connection), ``parse_range``, ``remat`` (top of every
lineage-driven shard re-materialization, runtime/remat.py — raise there
proves a failed remat degrades to full re-import, never to wrong data),
``cv_fold``, ``grid_member``, ``automl_member``, ``glm_lambda``,
``snapshot_write``, ``deep_level``, ``sched_assign``, ``host_join``.  ``sched_assign``
fires when the cluster scheduler (runtime/scheduler.py) hands a job to
a worker thread — kill/raise there proves admission state survives a
lost assignment; ``host_join`` fires when the elastic membership
observer sees a newly-alive host, before quarantine/rebuild arming, so
join-time crashes are injectable.  ``ktree_round`` fires at the top of every batched
K-tree boosting round (the fused multinomial/multiclass level
program), so kill/resume mid-round exercises snapshot recovery of the
one-launch-per-level path.  ``deep_level`` fires at the top of a tree
chunk/round only when the node-sparse deep-level layout
(``hist_layout="sparse"``) is engaged past its depth threshold, so
kill/resume mid-deep-tree exercises recovery of the sparse path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import dkv, heartbeat

FAILURES_PREFIX = "!failures/"


class NodeFailedError(RuntimeError):
    """A cluster member stopped heartbeating mid-job."""


class InjectedFault(RuntimeError):
    """Deliberately injected failure (H2O3_TPU_FAULT_INJECT action=raise)."""


_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_handled: set = set()
_inject_counts: Dict[str, int] = {}


def start(poll: float = 2.0, hb_interval: float = 5.0) -> None:
    """Start the watchdog thread (idempotent)."""
    global _thread
    stop()
    _stop.clear()

    def _run():
        while not _stop.wait(poll):
            try:
                check(hb_interval)
            except Exception:        # noqa: BLE001 — watchdog must not die
                pass

    _thread = threading.Thread(target=_run, name="failure-watchdog",
                               daemon=True)
    _thread.start()


def stop() -> None:
    global _thread
    _stop.set()
    if _thread is not None:
        _thread.join(timeout=2.0)
        _thread = None


def check(hb_interval: float = 5.0) -> list:
    """One watchdog sweep; returns newly dead node names (also callable
    directly from tests / REST handlers without the thread)."""
    newly_dead = []
    for node, info in heartbeat.members(interval=hb_interval).items():
        if info.get("status") == "dead" and node not in _handled:
            _handled.add(node)
            newly_dead.append(node)
            _on_dead(node, info)
    return newly_dead


def any_dead() -> bool:
    """Has this process observed any member death (watchdog or sweep)?"""
    return bool(_handled)


def cluster_degraded(hb_interval: float = 5.0) -> bool:
    """True when any member is not (yet) fully alive.

    Used when a training collective dies with a raw runtime error: a peer
    may have crashed moments ago and not yet aged to ``dead`` — a stale
    (suspect) stamp at failure time is treated as a node failure, so the
    recovery journal keeps the job resumable instead of marking it
    deterministically failed."""
    if _handled:
        return True
    try:
        return any(m.get("status") != "alive"
                   for m in heartbeat.members(interval=hb_interval).values())
    except Exception:                # noqa: BLE001 — coordinator gone ⇒ yes
        return True


def _on_dead(node: str, info: dict) -> None:
    from .observability import record, log
    age = float(info.get("age", 0.0))
    record("node_dead", node=node, age=age)
    log.error("worker %s declared dead (no heartbeat for %.1fs); "
              "aborting running jobs", node, age)
    try:
        # host_index (the heartbeat's stamped jax process index) tells
        # runtime/remat.py WHICH frame shards died with this member
        dkv.put(FAILURES_PREFIX + node,
                {"ts": time.time(), "age": age, "pid": info.get("pid"),
                 "host_index": info.get("proc")})
    except Exception:                # noqa: BLE001 — coordinator may be gone
        pass
    from .job import list_jobs
    err = NodeFailedError(
        f"worker {node} lost mid-job (heartbeat dead for {age:.1f}s); "
        "collectives cannot complete — the scheduler's degraded-mode "
        "requeue re-materializes the lost frame shards from lineage "
        "(runtime/remat.py) and retries; after a full cluster restart, "
        "runtime.recovery.resume() rebuilds frames from lineage (falling "
        "back to source re-import) and resurrects the job")
    # degraded-mode continuation: the scheduler requeues its in-flight
    # jobs with retry budget from their journal snapshots onto the
    # shrunken mesh; only what it cannot requeue is failed below
    requeued: set = set()
    try:
        from . import scheduler as _sched
        requeued = _sched.on_node_dead(node, err)
    except Exception:                # noqa: BLE001 — fall back to fail-all
        requeued = set()
    for job in list_jobs():
        if job is not None and getattr(job, "is_running", False) \
                and job.key not in requeued:
            job.fail(err)


def reset() -> None:
    """Forget handled deaths + injection counts (tests)."""
    _handled.clear()
    _inject_counts.clear()


# ------------------------------------------------------------ fault injection

def maybe_inject(point: str) -> None:
    """Act on the configured injection matrix at ``point`` (module
    docstring has the ``H2O3_TPU_FAULT_INJECT`` spec grammar).  No-op
    when unset; costs one env lookup on the hot path."""
    env = os.environ.get("H2O3_TPU_FAULT_INJECT")
    if not env:
        return
    for i, spec in enumerate(env.split(",")):
        _inject_one(point, spec.strip(), i)


def _inject_one(point: str, spec: str, slot: int) -> None:
    parts = spec.split(":")
    if len(parts) < 3:
        return
    try:
        pt, proc, nth = parts[0], parts[1], int(parts[2])
    except ValueError:
        return
    if pt != point:
        return
    rest = parts[3:]
    action = rest[0] if rest else "kill"
    args = rest[1:]
    try:
        delay_ms = float(args.pop(0)) if action == "delay" and args else 0.0
        repeat = int(args.pop(0)) if args else (None if action == "kill"
                                                else 1)
    except ValueError:
        return
    if action not in ("kill", "raise", "delay", "dkv_drop"):
        return
    if proc == "coordinator":
        # role selector: fires only on the process serving the DKV
        # control plane (no jax import — usable before device init)
        if not dkv.is_coordinator():
            return
        pidx = None
    else:
        try:
            pidx = int(proc)
        except ValueError:
            return
        import jax
        if jax.process_index() != pidx:
            return
    key = (point, slot)
    _inject_counts[key] = count = _inject_counts.get(key, 0) + 1
    if count < nth or (repeat is not None and count >= nth + repeat):
        return
    from .observability import log, record
    record("fault_injected", point=point, action=action, hit=count)
    if action == "kill":
        log.error("FAULT INJECTION: killing process %s at %s #%d",
                  "coordinator" if pidx is None else pidx, point, count)
        os._exit(137)
    log.warning("FAULT INJECTION: %s at %s #%d", action, point, count)
    if action == "raise":
        raise InjectedFault(f"injected fault at {point} (hit #{count})")
    if action == "dkv_drop":
        raise ConnectionError(
            f"injected DKV drop at {point} (hit #{count})")
    time.sleep(delay_ms / 1000.0)
