"""The MRTask analog: sharded map + mesh-wide reduce as XLA programs.

Reference: ``water/MRTask.java`` (989 LoC) — user code is serialized, fanned
out over the cluster in a binary tree of RPCs (remote_compute,
MRTask.java:739-760), runs ``map(Chunk)`` on home-node chunks via ForkJoin
divide-and-conquer (compute2, :764-830), and ``reduce()``s partials up the
tree.  Code shipping requires the whole Iced/Weaver serialization machinery
(water/Weaver.java:14).

TPU-native redesign: there is no code shipping — a traced, jit-compiled SPMD
program IS the shipped code, and the reduce tree IS a hardware collective.
``map_reduce`` wraps a per-shard function in ``shard_map`` over the mesh
"rows" axis and combines partials with ``psum`` (ICI tree/ring reduce), which
replaces both MRTask's RPC fan-out and its binary-tree reduce.  For most
algorithms you don't even need this: operating on row-sharded arrays inside
``jax.jit`` lets GSPMD insert the same collectives automatically — use
``map_reduce`` when you want the per-shard view to be explicit (histograms,
per-partition state).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:                       # jax<0.5: experimental namespace
    from jax.experimental.shard_map import shard_map

from .cluster import cluster, ROW_AXIS


def map_partitions(fn: Callable, *arrays, out_spec=P(ROW_AXIS)):
    """Apply ``fn`` independently to each row-shard (the `map` half).

    ``fn`` sees the local shard of every input array and must return arrays
    whose row dim is the local shard size.  Equivalent of MRTask.map(Chunk)
    without a reduce.
    """
    mesh = cluster().mesh
    specs = tuple(P(ROW_AXIS, *([None] * (a.ndim - 1))) for a in arrays)
    f = shard_map(fn, mesh=mesh, in_specs=specs, out_specs=out_spec)
    return jax.jit(f)(*arrays)


def map_reduce(map_fn: Callable, *arrays):
    """Full MRTask: per-shard map, then ``psum`` of the partials over rows.

    ``map_fn(*local_shards) -> pytree of partial reductions``; the result is
    the mesh-wide sum, replicated everywhere (MRTask.doAll + reduce()).
    Non-additive reductions (min/max) should be expressed by mapping into an
    additive/idempotent form first, exactly as reference MRTasks fold their
    state into arrays that reduce elementwise (e.g. DHistogram._vals adds).
    """
    mesh = cluster().mesh

    def shard_fn(*local):
        partial = map_fn(*local)
        return jax.tree.map(lambda x: jax.lax.psum(x, ROW_AXIS), partial)

    specs = tuple(P(ROW_AXIS, *([None] * (a.ndim - 1))) for a in arrays)
    f = shard_map(shard_fn, mesh=mesh, in_specs=specs, out_specs=P())
    return jax.jit(f)(*arrays)


def psum_rows(x):
    """Replicated sum over the rows axis of a sharded array inside jit."""
    return jnp.sum(x, axis=0)
