"""The MRTask analog: sharded map + mesh-wide reduce as XLA programs.

Reference: ``water/MRTask.java`` (989 LoC) — user code is serialized, fanned
out over the cluster in a binary tree of RPCs (remote_compute,
MRTask.java:739-760), runs ``map(Chunk)`` on home-node chunks via ForkJoin
divide-and-conquer (compute2, :764-830), and ``reduce()``s partials up the
tree.  Code shipping requires the whole Iced/Weaver serialization machinery
(water/Weaver.java:14).

TPU-native redesign: there is no code shipping — a traced, jit-compiled SPMD
program IS the shipped code, and the reduce tree IS a hardware collective.
``map_reduce`` wraps a per-shard function in ``shard_map`` over the mesh's
row axes and combines partials with ``psum``, which replaces both MRTask's
RPC fan-out and its binary-tree reduce.

The reduce is HIERARCHICAL on the ``("hosts", "chips")`` mesh
(runtime/cluster.py): partials first psum around each host's ICI ring
(``"chips"``), then one small cross-host psum rides DCN (``"hosts"``).
That mirrors the reference's two-level reduce (node-local ForkJoin fold,
then the RPC tree) and keeps the large pre-reduce tensors off the slow
links.  The one-collective flat schedule stays available as the oracle
behind ``reduce_mode``:

  * ``"hier"``  — staged ICI-then-DCN psum (default; H2O3_TPU_REDUCE_MODE)
  * ``"flat"``  — single psum over the flattened product axis
  * ``"check"`` — run both whole programs and raise ``ReduceParityError``
                  on divergence (the ``hist_mode="check"`` analog)

For most algorithms you don't even need ``map_reduce``: operating on
row-sharded arrays inside ``jax.jit`` lets GSPMD insert the collectives
automatically — use it when the per-shard view must be explicit
(histograms, per-partition state).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .cluster import CHIP_AXIS, HOST_AXIS, ROW_AXES, ROW_AXIS, cluster
from .compat import shard_map

REDUCE_MODES = ("flat", "hier", "check")

_forced_mode: str | None = None


class ReduceParityError(AssertionError):
    """flat and hier reductions disagreed (``reduce_mode="check"``)."""


def resolve_reduce_mode(mode: str | None = None) -> str:
    """Effective reduce mode: explicit arg > force_reduce_mode > config.

    ``"auto"`` (the config default) defers to the autotuner, which picks
    hier/flat per mesh geometry — and resolves to the historical fixed
    default (``hier``) when the tuner is off, so pinned runs stay
    bit-identical."""
    if not mode:
        mode = _forced_mode
    if not mode:
        from .config import config
        mode = config().reduce_mode
    if mode == "auto":
        from . import autotune
        mode = autotune.resolve_reduce_mode_auto()
    if mode not in REDUCE_MODES:
        raise ValueError(
            f"reduce_mode={mode!r} not in {REDUCE_MODES} + ('auto',)")
    return mode


@contextlib.contextmanager
def force_reduce_mode(mode: str):
    """Scoped override of the configured reduce mode (tests, benchmarks)."""
    if mode not in REDUCE_MODES and mode != "auto":
        raise ValueError(f"reduce_mode={mode!r} not in {REDUCE_MODES}")
    global _forced_mode
    prev = _forced_mode
    _forced_mode = mode
    try:
        yield
    finally:
        _forced_mode = prev


def psum_shards(x, mode: str = ""):
    """Sum ``x`` across every row shard, from inside a shard_map'd body.

    ``"flat"`` is one collective over the flattened product axis (the
    oracle).  ``"hier"`` stages it: psum around the host-local ``"chips"``
    ring first (ICI), then one ``"hosts"`` psum of the per-host partials
    (DCN) — same result, but the cross-host stage moves an already-reduced
    tensor.  ``"check"`` compiles the hier schedule here; the flat-vs-hier
    comparison runs one level up (``checked_pair``/``map_reduce``), where
    both whole programs can execute and be compared on the host.
    """
    mode = resolve_reduce_mode(mode or None)
    if mode == "flat":
        return jax.lax.psum(x, ROW_AXES)
    return jax.lax.psum(jax.lax.psum(x, CHIP_AXIS), HOST_AXIS)


def assert_reduce_parity(flat, hier, what: str = "map_reduce") -> None:
    """Compare flat/hier pytrees: bitwise first, tiny tolerance second.

    Integer-valued float stats (counts, quantized gradients) reduce
    bitwise-identically under both schedules; genuinely fractional floats
    may differ by reassociation ulps, which get recorded (not raised).
    Anything beyond tolerance raises ``ReduceParityError``.
    """
    from . import observability as obs
    flat_l, treedef_f = jax.tree.flatten(flat)
    hier_l, treedef_h = jax.tree.flatten(hier)
    if treedef_f != treedef_h:
        raise ReduceParityError(
            f"{what}: flat/hier output structures differ: "
            f"{treedef_f} vs {treedef_h}")
    for i, (a, b) in enumerate(zip(flat_l, hier_l)):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape == b.shape and a.tobytes() == b.tobytes():
            continue
        if a.shape == b.shape and np.allclose(a, b, rtol=1e-5, atol=1e-6,
                                              equal_nan=True):
            obs.record("reduce_parity_ulp", what=what, leaf=i)
            continue
        diff = np.max(np.abs(a.astype(np.float64) - b.astype(np.float64))) \
            if a.shape == b.shape else float("inf")
        raise ReduceParityError(
            f"{what}: flat/hier reduction divergence at leaf {i} "
            f"(shape {a.shape} vs {b.shape}, maxdiff {diff:.3e})")


def checked_pair(flat_fn: Callable, hier_fn: Callable,
                 what: str = "reduce") -> Callable:
    """Run both mode-variants of a program, compare, return the hier result.

    The ``reduce_mode="check"`` dispatcher: ``flat_fn``/``hier_fn`` are the
    same compiled program built with the two schedules (e.g. two entries of
    a builder's LRU cache keyed on ``reduce_mode``).
    """
    @functools.wraps(hier_fn)
    def run(*args, **kw):
        flat = flat_fn(*args, **kw)
        hier = hier_fn(*args, **kw)
        assert_reduce_parity(flat, hier, what=what)
        return hier
    return run


def map_partitions(fn: Callable, *arrays, out_spec=P(ROW_AXIS)):
    """Apply ``fn`` independently to each row-shard (the `map` half).

    ``fn`` sees the local shard of every input array and must return arrays
    whose row dim is the local shard size.  Equivalent of MRTask.map(Chunk)
    without a reduce.
    """
    mesh = cluster().mesh
    specs = tuple(P(ROW_AXIS, *([None] * (a.ndim - 1))) for a in arrays)
    f = shard_map(fn, mesh=mesh, in_specs=specs, out_specs=out_spec)
    return jax.jit(f)(*arrays)


# per-map_fn xprof wrappers, weakly keyed: a stable map_fn (module-level
# task) reuses its AOT-compiled program across calls instead of paying
# jax a fresh trace+compile per invocation; throwaway lambdas vanish
# with their entry.  Keyed further by (mode, ndims) since the shard_map
# specs depend on the operand ranks.
_MR_PROGRAMS: "weakref.WeakKeyDictionary[Callable, dict]" = None  # type: ignore


def _mr_program(map_fn: Callable, arrays, mode: str):
    global _MR_PROGRAMS
    if _MR_PROGRAMS is None:
        import weakref
        _MR_PROGRAMS = weakref.WeakKeyDictionary()
    from . import xprof
    mesh = cluster().mesh
    key = (mode, tuple(a.ndim for a in arrays), id(mesh))
    try:
        per_fn = _MR_PROGRAMS.setdefault(map_fn, {})
    except TypeError:                    # unweakrefable callable
        per_fn = {}
    prog = per_fn.get(key)
    if prog is None:
        def shard_fn(*local):
            partial = map_fn(*local)
            return jax.tree.map(lambda x: psum_shards(x, mode), partial)

        specs = tuple(P(ROW_AXIS, *([None] * (a.ndim - 1)))
                      for a in arrays)
        f = shard_map(shard_fn, mesh=mesh, in_specs=specs, out_specs=P())
        prog = xprof.register_program("map_reduce", jax.jit(f))
        per_fn[key] = prog
    return prog


def _map_reduce_once(map_fn: Callable, arrays, mode: str):
    from . import observability as obs
    prog = _mr_program(map_fn, arrays, mode)
    t0 = time.perf_counter()
    out = jax.block_until_ready(prog(*arrays))
    obs.observe("collective_seconds", time.perf_counter() - t0,
                axis="chips+hosts" if mode == "hier" else "rows",
                op="map_reduce")
    return out


def map_reduce(map_fn: Callable, *arrays, reduce_mode: str | None = None):
    """Full MRTask: per-shard map, then ``psum`` of the partials over rows.

    ``map_fn(*local_shards) -> pytree of partial reductions``; the result is
    the mesh-wide sum, replicated everywhere (MRTask.doAll + reduce()).
    Non-additive reductions (min/max) should be expressed by mapping into an
    additive/idempotent form first, exactly as reference MRTasks fold their
    state into arrays that reduce elementwise (e.g. DHistogram._vals adds).

    ``reduce_mode`` picks the collective schedule (module docstring); the
    default follows ``H2O3_TPU_REDUCE_MODE``/``force_reduce_mode``.
    """
    mode = resolve_reduce_mode(reduce_mode)
    if mode == "check":
        flat = _map_reduce_once(map_fn, arrays, "flat")
        hier = _map_reduce_once(map_fn, arrays, "hier")
        assert_reduce_parity(flat, hier, what="map_reduce")
        return hier
    return _map_reduce_once(map_fn, arrays, mode)


def psum_rows(x):
    """Replicated sum over the rows axis of a sharded array inside jit."""
    return jnp.sum(x, axis=0)
