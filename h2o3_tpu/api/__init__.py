"""REST API layer (the water/api analog)."""

from .server import H2OServer, start_server
