"""REST server: versioned JSON routes over the runtime — water/api analog.

Reference: ``water/api/RequestServer.java:56,75-80`` (~150 routes, versioned
schemas under ``water/api/schemas3``), served by Jetty adapters
(h2o-webserver-iface).  Clients (h2o-py/h2o-r/Flow) drive everything through
these routes.

TPU-native redesign: a stdlib ThreadingHTTPServer (no Jetty analog needed —
the control plane is a single coordinator process; the data plane never
touches HTTP).  Routes keep the reference's shapes/paths so an h2o-py-style
client maps 1:1: /3/Cloud, /3/Jobs, /3/Frames, /3/Parse, /3/ModelBuilders/
{algo}, /3/Models, /3/Predictions/models/{m}/frames/{f}, /3/DKV.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

ALGOS = ("glm", "gbm", "drf", "xgboost", "deeplearning", "kmeans", "pca",
         "svd", "naivebayes", "isolationforest", "extendedisolationforest",
         "isotonicregression", "quantile", "stackedensemble", "adaboost",
         "targetencoder", "glrm", "coxph", "word2vec", "rulefit",
         "aggregator", "gam", "upliftdrf", "dt", "psvm", "anovaglm",
         "modelselection", "infogram")


def _builder(algo: str):
    from .. import models as M
    return {
        "glm": M.GLM, "gbm": M.GBM, "drf": M.DRF, "xgboost": M.XGBoost,
        "deeplearning": M.DeepLearning, "kmeans": M.KMeans, "pca": M.PCA,
        "svd": M.SVD, "naivebayes": M.NaiveBayes,
        "isolationforest": M.IsolationForest,
        "extendedisolationforest": M.ExtendedIsolationForest,
        "isotonicregression": M.IsotonicRegression,
        "quantile": M.Quantile, "stackedensemble": M.StackedEnsemble,
        "adaboost": M.AdaBoost, "targetencoder": M.TargetEncoder,
        "glrm": M.GLRM, "coxph": M.CoxPH, "word2vec": M.Word2Vec,
        "rulefit": M.RuleFit, "aggregator": M.Aggregator, "gam": M.GAM,
        "upliftdrf": M.UpliftDRF, "dt": M.DecisionTree,
        "psvm": M.PSVM, "anovaglm": M.ANOVAGLM,
        "modelselection": M.ModelSelection, "infogram": M.Infogram,
    }[algo]


def _frame_schema(key: str, fr) -> dict:
    return {
        "frame_id": {"name": key},
        "rows": fr.nrows, "columns": [
            {"label": n, "type": v.type,
             "domain": v.domain,
             "missing_count": int(v.nmissing()) if v.data is not None else 0}
            for n, v in zip(fr.names, fr.vecs)],
    }


def _model_schema(key: str, m) -> dict:
    def metr(x):
        if x is None:
            return None
        if isinstance(x, dict):
            return x
        d = x.describe() if hasattr(x, "describe") else {}
        return {k: v for k, v in d.items()
                if isinstance(v, (int, float, str, bool))}
    return {
        "model_id": {"name": key},
        "algo": m.algo,
        "response_column": m.params.response_column,
        "training_metrics": metr(m.training_metrics),
        "validation_metrics": metr(m.validation_metrics),
        "cross_validation_metrics": metr(m.cross_validation_metrics),
        "output": {k: v for k, v in m.output.items()
                   if isinstance(v, (int, float, str, bool))},
    }


class _Server(ThreadingHTTPServer):
    """HTTP server with optional per-connection TLS (deferred handshake)
    and in-flight handler tracking so shutdown can drain gracefully."""

    ssl_context = None
    daemon_threads = True
    block_on_close = False        # drain() bounds the wait instead

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()

    def get_request(self):
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False)
        return sock, addr

    def process_request_thread(self, request, client_address):
        t = threading.current_thread()
        with self._inflight_lock:
            self._inflight.add(t)
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._inflight_lock:
                self._inflight.discard(t)

    def drain(self, timeout: float) -> int:
        """Wait (bounded) for in-flight request handlers; returns how many
        were still running when the deadline hit."""
        deadline = time.time() + timeout
        while True:
            with self._inflight_lock:
                live = [t for t in self._inflight
                        if t.is_alive() and t is not threading.current_thread()]
            if not live or time.time() >= deadline:
                return len(live)
            live[0].join(timeout=min(0.1, max(deadline - time.time(), 0.01)))


class _Handler(BaseHTTPRequestHandler):
    timeout = 120                               # bounds a stalled peer
    routes_get: Dict[str, Callable] = {}
    routes_post: Dict[str, Callable] = {}
    routes_delete: Dict[str, Callable] = {}

    def log_message(self, fmt, *args):          # quiet
        pass

    def _authorized(self) -> bool:
        """Pluggable authn (api/auth.py SPI): a valid form-login session
        cookie OR HTTP Basic checked against the configured Authenticator.
        Reference surface: h2o-security / h2o-jaas-pam login services."""
        authn = getattr(self.server, "authenticator", None)
        if authn is None:
            return True
        from . import auth as _auth
        sessions = self.server.sessions
        token = _auth.parse_cookie(self.headers.get("Cookie", ""),
                                   "h2o3-session")
        if token and sessions.user_for(token):
            return True
        creds = _auth.parse_basic(self.headers.get("Authorization", ""))
        return bool(creds) and authn.check(*creds)

    def _do_login(self, params: dict):
        """POST /3/Login (form fields username/password) -> session cookie.

        The form-login flow (h2o-security LoginHandler analog): Flow and
        browser clients authenticate once and carry the cookie."""
        from . import auth as _auth
        authn = self.server.authenticator
        user = str(params.get("username", ""))
        password = str(params.get("password", ""))
        if authn is None or authn.check(user, password):
            body = json.dumps({"login": "ok", "username": user}).encode()
            self.send_response(200)
            if authn is not None:
                token = self.server.sessions.create(user)
                self.send_header(
                    "Set-Cookie",
                    f"h2o3-session={token}; HttpOnly; Path=/; SameSite=Lax")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(401, {"error": "invalid credentials"})

    def _do_logout(self):
        from . import auth as _auth
        token = _auth.parse_cookie(self.headers.get("Cookie", ""),
                                   "h2o3-session")
        if token:
            self.server.sessions.destroy(token)
        self._reply(200, {"logout": "ok"})

    def _reply(self, code: int, payload: dict):
        body = json.dumps(payload, default=_json_default).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_html(self, html: str):
        body = html.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, text: str):
        # Prometheus text exposition (the only str-returning route)
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _deny(self):
        self.send_response(401)
        self.send_header("WWW-Authenticate", 'Basic realm="h2o3_tpu"')
        self.end_headers()

    def _dispatch(self, table):
        if not self._authorized():
            return self._deny()
        parsed = urlparse(self.path)
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            raw = self.rfile.read(length)
            try:
                params.update(json.loads(raw))
            except Exception:
                params.update({k: v[0] for k, v
                               in parse_qs(raw.decode()).items()})
        for pattern, fn in table.items():
            m = re.fullmatch(pattern, parsed.path)
            if m:
                try:
                    out = fn(self.server.api, *m.groups(), **params)
                    if isinstance(out, bytes):       # artifact downloads
                        return self._reply_bytes(out)
                    if isinstance(out, str):         # /metrics exposition
                        return self._reply_text(out)
                    return self._reply(200, out)
                except KeyError as e:
                    return self._reply(404, {"error": str(e)})
                except Exception as e:      # noqa: BLE001
                    from ..serving.batcher import DeadlineExceeded
                    if isinstance(e, DeadlineExceeded):
                        # shed, not failed: retryable service pressure
                        return self._reply(503, {"error": str(e)})
                    return self._reply(400, {
                        "error": repr(e),
                        "stacktrace": traceback.format_exc().splitlines()})
        self._reply(404, {"error": f"no route {parsed.path}"})

    def _reply_bytes(self, data: bytes):
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path = urlparse(self.path).path
        if path in ("/", "/flow", "/flow/index.html"):
            if not self._authorized():
                return self._deny()
            from .flow import FLOW_HTML
            return self._reply_html(FLOW_HTML)
        self._dispatch(self.routes_get)

    def do_POST(self):
        path = urlparse(self.path).path
        if path == "/3/Login":
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            try:
                params = json.loads(raw)
                if not isinstance(params, dict):
                    raise ValueError("login body must be an object")
            except Exception:           # noqa: BLE001 — form-encoded body
                try:
                    params = {k: v[0] for k, v in
                              parse_qs(raw.decode()).items()}
                except Exception:       # noqa: BLE001 — binary garbage
                    return self._reply(400, {"error": "malformed login "
                                                      "body"})
            return self._do_login(params)
        if path == "/3/Logout":
            return self._do_logout()
        if path in ("/3/Models.upload.bin", "/3/PostFile"):
            # raw binary body (artifact / file upload), not JSON
            if not self._authorized():
                return self._deny()
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length)
            try:
                if path == "/3/PostFile":
                    q = {k: v[0] for k, v in
                         parse_qs(urlparse(self.path).query).items()}
                    return self._reply(200, self.server.api.post_file(
                        raw, filename=q.get("filename", "upload")))
                return self._reply(200, self.server.api.model_upload(raw))
            except Exception as e:          # noqa: BLE001
                return self._reply(400, {"error": repr(e)})
        self._dispatch(self.routes_post)

    def do_DELETE(self):
        self._dispatch(self.routes_delete)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return v if np.isfinite(v) else None
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class Api:
    """Route implementations bound to the in-process runtime."""

    def __init__(self):
        self._lock = threading.Lock()
        self.jobs: Dict[str, dict] = {}

    # ---------------------------------------------------------------- cloud
    def cloud(self) -> dict:
        from ..runtime.cluster import cluster
        from ..runtime import heartbeat
        c = cluster().describe()
        members = heartbeat.members()
        healthy = all(m["status"] == "alive" for m in members.values())
        return {"version": "h2o3_tpu", "cloud_healthy": healthy,
                "cloud_size": c["process_count"], "members": members, **c}

    # ---------------------------------------------------------------- frames
    def frames(self) -> dict:
        from ..runtime import dkv
        from ..frame.frame import Frame
        out = []
        for k in dkv.keys():
            v = dkv.get(k)
            if isinstance(v, Frame):
                out.append(_frame_schema(k, v))
        return {"frames": out}

    def frame(self, key: str) -> dict:
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        return {"frames": [_frame_schema(key, fr)]}

    def parse(self, source_frames=None, destination_frame=None, path=None,
              col_types=None, **kw) -> dict:
        import os
        import tempfile
        from .. import import_file
        src = path or source_frames
        if isinstance(col_types, str):
            col_types = json.loads(col_types)
        fr = import_file(src, destination_frame=destination_frame,
                         **({"col_types": col_types} if col_types else {}))
        # a PostFile spool is single-use: delete once parsed so repeated
        # uploads cannot leak disk on a long-lived coordinator
        spool = os.path.join(tempfile.gettempdir(), "h2o3_uploads")
        for p in ([src] if isinstance(src, str) else list(src or [])):
            if isinstance(p, str) and os.path.dirname(p) == spool:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        return {"job": {"status": "DONE"},
                "destination_frame": {"name": fr.key}}

    # ---------------------------------------------------------------- models
    @staticmethod
    def _coerce(params: dict) -> dict:
        """Coerce numeric/JSON strings (query-string transport)."""
        clean = {}
        for k, v in params.items():
            if isinstance(v, str):
                try:
                    v = json.loads(v)
                except Exception:
                    pass
            clean[k] = v
        return clean

    def _frame_pair(self, params: dict):
        from ..runtime import dkv
        training = params.pop("training_frame")
        valid_key = params.pop("validation_frame", None)
        frame = dkv.get(training)
        if frame is None:
            raise KeyError(f"no frame {training!r}")
        valid = dkv.get(valid_key) if valid_key else None
        return frame, valid

    def train(self, algo: str, **params) -> dict:
        algo = algo.lower()
        if algo not in ALGOS:
            raise KeyError(f"unknown algo {algo!r}")
        frame, valid = self._frame_pair(params)
        clean = self._coerce(params)
        model = _builder(algo)(**clean).train(frame, valid)
        return {"job": {"status": "DONE",
                        "dest": {"name": model.key}},
                "model": _model_schema(model.key, model)}

    def models(self) -> dict:
        from ..runtime import dkv
        from ..models.base import Model
        out = []
        for k in dkv.keys():
            v = dkv.get(k)
            if isinstance(v, Model):
                out.append(_model_schema(k, v))
        return {"models": out}

    def model(self, key: str) -> dict:
        from ..runtime import dkv
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        return {"models": [_model_schema(key, m)]}

    def predict(self, model_key: str, frame_key: str, **kw) -> dict:
        from ..runtime import dkv
        m = dkv.get(model_key)
        fr = dkv.get(frame_key)
        if m is None or fr is None:
            raise KeyError(f"missing {model_key!r} or {frame_key!r}")
        pred = m.predict(fr)
        dest = kw.get("predictions_frame") or f"{model_key}_preds"
        pred.key = dest
        from ..runtime import dkv as _dkv
        _dkv.put(dest, pred)
        return {"predictions_frame": {"name": dest},
                "frames": [_frame_schema(dest, pred)]}

    # ------------------------------------------------------- online serving
    def predict_realtime(self, model_key: str, **kw) -> dict:
        """POST /3/Predictions/realtime/{model} — online row scoring
        through the packed-ensemble micro-batcher (h2o3_tpu/serving/).

        Body: ``{"row": {...}}`` or ``{"rows": [{...}, ...]}``; optional
        ``score_mode`` ("packed" | "ref" | "check") for parity drills.
        """
        from .. import serving
        entry = serving.ensure_published(model_key)
        rows = kw.get("rows")
        if rows is None and "row" in kw:
            rows = [kw["row"]]
        if not rows or not isinstance(rows, list):
            raise ValueError("realtime predict needs 'row' (object) or "
                             "'rows' (list of objects)")
        out = entry.predict_rows(rows, score_mode=kw.get("score_mode"))
        preds = []
        for i in range(len(rows)):
            p = {"predict": out["predict"][i]}
            if "probabilities" in out:
                p["probabilities"] = out["probabilities"][i]
            preds.append(p)
        return {"model_id": {"name": model_key}, "predictions": preds}

    def publish_realtime(self, model_key: str, **kw) -> dict:
        """POST /3/Predictions/realtime/{model}/warmup — pack, publish
        and AOT-warm the serving executable at model-publish time so the
        first live request never pays a compile."""
        from .. import serving
        entry = serving.publish(model_key)
        pk = entry.scorer.packed
        return {"model_id": {"name": model_key}, "published": True,
                "warmup_seconds": entry.warmup_s,
                "n_nodes": pk.n_nodes, "packed_bytes": pk.nbytes(),
                "max_batch": entry.batcher.max_batch}

    # ----------------------------------------------------------------- grids
    def grid_train(self, algo: str, **params) -> dict:
        """POST /99/Grid/{algo} — hyperparameter search
        (water/api/GridSearchHandler / hex/grid/GridSearch.java)."""
        from ..runtime import dkv
        from ..models.grid import GridSearch
        algo = algo.lower()
        if algo not in ALGOS:
            raise KeyError(f"unknown algo {algo!r}")
        frame, valid = self._frame_pair(params)
        clean = self._coerce(params)
        hyper = clean.pop("hyper_parameters", None) or {}
        criteria = clean.pop("search_criteria", None)
        sort_metric = clean.pop("sort_metric", None)
        grid = GridSearch(_builder(algo), hyper,
                          search_criteria=criteria, **clean).train(
            frame, valid, sort_metric=sort_metric)
        # Grid.__init__ registered itself in the DKV
        return self._grid_schema(grid)

    @staticmethod
    def _grid_schema(grid) -> dict:
        return {"grid_id": {"name": grid.key},
                "hyper_names": grid.hyper_names,
                "model_ids": [{"name": m.key} for m in grid.models],
                "sort_metric": grid.sort_metric,
                "summary_table": grid.sorted_metric_table(),
                # GridSchemaV99 failure_details analog: one entry per
                # member that failed to build (combo params + error)
                "failed_entries": grid.failed_entries}

    def grids(self) -> dict:
        from ..runtime import dkv
        from ..models.grid import Grid
        out = []
        for k in dkv.keys("grid"):
            v = dkv.get(k)
            if isinstance(v, Grid):
                out.append({"name": k})
        return {"grids": out}

    def grid(self, key: str) -> dict:
        from ..runtime import dkv
        g = dkv.get(key)
        if g is None:
            raise KeyError(f"no grid {key!r}")
        return self._grid_schema(g)

    # ---------------------------------------------------------------- automl
    def automl_build(self, **params) -> dict:
        """POST /99/AutoMLBuilder — run AutoML
        (ai/h2o/automl/AutoML.java:49 via AutoMLBuilderHandler)."""
        from ..runtime import dkv
        from ..automl import AutoML
        frame, valid = self._frame_pair(params)
        clean = self._coerce(params)
        project = clean.pop("project_name", None) or dkv.make_key("automl")
        aml = AutoML(**clean)
        leader = aml.train(frame, valid)
        dkv.put(f"automl_{project}", aml)
        return {"project_name": project,
                "leader": {"name": leader.key},
                "leaderboard_table": aml.leaderboard.as_table()
                if aml.leaderboard else []}

    def leaderboard(self, project: str) -> dict:
        """GET /99/Leaderboards/{project} (LeaderboardsHandler)."""
        from ..runtime import dkv
        aml = dkv.get(f"automl_{project}")
        if aml is None or aml.leaderboard is None:
            raise KeyError(f"no automl project {project!r}")
        lb = aml.leaderboard
        return {"project_name": project,
                "sort_metric": lb.sort_metric,
                "leaderboard_table": lb.as_table()}

    # ------------------------------------------------- model save / download
    def model_save(self, key: str, dir: str, **kw) -> dict:
        """POST /99/Models.bin/{model} — server-side save (h2o.save_model)."""
        from ..runtime import dkv
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        path = f"{dir.rstrip('/')}/{key}.bin" if not dir.endswith(".bin") \
            else dir
        return {"path": m.save(path)}

    def model_fetch_bin(self, key: str) -> bytes:
        """GET /3/Models.fetch.bin/{model} — binary artifact download."""
        import os
        import tempfile
        from ..runtime import dkv
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.bin")
            m.save(p)
            with open(p, "rb") as f:
                return f.read()

    def model_fetch_mojo(self, key: str) -> bytes:
        """GET /3/Models/{model}/mojo — portable scoring artifact
        (ModelsHandler.fetchMojo analog)."""
        import os
        import tempfile
        from ..runtime import dkv
        from ..export.mojo import export_mojo
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.zip")
            export_mojo(m, p)
            with open(p, "rb") as f:
                return f.read()

    def post_file(self, raw: bytes, filename: str = "upload") -> dict:
        """POST /3/PostFile — push raw file bytes to the cluster
        (water/api/PostFileHandler analog); returns the server-side path
        to feed /3/Parse."""
        import os
        import tempfile
        base = os.path.join(tempfile.gettempdir(), "h2o3_uploads")
        os.makedirs(base, exist_ok=True)
        safe = os.path.basename(filename) or "upload"
        fd, path = tempfile.mkstemp(suffix="_" + safe, dir=base)
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        return {"destination_key": path, "total_bytes": len(raw)}

    def model_upload(self, raw: bytes, **kw) -> dict:
        """POST /3/Models.upload.bin — install a client-side artifact."""
        import os
        import tempfile
        from ..models.base import Model
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.bin")
            with open(p, "wb") as f:
                f.write(raw)
            m = Model.load(p)
        return {"models": [_model_schema(m.key, m)]}

    # --------------------------------------------------------------- explain
    def varimp(self, key: str) -> dict:
        """GET /3/Models/{model}/varimp — variable importances."""
        from ..runtime import dkv
        from ..explain import _varimp_of
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        vi = _varimp_of(m) or {}
        return {"varimp": [{"variable": k, "relative_importance": float(v)}
                           for k, v in vi.items()]}

    def partial_dependence(self, **params) -> dict:
        """POST /3/PartialDependence — PD table for one column."""
        from ..runtime import dkv
        from ..explain import partial_dependence as pd_fn
        clean = self._coerce(params)
        m = dkv.get(clean["model"])
        fr = dkv.get(clean["frame"])
        if m is None or fr is None:
            raise KeyError("missing model or frame")
        out = pd_fn(m, fr, clean["column"],
                    nbins=int(clean.get("nbins", 20)))
        return {"partial_dependence": {
            k: (v.tolist() if hasattr(v, "tolist") else v)
            for k, v in out.items()}}

    # -------------------------------------------------------------- builders
    def model_builders(self, algo: Optional[str] = None) -> dict:
        """GET /3/ModelBuilders[/{algo}] — algo list + parameter metadata
        (water/api/ModelBuildersHandler; drives client codegen)."""
        schemas = {s["algo"]: s for s in self.schemas()["schemas"]}
        if algo is not None:
            a = algo.lower()
            if a not in schemas:
                raise KeyError(f"unknown algo {algo!r}")
            return {"model_builders": {a: schemas[a]}}
        return {"model_builders": schemas}

    # ------------------------------------------------------------------ jobs
    def jobs_list(self) -> dict:
        from ..runtime import dkv
        from ..runtime.job import MIRROR_PREFIX, list_jobs
        out = [j.describe() for j in list_jobs()]
        seen = {d["key"] for d in out}
        # plain status mirrors replicated from other members' jobs
        for k in dkv.keys(MIRROR_PREFIX):
            d = dkv.get(k)
            if isinstance(d, dict) and d.get("key") not in seen:
                out.append(d)
        return {"jobs": out}

    # -------------------------------------------- small utility handlers
    # (the reference's RequestServer breadth: Typeahead, CreateFrame,
    #  MissingInserter, Interactions, Tabulate, DCTTransformer, JStack,
    #  NetworkTest — water/api/*Handler.java)
    def typeahead(self, src: str = "", limit: int = 100) -> dict:
        """GET /3/Typeahead/files — filesystem path completion."""
        import glob as _glob
        import os as _os
        limit = int(limit)
        pat = src + "*" if not src.endswith("*") else src
        matches = sorted(_glob.glob(_os.path.expanduser(pat)))[:limit]
        return {"src": src, "limit": limit, "matches": matches}

    def create_frame(self, **params) -> dict:
        from ..frame.create import create_frame
        fr = create_frame(**self._coerce(params))
        return {"key": {"name": fr.key}, **_frame_schema(fr.key, fr)}

    def missing_inserter(self, dataset: str, fraction: float = 0.1,
                         seed=None) -> dict:
        from ..frame.create import insert_missing_values
        from ..runtime import dkv
        fr = dkv.get(dataset)
        if fr is None:
            raise KeyError(f"no frame {dataset!r}")
        out = insert_missing_values(
            fr, fraction=float(fraction),
            seed=int(seed) if seed is not None else None)
        return {"key": {"name": out.key}, **_frame_schema(out.key, out)}

    def interaction(self, source_frame: str, factor_columns,
                    **params) -> dict:
        from ..frame.create import interaction
        from ..runtime import dkv
        fr = dkv.get(source_frame)
        if fr is None:
            raise KeyError(f"no frame {source_frame!r}")
        if isinstance(factor_columns, str):
            factor_columns = [c for c in factor_columns.split(",") if c]
        out = interaction(fr, factor_columns, **self._coerce(params))
        return {"key": {"name": out.key}, **_frame_schema(out.key, out)}

    def tabulate(self, dataset: str, predictor: str, response: str,
                 **params) -> dict:
        from ..frame.create import tabulate
        from ..runtime import dkv
        fr = dkv.get(dataset)
        if fr is None:
            raise KeyError(f"no frame {dataset!r}")
        return tabulate(fr, predictor, response, **self._coerce(params))

    def dct_transform(self, dataset: str, dimensions,
                      **params) -> dict:
        from ..frame.create import dct_transform
        from ..runtime import dkv
        fr = dkv.get(dataset)
        if fr is None:
            raise KeyError(f"no frame {dataset!r}")
        if isinstance(dimensions, str):
            dimensions = [int(x) for x in dimensions.split(",") if x]
        out = dct_transform(fr, dimensions, **self._coerce(params))
        return {"key": {"name": out.key}, **_frame_schema(out.key, out)}

    def jstack(self) -> dict:
        from ..runtime.observability import jstack
        return {"traces": jstack()}

    def network_test(self) -> dict:
        from ..runtime.observability import network_test
        return {"results": network_test()}

    # ------------------------------------------------------------------- dkv
    def remove(self, key: str) -> dict:
        from ..runtime import dkv
        dkv.remove(key)
        return {"removed": key}

    # ---------------------------------------------------------------- rapids
    def rapids(self, ast: str, **kw) -> dict:
        """POST /99/Rapids — evaluate a Rapids expression (Rapids.java:29)."""
        from ..rapids.ast import rapids as _eval
        from ..frame.frame import Frame
        out = _eval(ast)
        if isinstance(out, Frame):
            return {"key": {"name": out.key},
                    **_frame_schema(out.key or "", out)}
        if out is None:
            return {"result": None}
        if isinstance(out, (int, float)):
            return {"scalar": out}
        return {"string": str(out)}

    def about(self) -> dict:
        """GET /3/About — effective config + extensions (AboutHandler)."""
        from ..runtime.config import config
        from ..runtime.extensions import loaded
        from .. import __version__
        return {"version": __version__, "config": config().describe(),
                "extensions": loaded()}

    # -------------------------------------------------------------- metadata
    def schemas(self) -> dict:
        """GET /3/Metadata/schemas — parameter schemas for client codegen
        (the h2o-bindings gen_python.py contract)."""
        import dataclasses
        out = []
        for algo in ALGOS:
            try:
                cls = _builder(algo)
                pcls = cls(**{}).params.__class__
            except Exception:
                import inspect
                sig = inspect.signature(_builder(algo).__init__)
                pcls = None
            fields = []
            if pcls is not None:
                for f in dataclasses.fields(pcls):
                    default = f.default
                    if default is dataclasses.MISSING:
                        default = None
                    fields.append({
                        "name": f.name,
                        "type": getattr(f.type, "__name__", str(f.type)),
                        "default": default
                        if isinstance(default, (int, float, str, bool,
                                                type(None))) else
                        list(default) if isinstance(default, (list, tuple))
                        else str(default),
                    })
            out.append({"algo": algo, "parameters": fields})
        # grid-level parameters (GridSearch's own knobs, not per-model
        # hyperparameters) — introspected so client codegen tracks the
        # server, exactly like the builder schemas above
        import inspect
        from ..models.grid import GridSearch
        gfields = []
        for name, p in inspect.signature(
                GridSearch.__init__).parameters.items():
            if name in ("self", "builder_cls", "hyper_params",
                        "base_params") or p.kind in (
                    inspect.Parameter.VAR_KEYWORD,
                    inspect.Parameter.VAR_POSITIONAL):
                continue
            default = (None if p.default is inspect.Parameter.empty
                       else p.default)
            gfields.append({
                "name": name,
                "type": type(default).__name__ if default is not None
                else "object",
                "default": default if isinstance(
                    default, (int, float, str, bool, type(None)))
                else str(default)})
        return {"schemas": out, "grid": {"parameters": gfields}}

    # --------------------------------------------------------------- export
    def frame_summary(self, key: str) -> dict:
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        return {"frames": [{**_frame_schema(key, fr),
                            "summary": fr.summary()}]}

    def frame_data(self, key: str, row_offset=0, row_count=100, **kw) -> dict:
        """GET /3/Frames/{k}/data — paged column data (Flow grid contract)."""
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        lo = int(row_offset)
        hi = min(fr.nrows, lo + int(row_count))
        cols = {}
        for n, v in zip(fr.names, fr.vecs):
            col = v.decoded()[lo:hi]
            cols[n] = [None if (x is None or (isinstance(x, float)
                                              and np.isnan(x))) else x
                       for x in col.tolist()]
        return {"frame_id": {"name": key}, "row_offset": lo,
                "row_count": hi - lo, "data": cols}

    def export_frame(self, key: str, path: str, **kw) -> dict:
        from ..runtime import dkv
        from ..frame.parse import export_file
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        export_file(fr, path)
        return {"job": {"status": "DONE"}, "path": path}

    def import_files(self, path: str, **kw) -> dict:
        """GET /3/ImportFiles — expand globs/dirs (ImportFilesHandler)."""
        from ..frame.parse import _expand_paths
        files = _expand_paths(path)
        return {"files": files, "destination_frames": files}

    # ------------------------------------------------ round-5 route breadth
    def frame_columns(self, key: str) -> dict:
        """GET /3/Frames/{id}/columns (FramesHandler.columns)."""
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        cols = []
        for n, v in zip(fr.names, fr.vecs):
            cols.append({"label": n, "type": v.type,
                         "domain": v.domain,
                         "missing_count": int(v.rollups().nmissing)
                         if v.is_numeric or v.type == "cat" else 0})
        return {"frame_id": {"name": key}, "columns": cols}

    def frame_column_summary(self, key: str, col: str) -> dict:
        """GET /3/Frames/{id}/columns/{col}/summary."""
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        v = fr.vec(col)
        out = {"label": col, "type": v.type, "domain": v.domain}
        if v.is_numeric:
            r = v.rollups()
            out.update({"mins": [r.vmin], "maxs": [r.vmax], "mean": r.mean,
                        "sigma": r.sigma, "missing_count": r.nmissing})
        return {"frames": [{"columns": [out]}]}

    def frame_light(self, key: str) -> dict:
        """GET /3/Frames/{id}/light — metadata without data preview."""
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        return {"frames": [{"frame_id": {"name": key}, "rows": fr.nrows,
                            "column_count": fr.ncols,
                            "columns": [{"label": n} for n in fr.names]}]}

    def download_dataset(self, frame_id: str, **kw) -> bytes:
        """GET /3/DownloadDataset — frame as CSV bytes."""
        import io as _io
        from ..runtime import dkv
        from ..frame.parse import export_file
        fr = dkv.get(frame_id)
        if fr is None:
            raise KeyError(f"no frame {frame_id!r}")
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".csv", delete=False) as f:
            tmp = f.name
        try:
            export_file(fr, tmp)
            return open(tmp, "rb").read()
        finally:
            os.unlink(tmp)

    def model_java(self, key: str) -> bytes:
        """GET /3/Models.java/{id} — POJO source download."""
        from ..runtime import dkv
        from ..export.pojo import export_pojo
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".java",
                                         delete=False) as f:
            tmp = f.name
        try:
            export_pojo(m, tmp)
            return open(tmp, "rb").read()
        finally:
            os.unlink(tmp)

    def model_metrics_stored(self, key: str) -> dict:
        """GET /3/ModelMetrics/models/{id} — training/cv metrics."""
        from ..runtime import dkv
        m = dkv.get(key)
        if m is None:
            raise KeyError(f"no model {key!r}")
        out = []
        for kind, mm in (("training", m.training_metrics),
                         ("validation", m.validation_metrics),
                         ("cross_validation",
                          m.cross_validation_metrics)):
            if mm is None:
                continue
            d = mm.describe() if hasattr(mm, "describe") else (
                mm if isinstance(mm, dict) else {})
            out.append({"kind": kind,
                        **{k: v for k, v in d.items()
                           if isinstance(v, (int, float, str))}})
        return {"model_metrics": out}

    def word2vec_synonyms(self, model: str, word: str,
                          count: int = 20, **kw) -> dict:
        """GET /3/Word2VecSynonyms (Word2VecHandler.findSynonyms)."""
        from ..runtime import dkv
        m = dkv.get(model)
        if m is None:
            raise KeyError(f"no model {model!r}")
        syn = m.find_synonyms(word, int(count))
        return {"synonyms": list(syn.keys()),
                "scores": [float(s) for s in syn.values()]}

    def word2vec_transform(self, model: str, words_frame: str,
                           aggregate_method: str = "NONE", **kw) -> dict:
        """GET /3/Word2VecTransform — embed a string column."""
        from ..runtime import dkv
        m = dkv.get(model)
        fr = dkv.get(words_frame)
        if m is None or fr is None:
            raise KeyError(f"missing {model!r} or {words_frame!r}")
        out = m.transform(fr, aggregate_method=aggregate_method.lower())
        out.key = dkv.make_key("w2v_transform")
        dkv.put(out.key, out)
        return {"vectors_frame": {"name": out.key}}

    def grid_export(self, key: str, export_dir: str, **kw) -> dict:
        """POST /99/Grids/{id}/export (GridImportExportHandler)."""
        from ..runtime import dkv
        g = dkv.get(key)
        if g is None:
            raise KeyError(f"no grid {key!r}")
        g.save(f"{export_dir.rstrip('/')}/{key}")
        return {"grid_id": key, "export_dir": export_dir}

    def grid_import(self, grid_path: str, **kw) -> dict:
        """POST /99/Grids.bin/import."""
        from ..models.grid import Grid
        g = Grid.load(grid_path)
        return {"grid_id": g.key, "n_models": len(g.models)}

    def capabilities(self) -> dict:
        """GET /3/Capabilities (CapabilitiesHandler)."""
        from ..runtime.extensions import loaded
        return {"capabilities": [{"name": e} for e in loaded()]}

    def endpoints(self) -> dict:
        """GET /3/Metadata/endpoints — the live route table."""
        out = []
        for verb, table in (("GET", _Handler.routes_get),
                            ("POST", _Handler.routes_post),
                            ("DELETE", _Handler.routes_delete)):
            for pat in table:
                out.append({"http_method": verb, "url_pattern": pat})
        return {"routes": out, "count": len(out)}

    def init_id(self) -> dict:
        """GET /3/InitID — session handshake (h2o-py connection boot)."""
        import uuid
        return {"session_key": f"_sid_{uuid.uuid4().hex[:12]}"}

    def session_start(self) -> dict:
        """POST /4/sessions (the /4 tier session API)."""
        import uuid
        return {"session_key": f"_sid_{uuid.uuid4().hex[:12]}"}

    def ping(self) -> dict:
        """GET /3/Ping — liveness + cloud health (PingHandler)."""
        from ..runtime.cluster import cluster
        cl = cluster()
        return {"cloud_healthy": True,
                "n_devices": len(getattr(cl, "devices", []) or [1])}

    def garbage_collect(self) -> dict:
        """POST /3/GarbageCollect (GarbageCollectHandler)."""
        import gc
        gc.collect()
        import jax
        jax.clear_caches()
        return {"status": "done"}

    def log_and_echo(self, message: str = "", **kw) -> dict:
        """POST /3/LogAndEcho — write into the server log."""
        from ..runtime.observability import record
        record("log_and_echo", message=message)
        return {"message": message}

    def recovery_resume(self, recovery_dir: str, **kw) -> dict:
        """POST /3/Recovery/resume (RecoveryHandler — Recovery.java:72)."""
        from ..runtime.recovery import resume
        resumed = resume(recovery_dir)
        return {"resumed": [getattr(m, "key", str(m)) for m in resumed]}

    def recovery_status(self, recovery_dir: str = "", **kw) -> dict:
        """GET /3/Recovery — journal + progress-snapshot state: which jobs
        are resumable, from which snapshot/cursor (operator view of the
        survivable-training pipeline; defaults to H2O3_TPU_RECOVERY_DIR)."""
        from ..runtime import dkv
        from ..runtime.recovery import journal_status
        entries = journal_status(recovery_dir or None)
        return {"recovery_dir": recovery_dir or
                os.environ.get("H2O3_TPU_RECOVERY_DIR", ""),
                "entries": entries,
                "resumable": sum(1 for e in entries
                                 if e.get("status") == "running"),
                # resumes that silently weren't: entries whose frame
                # re-import failed and trained from scratch (or skipped)
                "downgraded": sum(1 for e in entries if e.get("downgrade")),
                # coordinator durability/fencing: epoch, WAL generation/
                # records, dedup window — the restart-runbook facts
                "coordinator": dkv.wal_stats()}

    def scheduler_status(self, **kw) -> dict:
        """GET /3/Scheduler — the cluster scheduler's live view: chip
        capacity/usage, admission queue, running assignments with
        budgets and per-tenant fair-share usage, elastic-membership
        state (known hosts, armed rebuild) and the flap quarantine."""
        from ..runtime.job import scheduler
        return {"scheduler": scheduler().describe()}

    _nps: dict = {}

    def nps_put(self, category: str, name: str, value: str = "",
                **kw) -> dict:
        """POST /3/NodePersistentStorage/{cat}/{name}."""
        self._nps[(category, name)] = value
        return {"category": category, "name": name}

    def nps_get(self, category: str, name: str) -> dict:
        """GET /3/NodePersistentStorage/{cat}/{name}."""
        if (category, name) not in self._nps:
            raise KeyError(f"no NPS entry {category}/{name}")
        return {"category": category, "name": name,
                "value": self._nps[(category, name)]}

    def nps_list(self, category: str) -> dict:
        """GET /3/NodePersistentStorage/{cat}."""
        return {"entries": [{"category": c, "name": n}
                            for (c, n) in self._nps
                            if c == category]}

    def import_sql_table(self, connection_url: str, table: str = "",
                         select_query: str = "", username: str = "",
                         password: str = "", **kw) -> dict:
        """POST /99/ImportSQLTable (water/jdbc SQLManager analog)."""
        from ..frame.sql import import_sql_table
        fr = import_sql_table(connection_url, table=table or None,
                              select_query=select_query or None,
                              username=username or None,
                              password=password or None)
        return {"frames": [{"frame_id": {"name": fr.key}}]}

    def frame_chunks(self, key: str) -> dict:
        """GET /3/FrameChunks — per-shard row layout (ChunkSummary)."""
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        from ..runtime.cluster import cluster
        cl = cluster()
        ndev = max(len(cl.mesh.devices.flat), 1) \
            if hasattr(cl, "mesh") else 1
        per = -(-fr.nrows // ndev)
        chunks = [{"chunk_id": i,
                   "row_count": min(per, max(fr.nrows - i * per, 0))}
                  for i in range(ndev)]
        return {"frame_id": {"name": key}, "chunks": chunks}

    def shutdown(self, **kw) -> dict:
        """POST /3/Shutdown — the reference stops the cloud; here the
        server thread stops accepting after the in-flight reply."""
        import threading as _t
        srv = getattr(self, "_server_ref", None)
        if srv is not None:
            _t.Thread(target=srv.stop, daemon=True).start()
        return {"status": "shutting down"}

    def timeline(self, limit=500, **kw) -> dict:
        """GET /3/Timeline[?limit=N] — recent runtime events
        (TimelineHandler:12) plus the monotonic counters (WAL records/
        bytes, dedup hits), per-node sections built from the telemetry
        shipped on heartbeat stamps, and span events stitched into trace
        trees (local + shipped, matched by trace_id)."""
        from ..runtime import observability as obs
        limit = int(limit)
        events = obs.timeline_events(limit)
        nodes = {}
        all_events = list(events)
        try:
            me = obs.node_name()
            for node, stamp in obs.cluster_stamps().items():
                if not isinstance(stamp, dict):
                    continue
                shipped = stamp.get("events") or []
                nodes[node] = {
                    "ts": stamp.get("ts"),
                    "pid": stamp.get("pid"),
                    "metric_series": len(stamp.get("metrics") or []),
                    "events": shipped[-limit:] if node != me else [],
                }
                if node != me:
                    all_events.extend(shipped)
        except Exception:                # noqa: BLE001 — local-only view
            pass
        return {"events": events, "counters": obs.counters(),
                "nodes": nodes, "traces": obs.trace_forest(all_events)}

    def prometheus(self) -> str:
        """GET /metrics — Prometheus text exposition: this process's
        registry plus every heartbeating node's shipped snapshot.

        Device-memory gauges refresh at SCRAPE time (not just on
        heartbeat beats), so ``device_memory_bytes`` is current however
        infrequently the beat thread runs."""
        from ..runtime import cluster
        from ..runtime.observability import render_prometheus
        try:
            cluster.sample_memory_gauges()
        except Exception:                # noqa: BLE001 — scrape never 500s
            pass
        return render_prometheus(cluster=True)

    def profiler_start(self, logdir: str = "", **kw) -> dict:
        """POST /3/Profiler/start — begin an on-demand jax.profiler device
        trace (TensorBoard-viewable).  Idempotent: a start while a capture
        is live is a recorded no-op, not a 500."""
        from ..runtime import observability as obs
        if not logdir:
            logdir = os.path.join(tempfile.gettempdir(),
                                  f"h2o3_tpu_trace_{os.getpid()}")
        started = obs.start_device_trace(logdir)
        return {"started": started, "active": obs.profiler_active(),
                "logdir": logdir}

    def profiler_stop(self, **kw) -> dict:
        """POST /3/Profiler/stop — stop the live device trace (no-op when
        none is running)."""
        from ..runtime import observability as obs
        stopped = obs.stop_device_trace()
        return {"stopped": stopped, "active": obs.profiler_active()}

    def profiler_memory(self) -> bytes:
        """GET /3/Profiler/memory — pprof-format device memory profile
        (``jax.profiler.device_memory_profile``), served as octet-stream."""
        import jax.profiler
        return jax.profiler.device_memory_profile()

    def compile_ledger(self) -> dict:
        """GET /3/Profiler/compiles — the compile ledger as JSON (same
        data the ``compile_seconds``/``program_*`` series expose)."""
        from ..runtime import xprof
        return xprof.ledger_snapshot()

    def autotune_table(self) -> dict:
        """GET /3/Profiler/autotune — the autotuner's decision table:
        program signature -> chosen knobs, decision source, and
        predicted vs measured tree-phase seconds."""
        from ..runtime import autotune
        return autotune.decision_table()

    def logs(self, limit=500, **kw) -> dict:
        from ..runtime.observability import recent_logs
        return {"log": recent_logs(int(limit))}

    def job(self, key: str) -> dict:
        from ..runtime.job import list_jobs
        for j in list_jobs():
            if j.key == key:
                return {"jobs": [j.describe()]}
        raise KeyError(f"no job {key!r}")

    def model_metrics(self, model_key: str, frame_key: str, **kw) -> dict:
        from ..runtime import dkv
        m = dkv.get(model_key)
        fr = dkv.get(frame_key)
        if m is None or fr is None:
            raise KeyError(f"missing {model_key!r} or {frame_key!r}")
        perf = m.model_performance(fr)
        d = perf.describe() if hasattr(perf, "describe") else {}
        return {"model_metrics": [{k: v for k, v in d.items()
                                   if isinstance(v, (int, float, str))}]}

    def scoring_history(self, model_key: str) -> dict:
        from ..runtime import dkv
        m = dkv.get(model_key)
        if m is None:
            raise KeyError(f"no model {model_key!r}")
        return {"scoring_history": getattr(m, "scoring_history", [])}

    def split_frame(self, key: str, ratios="[0.75]", seed=0,
                    **kw) -> dict:
        from ..runtime import dkv
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        rr = json.loads(ratios) if isinstance(ratios, str) else ratios
        pieces = fr.split_frame([float(r) for r in rr], seed=int(seed))
        out = []
        for i, p in enumerate(pieces):
            k = f"{key}_part{i}"
            p.key = k
            dkv.put(k, p)
            out.append(k)
        return {"destination_frames": out}


class H2OServer:
    """In-process REST server — H2OApp/Jetty boot analog.

    ``auth`` is an api.auth SPI spec ("static:u:p", "hash_file:/path",
    "cmd:/bin/verifier", "module:pkg.attr") or an Authenticator instance;
    default comes from env ``H2O3_TPU_AUTH``.  ``https=True`` wraps the
    listener in TLS using ``https_cert``/``https_key`` PEMs or, absent
    those, the internode TLS pair (H2O3_TPU_TLS_CERT/KEY) — the
    client-facing counterpart of h2o-security's Jetty HTTPS flags.
    """

    def __init__(self, port: Optional[int] = None, username: str = "",
                 password: str = "", auth=None, https: bool = False,
                 https_cert: Optional[str] = None,
                 https_key: Optional[str] = None):
        from . import auth as _authmod
        self.api = Api()
        if password and not username:
            raise ValueError("basic auth requires a username with the "
                             "password")
        if auth is None and username:
            auth = _authmod.StaticAuthenticator(username, password)
        if auth is None and os.environ.get("H2O3_TPU_AUTH"):
            auth = os.environ["H2O3_TPU_AUTH"]
        self._authn = _authmod.resolve_authenticator(auth)
        self._sessions = _authmod.SessionStore()
        self._https = https or bool(https_cert)
        self._https_cert, self._https_key = https_cert, https_key
        _Handler.routes_get = {
            r"/3/Cloud": lambda a: a.cloud(),
            r"/3/Frames": lambda a: a.frames(),
            r"/3/Frames/([^/]+)": lambda a, k: a.frame(k),
            r"/3/Frames/([^/]+)/summary": lambda a, k: a.frame_summary(k),
            r"/3/Frames/([^/]+)/data": lambda a, k, **kw:
                a.frame_data(k, **kw),
            r"/3/Models": lambda a: a.models(),
            r"/3/Models/([^/]+)": lambda a, k: a.model(k),
            r"/3/Models/([^/]+)/scoring_history": lambda a, k:
                a.scoring_history(k),
            r"/3/Models/([^/]+)/varimp": lambda a, k: a.varimp(k),
            r"/3/Models/([^/]+)/mojo": lambda a, k: a.model_fetch_mojo(k),
            r"/3/Models\.fetch\.bin/([^/]+)": lambda a, k:
                a.model_fetch_bin(k),
            r"/3/ModelBuilders": lambda a: a.model_builders(),
            r"/3/ModelBuilders/([^/]+)": lambda a, algo:
                a.model_builders(algo),
            r"/99/Grids": lambda a: a.grids(),
            r"/99/Grids/([^/]+)": lambda a, k: a.grid(k),
            r"/99/Leaderboards/([^/]+)": lambda a, p: a.leaderboard(p),
            r"/3/Jobs": lambda a: a.jobs_list(),
            r"/3/Jobs/([^/]+)": lambda a, k: a.job(k),
            r"/3/ImportFiles": lambda a, **kw: a.import_files(**kw),
            r"/3/Metadata/schemas": lambda a: a.schemas(),
            r"/3/About": lambda a: a.about(),
            r"/3/Timeline": lambda a, **kw: a.timeline(**kw),
            r"/3/Logs": lambda a, **kw: a.logs(**kw),
            r"/metrics": lambda a: a.prometheus(),
            r"/3/Typeahead/files": lambda a, **kw: a.typeahead(**kw),
            r"/3/JStack": lambda a: a.jstack(),
            r"/3/NetworkTest": lambda a: a.network_test(),
            r"/3/Frames/([^/]+)/columns": lambda a, k: a.frame_columns(k),
            r"/3/Frames/([^/]+)/columns/([^/]+)/summary":
                lambda a, k, c: a.frame_column_summary(k, c),
            r"/3/Frames/([^/]+)/light": lambda a, k: a.frame_light(k),
            r"/3/DownloadDataset": lambda a, **kw:
                a.download_dataset(**kw),
            r"/3/Models\.java/([^/]+)": lambda a, k: a.model_java(k),
            r"/3/ModelMetrics/models/([^/]+)":
                lambda a, k: a.model_metrics_stored(k),
            r"/3/Word2VecSynonyms": lambda a, **kw:
                a.word2vec_synonyms(**kw),
            r"/3/Word2VecTransform": lambda a, **kw:
                a.word2vec_transform(**kw),
            r"/3/Capabilities": lambda a: a.capabilities(),
            r"/3/Metadata/endpoints": lambda a: a.endpoints(),
            r"/3/InitID": lambda a: a.init_id(),
            r"/3/Ping": lambda a: a.ping(),
            r"/3/NodePersistentStorage/([^/]+)/([^/]+)":
                lambda a, c, n: a.nps_get(c, n),
            r"/3/NodePersistentStorage/([^/]+)":
                lambda a, c: a.nps_list(c),
            r"/3/FrameChunks/([^/]+)": lambda a, k: a.frame_chunks(k),
            r"/3/Recovery": lambda a, **kw: a.recovery_status(**kw),
            r"/3/Scheduler": lambda a, **kw: a.scheduler_status(**kw),
            r"/3/Profiler/memory": lambda a: a.profiler_memory(),
            r"/3/Profiler/compiles": lambda a: a.compile_ledger(),
            r"/3/Profiler/autotune": lambda a: a.autotune_table(),
        }
        _Handler.routes_post = {
            r"/3/Parse": lambda a, **kw: a.parse(**kw),
            r"/3/ModelBuilders/([^/]+)": lambda a, algo, **kw:
                a.train(algo, **kw),
            r"/3/Predictions/models/([^/]+)/frames/([^/]+)":
                lambda a, m, f, **kw: a.predict(m, f, **kw),
            r"/3/Predictions/realtime/([^/]+)":
                lambda a, m, **kw: a.predict_realtime(m, **kw),
            r"/3/Predictions/realtime/([^/]+)/warmup":
                lambda a, m, **kw: a.publish_realtime(m, **kw),
            r"/99/Rapids": lambda a, **kw: a.rapids(**kw),
            r"/3/Frames/([^/]+)/export": lambda a, k, **kw:
                a.export_frame(k, **kw),
            r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)":
                lambda a, m, f, **kw: a.model_metrics(m, f, **kw),
            r"/3/SplitFrame": lambda a, **kw: a.split_frame(**kw),
            r"/99/Grid/([^/]+)": lambda a, algo, **kw:
                a.grid_train(algo, **kw),
            r"/99/AutoMLBuilder": lambda a, **kw: a.automl_build(**kw),
            r"/99/Models\.bin/([^/]+)": lambda a, k, **kw:
                a.model_save(k, **kw),
            r"/3/PartialDependence": lambda a, **kw:
                a.partial_dependence(**kw),
            r"/3/CreateFrame": lambda a, **kw: a.create_frame(**kw),
            r"/3/MissingInserter": lambda a, **kw:
                a.missing_inserter(**kw),
            r"/3/Interaction": lambda a, **kw: a.interaction(**kw),
            r"/99/Tabulate": lambda a, **kw: a.tabulate(**kw),
            r"/99/DCTTransformer": lambda a, **kw: a.dct_transform(**kw),
            r"/99/Grids/([^/]+)/export": lambda a, k, **kw:
                a.grid_export(k, **kw),
            r"/99/Grids\.bin/import": lambda a, **kw: a.grid_import(**kw),
            r"/4/sessions": lambda a, **kw: a.session_start(),
            r"/3/GarbageCollect": lambda a, **kw: a.garbage_collect(),
            r"/3/LogAndEcho": lambda a, **kw: a.log_and_echo(**kw),
            r"/3/Recovery/resume": lambda a, **kw:
                a.recovery_resume(**kw),
            r"/3/NodePersistentStorage/([^/]+)/([^/]+)":
                lambda a, c, n, **kw: a.nps_put(c, n, **kw),
            r"/99/ImportSQLTable": lambda a, **kw:
                a.import_sql_table(**kw),
            r"/3/Shutdown": lambda a, **kw: a.shutdown(**kw),
            r"/3/Profiler/start": lambda a, **kw: a.profiler_start(**kw),
            r"/3/Profiler/stop": lambda a, **kw: a.profiler_stop(**kw),
        }
        _Handler.routes_delete = {
            r"/3/DKV/([^/]+)": lambda a, k: a.remove(k),
        }
        if port is None:
            from ..runtime.config import config
            port = config().port
        self.httpd = _Server(("127.0.0.1", port), _Handler)
        self.httpd.api = self.api
        self.api._server_ref = self
        self.httpd.authenticator = self._authn
        self.httpd.sessions = self._sessions
        if self._https:
            import ssl
            from ..runtime.config import config
            cert = self._https_cert or config().tls_cert
            key = self._https_key or config().tls_key
            if not (cert and key):
                raise ValueError(
                    "https=True needs https_cert/https_key PEMs or "
                    "H2O3_TPU_TLS_CERT/H2O3_TPU_TLS_KEY in the env")
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert, key)
            # per-connection wrap with a deferred handshake: the TLS
            # handshake then runs in the HANDLER thread (first read),
            # not the accept loop — one stalled client cannot freeze
            # the listener (the handler's socket timeout bounds it)
            self.httpd.ssl_context = ctx
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "H2OServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        from ..runtime.config import config
        self.httpd.shutdown()               # stop accepting new requests
        # bounded drain: in-flight handlers get to finish their reply
        # (the /3/Shutdown response itself rides this grace window)
        left = self.httpd.drain(config().rest_drain_timeout_s)
        if left:
            from ..runtime.observability import log
            log.warning("REST shutdown: %d request handler(s) still "
                        "running after %.1fs drain", left,
                        config().rest_drain_timeout_s)
        self.httpd.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self._https else "http"
        return f"{scheme}://127.0.0.1:{self.port}"


def start_server(port: int = 0, username: str = "", password: str = "",
                 **kw) -> H2OServer:
    """Boot the REST layer on an in-process runtime (port 0 = ephemeral).

    Extra keywords (auth=, https=, https_cert=, https_key=) pass through
    to H2OServer — see its docstring for the authn/TLS surface."""
    return H2OServer(port=port, username=username,
                     password=password, **kw).start()
