"""Flow: an interactive single-page workbench over the REST API.

Reference: ``h2o-web``'s Flow notebook UI (assist, import, parse, build
model, predict from the browser).  This is a dependency-free SPA served
inline and driven purely by the same /3 and /99 endpoints every client
uses: import/parse, frame inspect/summary/split, assisted model building
(algo list + parameter metadata from /3/ModelBuilders), predictions,
Rapids expressions, AutoML with leaderboard, variable importances,
partial dependence, artifact downloads, and the live cloud/jobs/timeline
dashboards.
"""

FLOW_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>h2o3_tpu Flow</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1c2b33}
 header{background:#12333d;color:#fff;padding:10px 20px;font-size:18px;display:flex;align-items:center}
 header small{opacity:.7;margin-left:12px;font-size:13px}
 main{padding:16px 20px;display:grid;gap:16px;grid-template-columns:1fr 1fr}
 section{background:#fff;border:1px solid #dde3e8;border-radius:8px;padding:12px 16px}
 h2{font-size:14px;text-transform:uppercase;letter-spacing:.06em;color:#5b6b73;margin:0 0 8px}
 table{border-collapse:collapse;width:100%;font-size:13px}
 td,th{border-bottom:1px solid #eef1f4;padding:4px 8px;text-align:left}
 th{color:#5b6b73;font-weight:600}
 tr:hover{background:#f2f7fa}
 pre{background:#f2f4f6;padding:8px;border-radius:6px;overflow:auto;font-size:12px;max-height:340px}
 .pill{display:inline-block;background:#e4f0ee;border-radius:10px;padding:1px 8px;font-size:12px}
 #detail{grid-column:1 / -1}
 a{color:#176d81;cursor:pointer;text-decoration:none}
 input,select,textarea,button{font:inherit;font-size:13px;margin:2px 4px 2px 0;
   border:1px solid #c5cfd6;border-radius:5px;padding:4px 6px;background:#fff}
 button{background:#176d81;color:#fff;border:none;cursor:pointer;padding:5px 12px}
 button:hover{background:#12333d}
 textarea{width:100%;box-sizing:border-box;font-family:ui-monospace,monospace}
 .err{color:#b3261e;white-space:pre-wrap;font-size:12px}
 label{font-size:12px;color:#5b6b73;margin-right:2px}
</style></head><body>
<header>h2o3_tpu Flow<small id="cloud"></small></header>
<main>
 <section>
  <h2>Import / Parse</h2>
  <label>path/glob</label><input id="imp_path" size="38" placeholder="/data/train*.csv">
  <label>as</label><input id="imp_dest" size="12" placeholder="frame name">
  <button onclick="doImport()">import</button>
  <div id="imp_err" class="err"></div>
  <h2 style="margin-top:12px">Rapids</h2>
  <input id="rapids_expr" size="50" placeholder="(mean (cols train 'x'))">
  <button onclick="doRapids()">run</button>
  <div id="rapids_err" class="err"></div>
 </section>
 <section>
  <h2>Build Model (assist)</h2>
  <label>algo</label><select id="bm_algo" onchange="fillParams()"></select>
  <label>frame</label><select id="bm_frame" onchange="fillCols()"></select>
  <label>response</label><select id="bm_resp"></select>
  <br><label>params (JSON)</label>
  <textarea id="bm_params" rows="3">{"seed": 1}</textarea>
  <button onclick="doTrain()">train</button>
  <button onclick="doAutoML()">run AutoML</button>
  <div id="bm_err" class="err"></div>
 </section>
 <section><h2>Frames</h2><table id="frames"></table></section>
 <section><h2>Models</h2><table id="models"></table></section>
 <section><h2>Jobs</h2><table id="jobs"></table></section>
 <section><h2>Timeline</h2><table id="timeline"></table></section>
 <section id="detail"><h2 id="dtitle">Detail</h2><pre id="dbody">import a frame, then train…</pre></section>
</main>
<script>
const J = async p => { const r = await fetch(p); return r.json(); };
const P = async (p, body) => {
  const r = await fetch(p, {method:'POST', headers:{'Content-Type':'application/json'},
                            body: JSON.stringify(body||{})});
  const out = await r.json();
  if (!r.ok) throw new Error(out.error || r.statusText);
  return out;
};
const el = id => document.getElementById(id);
const esc = s => String(s).replace(/[&<>"'`]/g, c => ({'&':'&amp;','<':'&lt;','>':'&gt;','\"':'&quot;',"'":'&#39;','`':'&#96;'}[c]));
const enc = encodeURIComponent;
function detail(title, obj){
  el('dtitle').textContent = title;
  el('dbody').textContent = typeof obj === 'string' ? obj : JSON.stringify(obj, null, 2);
}
async function show(title, path){ detail(title, await J(path)); }
let frameCache = [];
async function doImport(){
  el('imp_err').textContent = '';
  try {
    const out = await P('/3/Parse', {path: el('imp_path').value,
                                     destination_frame: el('imp_dest').value || null});
    detail('parsed ' + out.destination_frame.name, out);
    refresh();
  } catch(e){ el('imp_err').textContent = e.message; }
}
async function doRapids(){
  el('rapids_err').textContent = '';
  try { detail('rapids', await P('/99/Rapids', {ast: el('rapids_expr').value})); refresh(); }
  catch(e){ el('rapids_err').textContent = e.message; }
}
async function doTrain(){
  el('bm_err').textContent = '';
  try {
    const params = JSON.parse(el('bm_params').value || '{}');
    params.training_frame = el('bm_frame').value;
    if (el('bm_resp').value) params.response_column = el('bm_resp').value;
    const algo = el('bm_algo').value;
    detail('training ' + algo + '…', 'working');
    const out = await P('/3/ModelBuilders/' + enc(algo), params);
    detail('trained ' + out.model.model_id.name, out.model);
    refresh();
  } catch(e){ el('bm_err').textContent = e.message; detail('train failed', e.message); }
}
async function doAutoML(){
  el('bm_err').textContent = '';
  try {
    const params = JSON.parse(el('bm_params').value || '{}');
    params.training_frame = el('bm_frame').value;
    if (el('bm_resp').value) params.response_column = el('bm_resp').value;
    if (!params.max_models) params.max_models = 5;
    detail('automl running…', 'working');
    const out = await P('/99/AutoMLBuilder', params);
    detail('automl leader ' + out.leader.name, out);
    refresh();
  } catch(e){ el('bm_err').textContent = e.message; }
}
async function doPredict(model){
  const frame = prompt('predict frame key', el('bm_frame').value);
  if (!frame) return;
  try {
    const out = await P('/3/Predictions/models/' + enc(model) + '/frames/' + enc(frame), {});
    await show('predictions ' + out.predictions_frame.name,
               '/3/Frames/' + enc(out.predictions_frame.name) + '/data?row_count=20');
    refresh();
  } catch(e){ detail('predict failed', e.message); }
}
async function doPD(model){
  const col = prompt('partial dependence column');
  if (!col) return;
  try { detail('pd ' + model + ' / ' + col,
               await P('/3/PartialDependence', {model: model, frame: el('bm_frame').value, column: col})); }
  catch(e){ detail('pd failed', e.message); }
}
async function doSplit(frame){
  const r = prompt('split ratio (0-1)', '0.75');
  if (!r) return;
  try { detail('split ' + frame, await P('/3/SplitFrame', {key: frame, ratios: JSON.stringify([+r])})); refresh(); }
  catch(e){ detail('split failed', e.message); }
}
async function doDelete(key){
  await fetch('/3/DKV/' + enc(key), {method:'DELETE'});
  refresh();
}
async function fillCols(){
  const f = frameCache.find(x => x.frame_id.name === el('bm_frame').value);
  el('bm_resp').innerHTML = '<option value=""></option>' + (f ? f.columns.map(c =>
    `<option>${esc(c.label)}</option>`).join('') : '');
}
async function fillParams(){
  try {
    const mb = await J('/3/ModelBuilders/' + enc(el('bm_algo').value));
    const ps = Object.values(mb.model_builders)[0].parameters.slice(0, 40);
    el('bm_params').placeholder = ps.map(p => p.name).join(', ');
  } catch(e) {}
}
async function refresh(){
  const c = await J('/3/Cloud');
  el('cloud').textContent = `${c.platform} · ${JSON.stringify(c.mesh_shape)} · ${c.cloud_size} process(es) · ${c.cloud_healthy ? 'healthy' : 'DEGRADED'}`;
  const fr = await J('/3/Frames');
  frameCache = fr.frames;
  const selected = el('bm_frame').value;
  el('bm_frame').innerHTML = fr.frames.map(f =>
    `<option ${f.frame_id.name===selected?'selected':''}>${esc(f.frame_id.name)}</option>`).join('');
  if (!selected && fr.frames.length) fillCols();
  el('frames').innerHTML = '<tr><th>frame</th><th>rows</th><th>cols</th><th>actions</th></tr>' +
    fr.frames.map(f => `<tr><td>${esc(f.frame_id.name)}</td><td>${f.rows}</td>
      <td>${f.columns.length}</td>
      <td><a onclick="show('frame ${esc(f.frame_id.name)}','/3/Frames/${enc(f.frame_id.name)}/data?row_count=20')">data</a>
          <a onclick="show('summary ${esc(f.frame_id.name)}','/3/Frames/${enc(f.frame_id.name)}/summary')">summary</a>
          <a onclick="doSplit('${esc(f.frame_id.name)}')">split</a>
          <a onclick="doDelete('${esc(f.frame_id.name)}')">✕</a></td></tr>`).join('');
  const mo = await J('/3/Models');
  el('models').innerHTML = '<tr><th>model</th><th>algo</th><th>metrics</th><th>actions</th></tr>' +
    mo.models.map(m => {
      const t = m.training_metrics || {};
      const head = ['auc','rmse','logloss','r2'].filter(k => t[k] != null)
        .map(k => `${k}=${(+t[k]).toFixed(4)}`).join(' ');
      const k = m.model_id.name;
      return `<tr><td><a onclick="show('model ${esc(k)}','/3/Models/${enc(k)}')">${esc(k)}</a></td>
        <td><span class="pill">${esc(m.algo)}</span></td><td>${head}</td>
        <td><a onclick="doPredict('${esc(k)}')">predict</a>
            <a onclick="show('varimp ${esc(k)}','/3/Models/${enc(k)}/varimp')">varimp</a>
            <a onclick="doPD('${esc(k)}')">pd</a>
            <a href="/3/Models/${enc(k)}/mojo" download="${esc(k)}.zip">mojo</a>
            <a href="/3/Models.fetch.bin/${enc(k)}" download="${esc(k)}.bin">bin</a>
            <a onclick="doDelete('${esc(k)}')">✕</a></td></tr>`;}).join('');
  const jo = await J('/3/Jobs');
  el('jobs').innerHTML = '<tr><th>job</th><th>status</th><th>progress</th></tr>' +
    jo.jobs.slice(-12).reverse().map(j =>
      `<tr><td>${esc(j.description)}</td><td>${esc(j.status)}</td>
       <td>${Math.round((j.progress||0)*100)}%</td></tr>`).join('');
  const tl = await J('/3/Timeline');
  el('timeline').innerHTML = '<tr><th>event</th><th>info</th></tr>' +
    tl.events.slice(-12).reverse().map(e => {
      const {ts, kind, ...rest} = e;
      return `<tr><td>${esc(kind)}</td><td>${esc(JSON.stringify(rest)).slice(0,90)}</td></tr>`;}).join('');
  const algoSel = el('bm_algo');
  if (!algoSel.options.length){
    const mb = await J('/3/ModelBuilders');
    algoSel.innerHTML = Object.keys(mb.model_builders).map(a =>
      `<option ${a==='gbm'?'selected':''}>${a}</option>`).join('');
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""
