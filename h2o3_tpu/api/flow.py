"""Flow-lite: a single-page dashboard over the REST API.

Reference: ``h2o-web``'s Flow notebook UI.  This is deliberately a
minimal read-only surface (cloud status, frames with summaries and data
preview, models with metrics, jobs, timeline) driven purely by the same
/3 endpoints any client uses — an honest subset, not a notebook clone.
"""

FLOW_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>h2o3_tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1c2b33}
 header{background:#12333d;color:#fff;padding:10px 20px;font-size:18px}
 header small{opacity:.7;margin-left:12px}
 main{padding:16px 20px;display:grid;gap:16px;grid-template-columns:1fr 1fr}
 section{background:#fff;border:1px solid #dde3e8;border-radius:8px;padding:12px 16px}
 h2{font-size:14px;text-transform:uppercase;letter-spacing:.06em;color:#5b6b73;margin:0 0 8px}
 table{border-collapse:collapse;width:100%;font-size:13px}
 td,th{border-bottom:1px solid #eef1f4;padding:4px 8px;text-align:left}
 th{color:#5b6b73;font-weight:600}
 tr:hover{background:#f2f7fa}
 pre{background:#f2f4f6;padding:8px;border-radius:6px;overflow:auto;font-size:12px;max-height:320px}
 .pill{display:inline-block;background:#e4f0ee;border-radius:10px;padding:1px 8px;font-size:12px}
 #detail{grid-column:1 / -1}
 a{color:#176d81;cursor:pointer;text-decoration:none}
</style></head><body>
<header>h2o3_tpu<small id="cloud"></small></header>
<main>
 <section><h2>Frames</h2><table id="frames"></table></section>
 <section><h2>Models</h2><table id="models"></table></section>
 <section><h2>Jobs</h2><table id="jobs"></table></section>
 <section><h2>Timeline</h2><table id="timeline"></table></section>
 <section id="detail"><h2 id="dtitle">Detail</h2><pre id="dbody">select a frame or model…</pre></section>
</main>
<script>
const J = async p => (await fetch(p)).json();
const el = id => document.getElementById(id);
const esc = s => String(s).replace(/[&<>"'`]/g, c => ({'&':'&amp;','<':'&lt;','>':'&gt;','\"':'&quot;',"'":'&#39;','`':'&#96;'}[c]));
async function show(title, path){
  el('dtitle').textContent = title;
  el('dbody').textContent = JSON.stringify(await J(path), null, 2);
}
async function refresh(){
  const c = await J('/3/Cloud');
  el('cloud').textContent = `${c.platform} · ${JSON.stringify(c.mesh_shape)} · ${c.cloud_size} process(es)`;
  const fr = await J('/3/Frames');
  el('frames').innerHTML = '<tr><th>frame</th><th>rows</th><th>cols</th><th></th></tr>' +
    fr.frames.map(f => `<tr><td>${esc(f.frame_id.name)}</td><td>${f.rows}</td>
      <td>${f.columns.length}</td>
      <td><a onclick="show('frame ${esc(f.frame_id.name)}','/3/Frames/${encodeURIComponent(f.frame_id.name)}/data?row_count=20')">data</a>
          <a onclick="show('summary ${esc(f.frame_id.name)}','/3/Frames/${encodeURIComponent(f.frame_id.name)}/summary')">summary</a></td></tr>`).join('');
  const mo = await J('/3/Models');
  el('models').innerHTML = '<tr><th>model</th><th>algo</th><th>metrics</th></tr>' +
    mo.models.map(m => {
      const t = m.training_metrics || {};
      const head = ['auc','rmse','logloss','r2'].filter(k => t[k] != null)
        .map(k => `${k}=${(+t[k]).toFixed(4)}`).join(' ');
      return `<tr><td><a onclick="show('model ${esc(m.model_id.name)}','/3/Models/${encodeURIComponent(m.model_id.name)}')">${esc(m.model_id.name)}</a></td>
        <td><span class="pill">${esc(m.algo)}</span></td><td>${head}</td></tr>`;}).join('');
  const jo = await J('/3/Jobs');
  el('jobs').innerHTML = '<tr><th>job</th><th>status</th><th>progress</th></tr>' +
    jo.jobs.slice(-12).reverse().map(j =>
      `<tr><td>${esc(j.description)}</td><td>${esc(j.status)}</td>
       <td>${Math.round((j.progress||0)*100)}%</td></tr>`).join('');
  const tl = await J('/3/Timeline');
  el('timeline').innerHTML = '<tr><th>event</th><th>info</th></tr>' +
    tl.events.slice(-12).reverse().map(e => {
      const {ts, kind, ...rest} = e;
      return `<tr><td>${esc(kind)}</td><td>${esc(JSON.stringify(rest)).slice(0,90)}</td></tr>`;}).join('');
}
refresh(); setInterval(refresh, 4000);
</script></body></html>
"""
