"""REST authentication: pluggable authn SPI + form-login sessions + HTTPS.

Reference surface: ``h2o-security/`` and ``h2o-jaas-pam/`` give H2O's Jetty
server hash-file login, LDAP, Kerberos, PAM and form login
(``water/webserver/jetty9/Jetty9ServerAdapter`` wires the LoginService;
``hash_login`` / ``ldap_login`` / ``pam_login`` flags in
``water.H2O.OptArgs``).  TPU-native redesign: authentication is a small SPI
(`Authenticator.check`) in front of the stdlib HTTP server, with three
built-ins and a module hook so enterprise backends (LDAP/Kerberos) can be
plugged without changing framework code — those live behind site modules
because this image has no directory server to speak to.

Spec strings (the ``-hash_login``-style CLI surface, env
``H2O3_TPU_AUTH``):
  ``static:<user>:<password>``     single credential pair
  ``hash_file:<path>``             htpasswd-style file of ``user:pbkdf2``
                                   records (make them with `hash_password`)
  ``cmd:<executable>``             external verifier — username as argv[1],
                                   password on stdin, exit 0 = authenticated
                                   (the PAM/LDAP escape hatch)
  ``module:<pkg.attr>``            import an Authenticator instance/factory
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
import subprocess
import time
from typing import Dict, Optional

_PBKDF2_ITERS = 120_000


def hash_password(password: str, iters: int = _PBKDF2_ITERS) -> str:
    """One hash-file record value: ``pbkdf2_sha256$iters$salt$hex``."""
    salt = secrets.token_hex(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt.encode(),
                             iters)
    return f"pbkdf2_sha256${iters}${salt}${dk.hex()}"


def _verify_hash(password: str, record: str) -> bool:
    try:
        scheme, iters, salt, want = record.strip().split("$")
        if scheme != "pbkdf2_sha256":
            return False
        dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt.encode(),
                                 int(iters))
        return hmac.compare_digest(dk.hex(), want)
    except (ValueError, AttributeError):
        return False


class Authenticator:
    """SPI: return True iff (username, password) is a valid login."""

    name = "base"

    def check(self, username: str, password: str) -> bool:  # pragma: no cover
        raise NotImplementedError


class StaticAuthenticator(Authenticator):
    name = "static"

    def __init__(self, username: str, password: str):
        self._user, self._password = username, password

    def check(self, username: str, password: str) -> bool:
        return (hmac.compare_digest(username, self._user)
                and hmac.compare_digest(password, self._password))


class HashFileAuthenticator(Authenticator):
    """``user:pbkdf2_sha256$...`` per line — the `hash_login` analog.

    The file is re-read when its mtime changes, so operators can rotate
    credentials without restarting the server.
    """

    name = "hash_file"

    def __init__(self, path: str):
        self.path = path
        self._mtime = -1.0
        self._records: Dict[str, str] = {}
        self._load()

    def _load(self):
        mtime = os.stat(self.path).st_mtime
        if mtime == self._mtime:
            return
        records = {}
        with open(self.path) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                user, _, rec = line.partition(":")
                records[user] = rec
        self._records, self._mtime = records, mtime

    def check(self, username: str, password: str) -> bool:
        self._load()
        rec = self._records.get(username)
        return bool(rec) and _verify_hash(password, rec)


class CommandAuthenticator(Authenticator):
    """Delegate to an external verifier — the PAM/LDAP/Kerberos hook.

    Contract: ``<cmd> <username>`` with the password on stdin; exit code 0
    means authenticated.  A site wraps ``pamtester`` / ``ldapwhoami`` /
    ``kinit`` in a 3-line script and points ``H2O3_TPU_AUTH=cmd:...`` at
    it — no framework change for a new enterprise backend.
    """

    name = "cmd"

    def __init__(self, cmd: str, timeout_s: float = 10.0):
        self.cmd = cmd
        self.timeout_s = timeout_s

    def check(self, username: str, password: str) -> bool:
        if "\x00" in username or "\n" in username:
            return False
        try:
            r = subprocess.run([self.cmd, username],
                               input=password.encode(),
                               timeout=self.timeout_s,
                               stdout=subprocess.DEVNULL,
                               stderr=subprocess.DEVNULL)
            return r.returncode == 0
        except Exception:               # noqa: BLE001 — verifier died = deny
            return False


def resolve_authenticator(spec) -> Optional[Authenticator]:
    """Spec string / instance / None -> Authenticator (see module doc)."""
    if spec is None or isinstance(spec, Authenticator):
        return spec
    kind, _, rest = str(spec).partition(":")
    if kind == "static":
        user, _, password = rest.partition(":")
        return StaticAuthenticator(user, password)
    if kind == "hash_file":
        return HashFileAuthenticator(rest)
    if kind == "cmd":
        return CommandAuthenticator(rest)
    if kind == "module":
        import importlib
        mod, _, attr = rest.rpartition(".")
        obj = getattr(importlib.import_module(mod), attr)
        return obj() if isinstance(obj, type) else obj
    raise ValueError(f"unknown authenticator spec {spec!r} "
                     "(static:/hash_file:/cmd:/module: are supported)")


class SessionStore:
    """Server-side form-login sessions (the Jetty session analog)."""

    def __init__(self, ttl_s: float = 8 * 3600.0):
        self.ttl_s = ttl_s
        self._sessions: Dict[str, tuple] = {}     # token -> (user, expiry)

    def create(self, username: str) -> str:
        now = time.time()
        # sweep expired sessions here so a login loop cannot grow the
        # store without bound on a long-lived coordinator
        expired = [t for t, (_, exp) in self._sessions.items() if now > exp]
        for t in expired:
            self._sessions.pop(t, None)
        token = secrets.token_urlsafe(32)
        self._sessions[token] = (username, now + self.ttl_s)
        return token

    def user_for(self, token: str) -> Optional[str]:
        entry = self._sessions.get(token)
        if entry is None:
            return None
        user, expiry = entry
        if time.time() > expiry:
            self._sessions.pop(token, None)
            return None
        return user

    def destroy(self, token: str):
        self._sessions.pop(token, None)


def parse_basic(header: str) -> Optional[tuple]:
    """'Basic base64(user:pass)' -> (user, pass) or None."""
    if not header.startswith("Basic "):
        return None
    try:
        user, _, password = base64.b64decode(
            header[6:]).decode().partition(":")
        return user, password
    except Exception:                   # noqa: BLE001 — malformed header
        return None


def parse_cookie(header: str, name: str) -> Optional[str]:
    for part in (header or "").split(";"):
        k, _, v = part.strip().partition("=")
        if k == name:
            return v
    return None
