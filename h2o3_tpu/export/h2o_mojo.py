"""Reader for REAL H2O-3 MOJO archives (GBM / DRF / GLM) — migration path.

Reference format: ``hex/genmodel/ModelMojoReader.java:25`` — a zip holding
``model.ini`` ([info] key=value, [columns], [domains] with per-domain text
files) plus binary blobs.  Tree models store one bytecode blob per
(class, tree) at ``trees/t{class:02d}_{group:03d}.bin``
(SharedTreeMojoReader.java:52); the node stream is walked by
``SharedTreeMojoModel.scoreTree`` (SharedTreeMojoModel.java:134): nodeType
byte, colId u16 (0xFFFF = leaf), NA direction byte, then a float split or
an inline/offset bitset, with left-subtree skip sizes encoded in the
nodeType masks.  GLM stores coefficients inline in the ini
(GlmMojoModel.score0, GlmMojoModel.java:26).

This reader re-implements the *format* so a MOJO produced by the Java
reference scores identically here — it does not share any code with it.
Scoring is vectorized numpy on host: these artifacts serve migration and
serving parity checks, not TPU training.  Mojo versions 1.10+ are
supported (1.00 used a different bitset layout and predates every modern
export).
"""

from __future__ import annotations

import io
import os
import struct
import zipfile
from typing import Dict, List, Optional, Sequence

import numpy as np

_LEAF_COL = 0xFFFF
_NA_VS_REST, _NA_LEFT, _NA_RIGHT, _LEFT, _RIGHT = 1, 2, 3, 4, 5


def _parse_scalar(s: str):
    s = s.strip()
    if s in ("null", "None", ""):
        return None
    if s in ("true", "false"):
        return s == "true"
    if s.startswith("[") and s.endswith("]"):
        inner = s[1:-1].strip()
        return [_parse_scalar(x) for x in inner.split(",")] if inner else []
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


class _DirBackend:
    """Extracted-MOJO directory as a zip-like backend (the reference's
    MojoReaderBackend has folder/classpath forms too)."""

    def __init__(self, base: str):
        self.base = base

    def read(self, name: str) -> bytes:
        with open(os.path.join(self.base, name), "rb") as fh:
            return fh.read()

    def getinfo(self, name: str):
        if not os.path.exists(os.path.join(self.base, name)):
            raise KeyError(name)
        return name


class _PrefixBackend:
    """View into a sub-MOJO nested inside an archive (StackedEnsemble
    stores base models under ``models/<algo>/<key>/`` prefixes)."""

    def __init__(self, parent, prefix: str):
        self.parent = parent
        self.prefix = prefix

    def read(self, name: str) -> bytes:
        return self.parent.read(self.prefix + name)

    def getinfo(self, name: str):
        return self.parent.getinfo(self.prefix + name)


class MojoArchive:
    """Parsed model.ini + blob access for one MOJO zip (or extracted
    directory, or a nested-backend view)."""

    def __init__(self, path_or_bytes, backend=None):
        if backend is not None:
            self.zf = backend
        elif isinstance(path_or_bytes, (str, os.PathLike)) \
                and os.path.isdir(path_or_bytes):
            self.zf = _DirBackend(os.fspath(path_or_bytes))
        else:
            if isinstance(path_or_bytes, (bytes, bytearray)):
                path_or_bytes = io.BytesIO(path_or_bytes)
            self.zf = zipfile.ZipFile(path_or_bytes)
        self.info: Dict[str, object] = {}
        self.columns: List[str] = []
        self.domains: Dict[int, List[str]] = {}
        section = None
        for line in self.zf.read("model.ini").decode().splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("["):
                section = line.strip("[]").lower()
                continue
            if section == "info" and "=" in line:
                k, _, v = line.partition("=")
                self.info[k.strip()] = _parse_scalar(v)
            elif section == "columns":
                self.columns.append(line)
            elif section == "domains":
                # "0: 7 d000.txt" -> column_index: cardinality file
                idx, _, rest = line.partition(":")
                fname = rest.split()[-1]
                levels = self.zf.read(
                    f"domains/{fname}").decode().splitlines()
                self.domains[int(idx)] = levels

    def blob(self, name: str) -> bytes:
        return self.zf.read(name)

    def has(self, name: str) -> bool:
        try:
            self.zf.getinfo(name)
            return True
        except KeyError:
            return False


# ----------------------------------------------------------- tree bytecode

def _score_tree(tree: bytes, row: np.ndarray,
                domain_len: Sequence[int], v11: bool) -> float:
    """One tree walk — SharedTreeMojoModel.scoreTree (Java :134 / :1040).

    ``domain_len[col]`` is the domain cardinality (0 for numeric); the
    current (v1.2+) walker treats an out-of-domain integer like NA.
    ``v11`` selects the 1.10 bitset layout (fill3_1: u16 nbytes) over the
    current one (fill3: u32 nbits).
    """
    pos = 0
    while True:
        node_type = tree[pos]
        col = tree[pos + 1] | (tree[pos + 2] << 8)
        pos += 3
        if col == _LEAF_COL:
            return struct.unpack_from("<f", tree, pos)[0]
        na_dir = tree[pos]
        pos += 1
        na_vs_rest = na_dir == _NA_VS_REST
        leftward = na_dir in (_NA_LEFT, _LEFT)
        lmask = node_type & 51
        equal = node_type & 12
        split_val = None
        bs_off = bs_nbits = bs_bitoff = 0
        if not na_vs_rest:
            if equal == 0:
                split_val = struct.unpack_from("<f", tree, pos)[0]
                pos += 4
            elif equal == 8:                   # 32-bit inline bitset
                bs_off, bs_nbits, bs_bitoff = pos, 32, 0
                pos += 4
            else:                              # offset bitset (equal == 12)
                bs_bitoff = tree[pos] | (tree[pos + 1] << 8)
                if v11:
                    nbytes = tree[pos + 2] | (tree[pos + 3] << 8)
                    bs_nbits = nbytes << 3
                    pos += 4
                else:
                    bs_nbits = struct.unpack_from("<i", tree, pos + 2)[0]
                    nbytes = ((bs_nbits - 1) >> 3) + 1
                    pos += 6
                bs_off = pos
                pos += nbytes

        d = row[col]
        if np.isnan(d):
            missing = True
        elif equal != 0:
            i = int(d) - bs_bitoff
            missing = not (0 <= i < bs_nbits)
        elif not v11 and domain_len[col] and int(d) >= domain_len[col]:
            missing = True
        else:
            missing = False
        if missing:
            go_right = not leftward
        elif na_vs_rest:
            go_right = False
        elif equal == 0:
            go_right = d >= split_val
        else:
            i = int(d) - bs_bitoff
            go_right = bool(tree[bs_off + (i >> 3)] & (1 << (i & 7)))

        if go_right:
            if lmask == 0:
                pos += 1 + tree[pos]
            elif lmask == 1:
                pos += 2 + (tree[pos] | (tree[pos + 1] << 8))
            elif lmask == 2:
                pos += 3 + (tree[pos] | (tree[pos + 1] << 8)
                            | (tree[pos + 2] << 16))
            elif lmask == 3:
                pos += 4 + struct.unpack_from("<i", tree, pos)[0]
            elif lmask == 48:
                pos += 4                       # skip the left prediction
            else:
                raise ValueError(f"illegal lmask {lmask}")
            lmask = (node_type & 0xC0) >> 2    # switch to the right mask
        else:
            if lmask <= 3:
                pos += lmask + 1
        if lmask & 16:
            return struct.unpack_from("<f", tree, pos)[0]


class H2OMojoModel:
    """Common surface: predict(dict of named columns) -> dict."""

    def __init__(self, ar: MojoArchive):
        self.archive = ar
        self.algo = str(ar.info["algo"])
        self.columns = ar.columns
        self.n_features = int(ar.info["n_features"])
        self.nclasses = int(ar.info["n_classes"])
        self.domains = ar.domains
        resp_idx = self.n_features
        self.response_domain = ar.domains.get(resp_idx)
        self.feature_names = ar.columns[: self.n_features]

    # -- row assembly: names -> model column order, cats -> domain codes
    def _matrix(self, data: Dict[str, Sequence]) -> np.ndarray:
        n = len(next(iter(data.values())))
        X = np.full((n, self.n_features), np.nan)
        for j, name in enumerate(self.feature_names):
            if name not in data:
                continue
            col = np.asarray(data[name], dtype=object)
            dom = self.domains.get(j)
            if dom is not None:
                lookup = {s: i for i, s in enumerate(dom)}
                X[:, j] = [lookup.get(str(v), np.nan)
                           if v is not None else np.nan for v in col]
            else:
                X[:, j] = [np.nan if v is None else float(v) for v in col]
        return X

    def _finish(self, raw: np.ndarray) -> dict:
        if self.nclasses >= 2:
            labels = np.argmax(raw, axis=1)
            if self.nclasses == 2:
                thr = float(self.archive.info.get("default_threshold", 0.5))
                labels = (raw[:, 1] >= thr).astype(int)
            dom = self.response_domain or [str(i) for i in
                                           range(self.nclasses)]
            return {"predict": np.asarray(dom, dtype=object)[labels],
                    "classes": dom,
                    "probabilities": raw}
        return {"predict": raw[:, 0]}

    def predict(self, data: Dict[str, Sequence]) -> dict:
        return self._finish(self._score_raw(self._matrix(data)))


class H2OMojoTreeModel(H2OMojoModel):
    """GBM / DRF / IsolationForest-style shared-tree MOJO."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        self.ntree_groups = int(ar.info["n_trees"])
        self.ntrees_per_group = int(ar.info["n_trees_per_class"])
        self.mojo_version = float(ar.info["mojo_version"])
        if self.mojo_version < 1.1:
            raise NotImplementedError(
                "MOJO 1.00 tree archives predate the supported format")
        self.trees: List[Optional[bytes]] = []
        for group in range(self.ntree_groups):
            for cls in range(self.ntrees_per_group):
                name = f"trees/t{cls:02d}_{group:03d}.bin"
                self.trees.append(ar.blob(name) if ar.has(name) else None)
        self.domain_len = [len(self.domains.get(j, ()))
                          for j in range(self.n_features)]

    def _tree_sums(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        k = self.ntrees_per_group
        out = np.zeros((n, k))
        v11 = self.mojo_version < 1.2
        for t, tree in enumerate(self.trees):
            if tree is None:
                continue
            cls = t % k
            for r in range(n):
                out[r, cls] += _score_tree(tree, X[r], self.domain_len,
                                           v11)
        return out

    def _score_raw(self, X: np.ndarray) -> np.ndarray:
        sums = self._tree_sums(X)
        info = self.archive.info
        if self.algo == "gbm":
            init_f = float(info.get("init_f") or 0.0)
            family = str(info.get("distribution"))
            link = str(info.get("link_function", "") or "")
            if family in ("bernoulli", "quasibinomial", "modified_huber"):
                f = sums[:, 0] + init_f
                p1 = _link_inv(link or "logit", f)
                return np.stack([1.0 - p1, p1], axis=1)
            if family == "multinomial":
                if self.nclasses == 2:
                    f = sums[:, 0] + init_f
                    e = np.stack([f, -f], axis=1)
                else:
                    e = sums
                e = np.exp(e - e.max(axis=1, keepdims=True))
                return e / e.sum(axis=1, keepdims=True)
            return _link_inv(link or "identity",
                             sums[:, [0]] + init_f)
        if self.algo == "drf":
            if self.nclasses == 1:
                return sums / self.ntree_groups
            if self.nclasses == 2 and not bool(
                    info.get("binomial_double_trees")):
                # DrfMojoModel.unifyPreds: binomial DRF trees vote for
                # CLASS 0 — preds[1] = sum/T, preds[2] = 1 - preds[1]
                p0 = sums[:, 0] / self.ntree_groups
                return np.stack([p0, 1.0 - p0], axis=1)
            s = sums.sum(axis=1, keepdims=True)
            with np.errstate(invalid="ignore", divide="ignore"):
                return np.where(s > 0, sums / s, sums)
        raise NotImplementedError(
            f"tree MOJO algo {self.algo!r} not supported yet "
            "(gbm/drf are)")


def _link_inv(link: str, f: np.ndarray) -> np.ndarray:
    link = link.lower()
    if link in ("logit", ""):
        return 1.0 / (1.0 + np.exp(-f))
    if link == "log":
        return np.exp(f)
    if link == "inverse":
        xx = np.where(np.abs(f) < 1e-5, np.sign(f) * 1e-5 + (f == 0) * 1e-5,
                      f)
        return 1.0 / xx
    if link == "ologit":
        return 1.0 / (1.0 + np.exp(-f))
    return f                                   # identity


class H2OMojoGlmModel(H2OMojoModel):
    """GLM MOJO — GlmMojoModel.score0 (GlmMojoModel.java:26)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        self.beta = np.asarray(info["beta"], dtype=float)
        self.cats = int(info.get("cats", 0))
        self.cat_offsets = list(info.get("cat_offsets") or [0])
        self.nums = int(info.get("nums", 0))
        self.use_all_levels = bool(info.get("use_all_factor_levels", False))
        self.mean_imputation = bool(info.get("mean_imputation", False))
        self.num_means = list(info.get("num_means") or [])
        self.cat_modes = list(info.get("cat_modes") or [])
        self.family = str(info.get("family", "gaussian"))
        self.link = str(info.get("link", "identity"))

    def _score_raw(self, X: np.ndarray) -> np.ndarray:
        X = X.copy()
        if self.mean_imputation:
            for i in range(self.cats):
                bad = ~np.isfinite(X[:, i])
                X[bad, i] = self.cat_modes[i]
            for j in range(self.nums):
                col = self.cats + j
                bad = ~np.isfinite(X[:, col])
                X[bad, col] = self.num_means[j]
        eta = np.zeros(X.shape[0])
        for i in range(self.cats):
            ival = X[:, i].astype(int)
            if not self.use_all_levels:
                ival = ival - 1
            ok = np.isfinite(X[:, i]) & (ival >= 0)
            idx = ival + self.cat_offsets[i]
            ok &= idx < self.cat_offsets[i + 1]
            eta[ok] += self.beta[idx[ok]]
        noff = self.cat_offsets[self.cats] - self.cats
        for i in range(self.cats, len(self.beta) - 1 - noff):
            eta += self.beta[noff + i] * np.nan_to_num(X[:, i])
        eta += self.beta[-1]
        mu = _link_inv(self.link, eta)
        if self.family in ("binomial", "quasibinomial", "fractionalbinomial"):
            return np.stack([1.0 - mu, mu], axis=1)
        return mu[:, None]


class H2OMojoKMeansModel(H2OMojoModel):
    """KMeans MOJO — KMeansMojoModel.score0 + GenModel KMeans utilities
    (GenModel.java:523-675: standardize/impute preprocess, categorical
    Manhattan + numeric Euclidean distance with missing-dimension
    rescaling)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        k = int(info["center_num"])
        self.centers = np.asarray(
            [info[f"center_{i}"] for i in range(k)], dtype=float)
        self.standardize = bool(info.get("standardize", False))
        self.means = np.asarray(info.get("standardize_means")
                                or [0.0] * self.n_features, dtype=float)
        self.mults = np.asarray(info.get("standardize_mults")
                                or [1.0] * self.n_features, dtype=float)
        self.modes = np.asarray(info.get("standardize_modes")
                                or [-1] * self.n_features, dtype=float)
        self.is_cat = np.array([j in self.domains
                                for j in range(self.n_features)])

    def _preprocess(self, X: np.ndarray) -> np.ndarray:
        """KMeansMojoModel.score0 preprocesses ONLY when standardize=true
        (impute + scale); otherwise rows pass through raw and missing
        dimensions are handled by the distance's NA-skip/rescale."""
        if not self.standardize:
            return X
        X = X.copy()
        for j in range(self.n_features):
            col = X[:, j]
            nan = np.isnan(col)
            if self.modes[j] == -1:               # numeric
                col = np.where(nan, self.means[j], col)
                col = (col - self.means[j]) * self.mults[j]
            else:                                  # categorical: mode
                col = np.where(nan, self.modes[j], col)
            X[:, j] = col
        return X

    def distances(self, data) -> np.ndarray:
        X = self._preprocess(self._matrix(data))
        n, k = X.shape[0], self.centers.shape[0]
        valid = ~np.isnan(X)
        pts = valid.sum(axis=1)
        scale = np.where((pts > 0) & (pts < self.n_features),
                         self.n_features / np.maximum(pts, 1), 1.0)
        out = np.zeros((n, k))
        for c in range(k):
            center = self.centers[c]
            sq = np.zeros(n)
            for j in range(self.n_features):
                d = X[:, j]
                ok = valid[:, j]
                if self.is_cat[j]:
                    sq += ok * (d != center[j])    # Manhattan
                else:
                    delta = np.where(ok, d - center[j], 0.0)
                    sq += delta * delta
            out[:, c] = sq * scale
        return out

    def predict(self, data) -> dict:
        d = self.distances(data)
        return {"predict": np.argmin(d, axis=1), "distances": d}


class H2OMojoSvmModel(H2OMojoModel):
    """SparkSVM MOJO — SvmMojoModel.score0 (linear margin + threshold)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        self.weights = np.asarray(info["weights"], dtype=float)
        self.interceptor = float(info["interceptor"])
        self.threshold = float(info.get("threshold", 0.0))
        self.mean_imputation = bool(info.get("meanImputation", False))
        self.means = np.asarray(info.get("means")
                                or [0.0] * self.n_features, dtype=float)

    def predict(self, data) -> dict:
        X = self._matrix(data)
        pred = np.full(X.shape[0], self.interceptor)
        for j in range(self.n_features):
            col = X[:, j]
            if self.mean_imputation:
                col = np.where(np.isnan(col), self.means[j], col)
            # no imputation: NaN propagates, exactly like score0 —
            # `NaN > threshold` is false, forcing label index 0
            pred += col * self.weights[j]
        if self.nclasses == 1:
            return {"predict": pred}
        with np.errstate(invalid="ignore"):
            label = np.where(np.isnan(pred), 0,
                             pred > self.threshold).astype(int)
        dom = self.response_domain or ["0", "1"]
        return {"predict": np.asarray(dom, dtype=object)[label],
                "label_index": label, "margin": pred}


class H2OMojoIsoforModel(H2OMojoTreeModel):
    """IsolationForest MOJO — IsolationForestMojoModel.unifyPreds:
    summed per-tree path lengths -> normalized anomaly score."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        self.min_path = float(ar.info["min_path_length"])
        self.max_path = float(ar.info["max_path_length"])
        self.output_anomaly_flag = bool(
            ar.info.get("output_anomaly_flag", False))
        self.anomaly_threshold = float(
            ar.info.get("default_threshold", 0.5))

    def predict(self, data) -> dict:
        X = self._matrix(data)
        lengths = self._tree_sums(X)[:, 0]
        mean_len = lengths / max(self.ntree_groups, 1)
        if self.max_path > self.min_path:
            score = (self.max_path - lengths) / (self.max_path
                                                 - self.min_path)
        else:
            score = np.ones_like(lengths)
        out = {"predict": score, "score": score, "mean_length": mean_len,
               "path_length": lengths}
        if self.output_anomaly_flag:
            # unifyPreds emits [flag, score, mean_length] in this mode
            out["predict"] = (score > self.anomaly_threshold).astype(int)
        return out


class H2OMojoEnsembleModel(H2OMojoModel):
    """StackedEnsemble MOJO — StackedEnsembleMojoModel.score0: base
    models score the row (each remaps columns by its own layout — free
    here, since scoring is name-keyed), their predictions form the
    metalearner's positional input, with the optional logit transform."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        if self.nclasses > 2:
            raise NotImplementedError(
                "multinomial StackedEnsemble MOJOs need a multinomial "
                "GLM metalearner reader (binomial/regression supported)")
        transform = str(info.get("metalearner_transform")
                        or "NONE").upper()
        if transform not in ("NONE", "LOGIT"):
            raise NotImplementedError(
                f"metalearner_transform {transform!r} (NONE/Logit are "
                "supported, matching StackedEnsembleMojoReader)")
        self.logit_transform = transform == "LOGIT"
        dirs = {}
        for i in range(int(info["submodel_count"])):
            dirs[str(info[f"submodel_key_{i}"])] = \
                str(info[f"submodel_dir_{i}"])

        def sub(key: str) -> H2OMojoModel:
            return load_h2o_mojo(None, backend=_PrefixBackend(
                ar.zf, dirs[key]))

        self.metalearner = sub(str(info["metalearner"]))
        # absent base_model{i} slots are pruned/unused models — the
        # reference skips them but keeps their basePreds position as 0.0
        self.base_models = [
            sub(str(info[f"base_model{i}"]))
            if info.get(f"base_model{i}") is not None else None
            for i in range(int(info["base_models_num"]))]

    @staticmethod
    def _logit(p: np.ndarray) -> np.ndarray:
        p = np.clip(p, 1e-9, 1 - 1e-9)
        x = p / (1 - p)
        return np.where(x == 0, -19.0, np.maximum(-19.0, np.log(x)))

    def predict(self, data) -> dict:
        n = len(next(iter(data.values())))
        base = np.zeros((n, len(self.base_models)))
        is_prob = np.zeros(len(self.base_models), dtype=bool)
        for i, bm in enumerate(self.base_models):
            if bm is None:                    # pruned slot: 0.0 column
                continue
            out = bm.predict(data)
            # level-one column per base, mirroring training's
            # _base_columns: classifiers contribute p(positive); other
            # algos their single raw output (cluster id, CoxPH lp, PC1)
            if self.nclasses == 2 and "probabilities" in out:
                base[:, i] = out["probabilities"][:, 1]
                is_prob[i] = True
            elif "predict" in out:
                base[:, i] = np.asarray(out["predict"], dtype=float)
            elif "projection" in out:         # PCA base (k=1 level-one col)
                base[:, i] = np.asarray(out["projection"])[:, 0]
            else:
                raise NotImplementedError(
                    f"ensemble base model produced no usable level-one "
                    f"column (outputs: {sorted(out)})")
        if self.logit_transform and self.nclasses == 2:
            # score0 logit-transforms only the classification branches;
            # regression/unsupervised base predictions feed the
            # metalearner raw
            base[:, is_prob] = self._logit(base[:, is_prob])
        meta_data = {name: base[:, j].tolist() for j, name in
                     enumerate(self.metalearner.feature_names)}
        out = self.metalearner.predict(meta_data)
        if self.nclasses == 2:
            # label decisions use the ENSEMBLE's threshold + domain
            p1 = out["probabilities"][:, 1]
            thr = float(self.archive.info.get("default_threshold", 0.5))
            dom = self.response_domain or ["0", "1"]
            out["predict"] = np.asarray(dom, dtype=object)[
                (p1 >= thr).astype(int)]
            out["classes"] = dom
        return out


class H2OMojoWord2VecModel(H2OMojoModel):
    """Word2Vec MOJO — Word2VecMojoModel.transform0: vocabulary text
    lines + BIG-endian float32 vectors (Java ByteBuffer default order,
    despite the ini's LITTLE_ENDIAN marker — Word2VecMojoReader wraps
    the blob without setting an order)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        self.vec_size = int(ar.info["vec_size"])
        vocab_size = int(ar.info["vocab_size"])
        # readtext semantics: every line kept (even blank tokens, which
        # consume a vector row), newline escapes undone, then trimmed
        vocab = [w.replace("\\n", "\n").strip()
                 for w in ar.blob("vocabulary").decode().splitlines()]
        raw = ar.blob("vectors")
        if len(raw) != vocab_size * self.vec_size * 4 \
                or len(vocab) != vocab_size:
            raise ValueError(
                f"corrupted word2vec vectors: {len(raw)} bytes / "
                f"{len(vocab)} words for vocab_size={vocab_size}, "
                f"vec_size={self.vec_size}")
        vecs = np.frombuffer(raw, dtype=">f4").astype(np.float32)
        vecs = vecs.reshape(vocab_size, self.vec_size)
        self.embeddings = {w: vecs[i] for i, w in enumerate(vocab)}
        if len(self.embeddings) != vocab_size:
            # duplicate vocabulary words collapse in the map; the reference
            # reader rejects this as corruption (Word2VecMojoReader:
            # "Corrupted model, unexpected number of words")
            raise ValueError(
                f"corrupted word2vec vocabulary: {len(self.embeddings)} "
                f"distinct words for vocab_size={vocab_size}")

    def transform(self, words) -> np.ndarray:
        """[n, vec_size]; out-of-dictionary words become NaN rows
        (transform0 returns null there)."""
        out = np.full((len(words), self.vec_size), np.nan, np.float32)
        for i, w in enumerate(words):
            vec = self.embeddings.get(str(w))
            if vec is not None:
                out[i] = vec
        return out

    def predict(self, data) -> dict:
        col = next(iter(data.values()))
        return {"embeddings": self.transform(list(col))}


class H2OMojoDeepLearningModel(H2OMojoModel):
    """DeepLearning MOJO — DeeplearningMojoModel.score0: one-hot cats
    (cat_offsets / use_all_factor_levels / NA->extra level or mode),
    normalized nums, MLP forward with per-layer [out, in]-major weights
    read from model.ini (DeeplearningMojoReader.readModelData)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        self.cats = int(info.get("cats", 0))
        self.nums = int(info.get("nums", 0))
        self.catoffsets = [int(x) for x in
                           (info.get("cat_offsets") or [0])]
        self.normsub = np.asarray(info.get("norm_sub") or [], float)
        self.normmul = np.asarray(info.get("norm_mul") or [], float)
        self.normrespsub = info.get("norm_resp_sub")
        self.normrespmul = info.get("norm_resp_mul")
        self.use_all = bool(info.get("use_all_factor_levels", False))
        self.units = [int(u) for u in info["neural_network_sizes"]]
        self.activation = str(info["activation"])
        self.impute_means = bool(info.get("mean_imputation", False))
        self.cat_modes = [int(x) for x in (info.get("cat_modes") or [])]
        self.family = str(info.get("distribution", "gaussian"))
        self.layers = []
        for k in range(len(self.units) - 1):
            W = np.asarray(info[f"weight_layer{k}"], float) \
                .reshape(self.units[k + 1], self.units[k])
            b = np.asarray(info[f"bias_layer{k}"], float)
            self.layers.append((W, b))

    def _assemble(self, X: np.ndarray) -> np.ndarray:
        """[n, cats+nums] codes/values -> [n, units[0]] network input."""
        n = X.shape[0]
        A = np.zeros((n, self.units[0]))
        ncat_inputs = self.catoffsets[-1] if self.cats else 0
        for c in range(self.cats):
            val = X[:, c].copy()
            if self.impute_means and self.cat_modes:
                val = np.where(np.isnan(val), self.cat_modes[c], val)
            base = self.catoffsets[c]
            width = self.catoffsets[c + 1] - base
            idx = val - (0 if self.use_all else 1)
            ok = (~np.isnan(val)) & (idx >= 0) & (idx < width)
            rows = np.flatnonzero(ok)
            A[rows, base + idx[ok].astype(int)] = 1.0
        for j in range(self.nums):
            x = X[:, self.cats + j]
            if len(self.normsub):
                x = np.where(np.isnan(x), self.normsub[j], x)
                x = (x - self.normsub[j]) * self.normmul[j]
            else:
                x = np.nan_to_num(x)
            A[:, ncat_inputs + j] = x
        return A

    @staticmethod
    def _act(name: str, z: np.ndarray) -> np.ndarray:
        base = name.replace("WithDropout", "")
        if base == "Rectifier":
            return np.maximum(z, 0.0)
        if base == "Tanh":
            return np.tanh(z)
        if base == "Maxout":
            return z.reshape(z.shape[0], -1, 2).max(axis=2)
        raise NotImplementedError(f"activation {name!r}")

    def _score_raw(self, X: np.ndarray) -> np.ndarray:
        h = self._assemble(X)
        for W, b in self.layers[:-1]:
            h = self._act(self.activation, h @ W.T + b)
        W, b = self.layers[-1]
        out = h @ W.T + b
        if self.nclasses >= 2:
            e = np.exp(out - out.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        mu = out[:, :1]
        if self.normrespmul is not None:
            mu = mu / float(self.normrespmul) + float(self.normrespsub)
        return mu


class H2OMojoPcaModel(H2OMojoModel):
    """PCA MOJO — PCAMojoModel.score0: normalize, project onto the
    eigenvector blob ([eigenvector_size, k] big-endian doubles)."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        self.k = int(info["k"])
        self.ncats = int(info.get("ncats", 0))
        self.nnums = int(info.get("nnums", 0))
        self.normsub = np.asarray(info.get("normSub") or [], float)
        self.normmul = np.asarray(info.get("normMul") or [], float)
        size = int(info["eigenvector_size"])
        self.V = np.frombuffer(ar.blob("eigenvectors_raw"),
                               dtype=">f8").astype(float) \
            .reshape(size, self.k)

    def predict(self, data) -> dict:
        X = self._matrix(data)
        Z = np.empty((X.shape[0], self.nnums))
        for j in range(self.nnums):
            x = X[:, self.ncats + j]
            x = np.where(np.isnan(x), self.normsub[j], x)
            Z[:, j] = (x - self.normsub[j]) * self.normmul[j]
        proj = Z @ self.V[-self.nnums:]
        return {"projection": proj,
                **{f"PC{i + 1}": proj[:, i] for i in range(self.k)}}


class H2OMojoCoxPHModel(H2OMojoModel):
    """CoxPH MOJO — CoxPHMojoModel.score0 (no strata / interactions):
    lp = coef . features - lpBase, cats one-hot then nums."""

    def __init__(self, ar: MojoArchive):
        super().__init__(ar)
        info = ar.info
        self.coef = np.asarray(info["coef"], float)
        self.cats = int(info.get("cats", 0))
        self.cat_offsets = [int(x) for x in
                            (info.get("cat_offsets") or [0])]
        self.nums = int(info.get("num_numerical_columns", 0))
        self.num_offsets = [int(x) for x in
                            (info.get("num_offsets") or [])]
        self.use_all = bool(info.get("use_all_factor_levels", False))
        s1 = int(info.get("x_mean_cat_size1", 0))
        s2 = int(info.get("x_mean_cat_size2", 0))
        mc = np.frombuffer(ar.blob("x_mean_cat"), dtype=">f8") \
            .reshape(s1, s2) if s1 else np.zeros((1, 0))
        s1n = int(info.get("x_mean_num_size1", 0))
        s2n = int(info.get("x_mean_num_size2", 0))
        mn = np.frombuffer(ar.blob("x_mean_num"), dtype=">f8") \
            .reshape(s1n, s2n) if s1n else np.zeros((1, 0))
        num_start = mc.shape[1]
        self.lp_base = float(
            np.dot(mc[0], self.coef[: num_start])
            + np.dot(mn[0], self.coef[num_start: num_start + mn.shape[1]]))

    def predict(self, data) -> dict:
        X = self._matrix(data)
        n = X.shape[0]
        lp = np.zeros(n)
        for c in range(self.cats):
            val = X[:, c]
            idx = val - (0 if self.use_all else 1)
            base = self.cat_offsets[c]
            width = self.cat_offsets[c + 1] - base
            ok = (~np.isnan(val)) & (idx >= 0) & (idx < width)
            rows = np.flatnonzero(ok)
            lp[rows] += self.coef[base + idx[ok].astype(int)]
            lp[np.isnan(val)] = np.nan
        for j in range(self.nums):
            x = X[:, self.cats + j]
            lp += self.coef[self.num_offsets[j]] * x
        lp -= self.lp_base
        return {"predict": lp, "lp": lp}


def load_h2o_mojo(path_or_bytes, backend=None) -> H2OMojoModel:
    """Open a reference-produced MOJO (zip or extracted directory) —
    ModelMojoReader.load analog."""
    ar = MojoArchive(path_or_bytes, backend=backend)
    algo = str(ar.info.get("algo"))
    if algo in ("gbm", "drf"):
        return H2OMojoTreeModel(ar)
    if algo == "glm":
        return H2OMojoGlmModel(ar)
    if algo == "kmeans":
        return H2OMojoKMeansModel(ar)
    if algo == "svm":
        return H2OMojoSvmModel(ar)
    if algo == "isolationforest":
        return H2OMojoIsoforModel(ar)
    if algo == "stackedensemble":
        return H2OMojoEnsembleModel(ar)
    if algo == "word2vec":
        return H2OMojoWord2VecModel(ar)
    if algo == "deeplearning":
        return H2OMojoDeepLearningModel(ar)
    if algo == "pca":
        return H2OMojoPcaModel(ar)
    if algo == "coxph":
        return H2OMojoCoxPHModel(ar)
    raise NotImplementedError(
        f"H2O MOJO algo {algo!r} not supported (gbm, drf, glm, kmeans, "
        "svm, isolationforest, stackedensemble, word2vec, deeplearning, "
        "pca, coxph are)")


def is_h2o_mojo(path) -> bool:
    if isinstance(path, (str, os.PathLike)) and os.path.isdir(path):
        return os.path.isfile(os.path.join(path, "model.ini"))
    try:
        with zipfile.ZipFile(path) as z:
            z.getinfo("model.ini")
        return True
    except Exception:               # noqa: BLE001 — not a reference MOJO
        return False
