"""Standalone scoring for exported models — numpy + stdlib ONLY.

Reference: ``h2o-genmodel`` — ``hex/genmodel/MojoModel.java:12``,
``GenModel.java:16``, ``EasyPredictModelWrapper.java:65``: a zero-dependency
scoring library that loads a MOJO archive and predicts with no cluster.

This module is the deployment contract's scoring half: it must never import
jax (or anything beyond numpy/stdlib) so artifacts score anywhere — a web
server, a batch job, a laptop.  The archive format lives in mojo.py.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np


class ScoringModel:
    """Loaded portable model — the MojoModel/EasyPredictModelWrapper analog."""

    def __init__(self, meta: dict, arrays: Dict[str, np.ndarray]):
        self.meta = meta
        self.arrays = arrays
        self.algo = meta["algo"]
        self.spec = meta["datainfo"]

    # ------------------------------------------------------- featurization
    def _columns(self, data: Dict[str, np.ndarray], n: int):
        cols = {}
        for s in self.spec["specs"]:
            name = s["name"]
            if name not in data:
                cols[name] = np.full(n, np.nan)
                continue
            col = np.asarray(data[name])
            if s["type"] == "cat":
                if col.dtype == object or col.dtype.kind in "US":
                    lookup = {lbl: i for i, lbl in enumerate(s["domain"])}
                    col = np.array([lookup.get(str(v), -1) for v in col],
                                   dtype=np.float64)
                else:
                    col = col.astype(np.float64)
                    col[~np.isfinite(col)] = -1
            else:
                col = col.astype(np.float64)
            cols[name] = col
        return cols

    def _design_standardized(self, data: Dict[str, np.ndarray], n: int):
        """One-hot + impute + standardize matrix (DataInfo.make_matrix)."""
        cols = self._columns(data, n)
        out = []
        for s in self.spec["specs"]:
            x = cols[s["name"]]
            if s["type"] == "cat":
                lo = 0 if self.spec["use_all_factor_levels"] else 1
                width = s["width"] - 1
                levels = np.arange(lo, lo + width)
                onehot = (x[:, None] == levels[None, :]).astype(np.float64)
                na = (x < 0)[:, None].astype(np.float64)
                out.append(np.concatenate([onehot, na], axis=1))
            else:
                xi = np.where(np.isnan(x), s["mean"], x)
                if self.spec["standardize"]:
                    xi = (xi - s["mean"]) / s["sigma"]
                out.append(xi[:, None])
        if self.spec["add_intercept"]:
            out.append(np.ones((n, 1)))
        return np.concatenate(out, axis=1)

    def _design_raw(self, data: Dict[str, np.ndarray], n: int):
        """Raw-value matrix for tree traversal (cat codes, NaN missing).

        float32, matching the training design: thresholds are f32 values of
        f32 data, so comparing in f64 flips ties at the split boundaries.
        """
        cols = self._columns(data, n)
        out = []
        for s in self.spec["specs"]:
            x = cols[s["name"]]
            if s["type"] == "cat":
                x = np.where(x < 0, np.nan, x)
            out.append(x)
        return np.stack(out, axis=1).astype(np.float32)

    # ------------------------------------------------------------ predict
    def predict(self, data) -> dict:
        """Score rows.  ``data``: dict of column arrays, or a single row dict.

        Returns {"predict": labels-or-values, "probabilities": [n, K]?}.
        """
        single = all(np.isscalar(v) or isinstance(v, str)
                     for v in data.values())
        if single:
            data = {k: np.asarray([v]) for k, v in data.items()}
        else:
            data = {k: np.asarray(v) for k, v in data.items()}
        n = len(next(iter(data.values())))
        raw = self._score(data, n)
        domain = self.spec.get("response_domain")
        if domain:
            labels = np.asarray(domain, dtype=object)[np.argmax(raw, axis=1)]
            if raw.shape[1] == 2:
                thr = self.meta.get("default_threshold", 0.5)
                labels = np.asarray(domain, dtype=object)[
                    (raw[:, 1] >= thr).astype(int)]
            out = {"predict": labels, "probabilities": raw}
        else:
            out = {"predict": raw.reshape(-1)}
        if single:
            out = {k: v[0] for k, v in out.items()}
        return out

    def _score(self, data, n) -> np.ndarray:
        fn = getattr(self, f"_score_{self.meta['family']}", None)
        if fn is None:
            raise ValueError(
                f"no standalone scorer for family {self.meta['family']!r}")
        return fn(data, n)

    # ------------------------------------------------------------ families
    def _linkinv(self, eta):
        link = self.meta.get("link", "identity")
        if link == "logit":
            return 1.0 / (1.0 + np.exp(-eta))
        if link == "log":
            return np.exp(eta)
        return eta

    def _score_glm(self, data, n):
        X = self._design_standardized(data, n)
        beta = self.arrays["beta"]
        if beta.ndim == 2:                         # multinomial
            eta = X @ beta
            eta -= eta.max(axis=1, keepdims=True)
            p = np.exp(eta)
            return p / p.sum(axis=1, keepdims=True)
        mu = self._linkinv(X @ beta)
        if self.spec.get("response_domain"):
            return np.stack([1 - mu, mu], axis=1)
        return mu

    def _packed(self, prefix=""):
        """Bitpacked node planes for one class group, packed once and
        cached — the layout serving/kernel.py puts on device."""
        from ..serving import pack as _pack
        cache = self.__dict__.setdefault("_pack_cache", {})
        pk = cache.get(prefix)
        if pk is None:
            pk = _pack.pack_group(self.arrays, int(self.meta["depth"]),
                                  prefix=prefix)
            cache[prefix] = pk
        return pk

    def _traverse(self, X, prefix=""):
        """Sum of packed-tree leaf values — GenModel tree walk.

        The heap-layout level arrays flatten once into the serving
        pack's bitpacked node planes, then descend iteratively: one
        gather+compare per depth step over live nodes only, with an
        early exit once every (row, tree) sits on a leaf — node-sparse
        deep trees (PR 7) stop at their real frontier instead of
        walking 2^d-wide dead levels to depth 12.
        """
        from ..serving import pack as _pack
        i32, f32, roots = self._packed(prefix)
        leaves = _pack.traverse(i32, f32, roots, X,
                                int(self.meta["depth"]))
        return leaves.sum(axis=1)

    def _score_tree(self, data, n):
        X = self._design_raw(data, n)
        K = int(self.meta.get("nclass_trees", 1))
        avg = self.meta.get("tree_average", False)
        T = int(self.meta["ntrees"])
        if K > 1:
            scores = np.stack([self._traverse(X, prefix=f"k{k}_")
                               for k in range(K)], axis=1)
            scores += np.asarray(self.meta["init_score"])[None, :]
            if avg:
                p = np.clip(scores / max(T, 1), 0, 1)
                return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
            e = np.exp(scores - scores.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        s = self._traverse(X) + float(self.meta["init_score"])
        if avg:
            s = s / max(T, 1)
        if self.spec.get("response_domain"):
            p1 = np.clip(s if avg else 1 / (1 + np.exp(-s)), 0.0, 1.0)
            return np.stack([1 - p1, p1], axis=1)
        link = self.meta.get("link", "identity")
        return np.exp(s) if link == "log" else s

    def predict_contributions(self, data) -> dict:
        """TreeSHAP contributions — EasyPredictModelWrapper
        ``predictContributions`` analog (binomial/regression tree models).

        Returns {"names": [...features, "BiasTerm"], "contributions":
        [n, F+1]}; rows sum to the margin prediction.
        """
        if self.meta.get("family") != "tree":
            raise ValueError("contributions are for tree models")
        if int(self.meta.get("nclass_trees", 1)) > 1:
            raise ValueError("contributions support binomial/regression "
                             "models only")
        if "covers" not in self.arrays:
            raise ValueError("artifact has no covers; re-export from a "
                             "model trained with cover recording")
        from . import treeshap
        T = int(self.meta["ntrees"])
        depth = int(self.meta["depth"])
        trees = []
        for t in range(T):
            trees.append(treeshap._ShapTree(
                [self.arrays[f"feat_{d}"][t] for d in range(depth)],
                [self.arrays[f"thr_{d}"][t] for d in range(depth)],
                [self.arrays[f"na_left_{d}"][t] for d in range(depth)],
                [self.arrays[f"valid_{d}"][t] for d in range(depth)],
                self.arrays["values"][t], self.arrays["covers"][t]))
        data = {k: np.asarray(v) for k, v in data.items()}
        n = len(next(iter(data.values())))
        X = self._design_raw(data, n).astype(np.float64)
        if self.meta.get("tree_average", False):
            scale, init = 1.0 / max(T, 1), 0.0
        else:
            scale, init = 1.0, float(self.meta["init_score"])
        contribs = treeshap.ensemble_contributions(trees, X, init, scale)
        names = [s["name"] for s in self.spec["specs"]] + ["BiasTerm"]
        return {"names": names, "contributions": contribs}

    def _score_isolation(self, data, n):
        X = self._design_raw(data, n)
        T = int(self.meta["ntrees"])
        mean_len = self._traverse(X) / max(T, 1)
        c = max(self.meta["c_norm"], 1e-9)
        return np.exp2(-mean_len / c)

    def _score_deeplearning(self, data, n):
        X = self._design_standardized(data, n)
        i = 0
        h = X
        act = self.meta["activation"]
        while f"W_{i}" in self.arrays:
            W, b = self.arrays[f"W_{i}"], self.arrays[f"b_{i}"]
            h = h @ W + b
            if f"W_{i+1}" in self.arrays:          # hidden layer
                if act == "tanh":
                    h = np.tanh(h)
                else:
                    h = np.maximum(h, 0.0)
            i += 1
        if self.spec.get("response_domain"):
            e = np.exp(h - h.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        return h.reshape(-1) * self.meta.get("response_sigma", 1.0) \
            + self.meta.get("response_mean", 0.0)

    def _score_kmeans(self, data, n):
        X = self._design_standardized(data, n)
        C = self.arrays["centers_std"]
        d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(axis=2)
        return np.argmin(d2, axis=1).astype(np.float64)

    def _score_pca(self, data, n):
        cols = self._design_standardized(data, n)
        mu, sd = self.arrays["mu"], self.arrays["sd"]
        Xt = (cols - mu[None, :]) * sd[None, :]
        return Xt @ self.arrays["eigenvectors"]

    def _score_naivebayes(self, data, n):
        X = self._design_standardized(data, n)
        ll = X @ self.arrays["log_cat_table"] \
            + self.arrays["log_prior"][None, :]
        idx = self.arrays["num_idx"].astype(int)
        if len(idx):
            Xn = X[:, idx]
            mu = self.arrays["num_mu"]
            diff = Xn[:, None, :] - mu[None, :, :]
            ll = ll - (diff * diff * self.arrays["num_inv2var"][None]
                       + self.arrays["num_logsd"][None]).sum(axis=2)
        ll -= ll.max(axis=1, keepdims=True)
        p = np.exp(ll)
        return p / p.sum(axis=1, keepdims=True)

    def _score_isotonic(self, data, n):
        x = np.asarray(data[self.meta["feature"]], np.float64)
        tx, ty = self.arrays["thresholds_x"], self.arrays["thresholds_y"]
        pred = np.interp(x, tx, ty)
        if self.meta.get("out_of_bounds") == "na":
            pred = np.where((x < tx[0]) | (x > tx[-1]), np.nan, pred)
        return np.where(np.isnan(x), np.nan, pred)
