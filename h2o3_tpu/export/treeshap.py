"""TreeSHAP: exact Shapley feature contributions for tree ensembles.

Reference: ``h2o-extensions/xgboost/.../predict/PredictTreeSHAPTask.java``
and ``h2o-genmodel`` EasyPredictModelWrapper ``predictContributions`` —
both run Lundberg's TreeSHAP (Algorithm 2 of the Tree SHAP paper) per row
per tree on the CPU using per-node covers recorded at training time.

This implementation is numpy-only on purpose: the live models and the
portable scoring artifact (export/scoring.py, "no jax import" contract)
share it.  Trees here are the perfect-depth per-level arrays of
models/tree/shared.py: an invalid interior node routes everything left, so
it behaves as a leaf whose value/cover are the cover-weighted aggregate of
its subtree (all cover sits on the leftmost path by construction).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class _ShapTree:
    """One tree unpacked into heap-ordered node arrays."""

    def __init__(self, feat, thr, na_left, valid, values, cover):
        depth = len(feat)
        self.depth = depth
        # per-level arrays; level d has 2^d nodes
        self.feat = [np.asarray(f, np.int64) for f in feat]
        self.thr = [np.asarray(t, np.float64) for t in thr]
        self.na_left = [np.asarray(n, bool) for n in na_left]
        self.valid = [np.asarray(v, bool) for v in valid]
        leaf_values = np.asarray(values, np.float64)
        leaf_cover = np.asarray(cover, np.float64)
        # bottom-up node value/cover (cover-weighted subtree means)
        self.value = [None] * (depth + 1)
        self.cover = [None] * (depth + 1)
        self.value[depth] = leaf_values
        self.cover[depth] = leaf_cover
        for d in range(depth - 1, -1, -1):
            cl = self.cover[d + 1][0::2]
            cr = self.cover[d + 1][1::2]
            vl = self.value[d + 1][0::2]
            vr = self.value[d + 1][1::2]
            c = cl + cr
            with np.errstate(invalid="ignore", divide="ignore"):
                v = np.where(c > 0, (vl * cl + vr * cr) / np.maximum(c, 1e-300),
                             0.0)
            self.value[d] = v
            self.cover[d] = c

    def is_leaf(self, d: int, i: int) -> bool:
        return d == self.depth or not self.valid[d][i]


def _extend(m, pz, po, pi):
    """EXTEND from the TreeSHAP paper: grow the feature path."""
    # m: list of [feature, zero_frac, one_frac, weight]
    l = len(m)
    m.append([pi, pz, po, 1.0 if l == 0 else 0.0])
    for i in range(l - 1, -1, -1):
        m[i + 1][3] += po * m[i][3] * (i + 1) / (l + 1)
        m[i][3] = pz * m[i][3] * (l - i) / (l + 1)


def _unwind(m, i):
    """UNWIND: undo the EXTEND that added path element i (new list)."""
    l = len(m) - 1
    pz, po = m[i][1], m[i][2]
    out = [row[:] for row in m]
    n = out[l][3]
    for j in range(l - 1, -1, -1):
        if po != 0:
            t = out[j][3]
            out[j][3] = n * (l + 1) / ((j + 1) * po)
            n = t - out[j][3] * pz * (l - j) / (l + 1)
        else:
            out[j][3] = out[j][3] * (l + 1) / (pz * (l - j))
    for j in range(i, l):
        out[j][0], out[j][1], out[j][2] = out[j + 1][0], out[j + 1][1], \
            out[j + 1][2]
    return out[:l]


def _unwound_sum(m, i):
    l = len(m) - 1
    pz, po = m[i][1], m[i][2]
    total = 0.0
    if po != 0:
        n = m[l][3]
        for j in range(l - 1, -1, -1):
            t = n / ((j + 1) * po)          # = unwound weight / (l+1)
            total += t
            n = m[j][3] - t * pz * (l - j)
    else:
        for j in range(l - 1, -1, -1):
            total += m[j][3] / (pz * (l - j))
    return total * (l + 1)


def _shap_recurse(tree: _ShapTree, x, phi, d, i, m, pz, po, pi):
    m = [row[:] for row in m]
    _extend(m, pz, po, pi)
    if tree.is_leaf(d, i):
        v = tree.value[d][i]
        for j in range(1, len(m)):
            w = _unwound_sum(m, j)
            phi[m[j][0]] += w * (m[j][2] - m[j][1]) * v
        return
    f = int(tree.feat[d][i])
    xv = x[f]
    goes_left = (not np.isnan(xv) and xv < tree.thr[d][i]) or \
        (np.isnan(xv) and tree.na_left[d][i])
    hot, cold = (2 * i, 2 * i + 1) if goes_left else (2 * i + 1, 2 * i)
    c_parent = tree.cover[d][i]
    if c_parent <= 0:
        return
    iz, io = 1.0, 1.0
    k = next((j for j in range(1, len(m)) if m[j][0] == f), None)
    if k is not None:
        iz, io = m[k][1], m[k][2]
        m = _unwind(m, k)
    ch, cc = tree.cover[d + 1][hot], tree.cover[d + 1][cold]
    _shap_recurse(tree, x, phi, d + 1, hot, m, iz * ch / c_parent, io, f)
    _shap_recurse(tree, x, phi, d + 1, cold, m, iz * cc / c_parent, 0.0, f)


def tree_contributions(tree: _ShapTree, X: np.ndarray) -> np.ndarray:
    """Per-row SHAP values for one tree: [n, F+1] (last col = bias)."""
    n, F = X.shape
    out = np.zeros((n, F + 1), np.float64)
    for r in range(n):
        phi = np.zeros(F, np.float64)
        _shap_recurse(tree, X[r], phi, 0, 0, [], 1.0, 1.0, -1)
        out[r, :F] = phi
        out[r, F] = tree.value[0][0]
    return out


def ensemble_contributions(trees: List[_ShapTree], X: np.ndarray,
                           init_score: float = 0.0,
                           scale: float = 1.0) -> np.ndarray:
    """Summed SHAP over an ensemble; bias column absorbs init_score.

    Invariant (tested): ``contribs.sum(axis=1) == margin prediction``.
    ``scale`` handles averaged ensembles (DRF: 1/ntrees).
    """
    n, F = X.shape
    out = np.zeros((n, F + 1), np.float64)
    for t in trees:
        out += tree_contributions(t, X)
    out *= scale
    out[:, F] += init_score
    return out


def shap_trees_from_model(trees) -> List[_ShapTree]:
    """Build _ShapTrees from host ``Tree`` objects (cover required)."""
    out = []
    for t in trees:
        if t.cover is None:
            raise ValueError(
                "tree has no recorded covers; contributions need a model "
                "trained by this version (re-train to enable TreeSHAP)")
        out.append(_ShapTree([np.asarray(f) for f in t.feat],
                             [np.asarray(x) for x in t.thr],
                             [np.asarray(x) for x in t.na_left],
                             [np.asarray(x) for x in t.valid],
                             np.asarray(t.values), np.asarray(t.cover)))
    return out
