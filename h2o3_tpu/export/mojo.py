"""Portable model archive writer/reader — the MOJO analog.

Reference: ``hex/genmodel/MojoModel.java:12`` + ``ModelMojoReader.java:25``:
a MOJO is a zip of binary blobs + metadata that the dependency-free genmodel
library scores offline.  Here the archive is a zip holding ``model.json``
(algo, featurization layout, link/metadata) and ``arrays.npz`` (all learned
tensors); ``scoring.py`` (numpy-only) is the genmodel analog that loads and
scores it with no jax and no cluster.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict

import numpy as np

from .scoring import ScoringModel

FORMAT_VERSION = 1


def _datainfo_meta(di) -> dict:
    return {
        "specs": [{
            "name": s.name, "type": s.type, "domain": s.domain,
            "mean": float(s.mean), "sigma": float(s.sigma),
            "offset": s.offset, "width": s.width,
        } for s in di.specs],
        "response_column": di.response_column,
        "response_domain": di.response_domain,
        "use_all_factor_levels": di.use_all_factor_levels,
        "standardize": di.standardize,
        "add_intercept": di.add_intercept,
        "nfeatures": di.nfeatures,
    }


def _tree_arrays(trees, depth: int, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for d in range(depth):
        out[f"{prefix}feat_{d}"] = np.stack(
            [np.asarray(t.feat[d]) for t in trees]).astype(np.int32)
        out[f"{prefix}thr_{d}"] = np.stack(
            [np.asarray(t.thr[d]) for t in trees]).astype(np.float32)
        out[f"{prefix}na_left_{d}"] = np.stack(
            [np.asarray(t.na_left[d]) for t in trees]).astype(bool)
        out[f"{prefix}valid_{d}"] = np.stack(
            [np.asarray(t.valid[d]) for t in trees]).astype(bool)
    out[f"{prefix}values"] = np.stack(
        [np.asarray(t.values) for t in trees]).astype(np.float32)
    if all(getattr(t, "cover", None) is not None for t in trees):
        # per-leaf training covers -> TreeSHAP contributions in the scorer
        out[f"{prefix}covers"] = np.stack(
            [np.asarray(t.cover) for t in trees]).astype(np.float32)
    return out


def _extract(model) -> (dict, Dict[str, np.ndarray]):
    """(meta, arrays) for the algo families with standalone scorers."""
    algo = model.algo
    o = model.output
    meta = {
        "algo": algo,
        "format_version": FORMAT_VERSION,
        "datainfo": _datainfo_meta(model.datainfo),
        "default_threshold": float(model.default_threshold())
        if model.datainfo.is_classifier else 0.5,
    }
    arrays: Dict[str, np.ndarray] = {}

    if algo == "glm":
        meta["family"] = "glm"
        fam = o.get("family", "gaussian")
        meta["link"] = {"binomial": "logit", "quasibinomial": "logit",
                        "poisson": "log", "gamma": "log", "tweedie": "log",
                        "negativebinomial": "log"}.get(fam, "identity")
        arrays["beta"] = np.asarray(o["beta_std"], np.float64)
    elif algo in ("gbm", "xgboost", "drf"):
        meta["family"] = "tree"
        meta["tree_average"] = algo == "drf"
        trees = o["trees"]
        K = o.get("nclass_trees", 1)
        meta["nclass_trees"] = K
        meta["depth"] = model.params.max_depth
        meta["ntrees"] = len(trees)
        dist = o.get("distribution", "gaussian")
        meta["link"] = "log" if dist in ("poisson", "gamma", "tweedie") \
            else "identity"
        if K > 1:
            meta["init_score"] = [float(v) for v in np.asarray(
                o["init_score"])]
            for k in range(K):
                arrays.update(_tree_arrays([t[k] for t in trees],
                                           model.params.max_depth,
                                           prefix=f"k{k}_"))
        else:
            meta["init_score"] = float(np.asarray(o["init_score"]))
            arrays.update(_tree_arrays(trees, model.params.max_depth))
    elif algo == "isolationforest":
        meta["family"] = "isolation"
        meta["depth"] = model.params.max_depth
        meta["ntrees"] = len(o["trees"])
        meta["c_norm"] = float(o["c_norm"])
        arrays.update(_tree_arrays(o["trees"], model.params.max_depth))
    elif algo == "deeplearning":
        meta["family"] = "deeplearning"
        act = getattr(model.params, "activation", "rectifier")
        if act.startswith("maxout"):
            raise ValueError("portable export does not support maxout")
        meta["activation"] = "tanh" if act.startswith("tanh") else "rectifier"
        meta["response_mean"] = float(model.datainfo.response_mean)
        meta["response_sigma"] = float(model.datainfo.response_sigma)
        for i, (W, b) in enumerate(o["weights"]):
            arrays[f"W_{i}"] = np.asarray(W, np.float32)
            arrays[f"b_{i}"] = np.asarray(b, np.float32)
    elif algo == "kmeans":
        meta["family"] = "kmeans"
        arrays["centers_std"] = np.asarray(o["centers_std"], np.float64)
    elif algo in ("pca", "svd"):
        meta["family"] = "pca"
        arrays["eigenvectors"] = np.asarray(
            o.get("eigenvectors", o.get("v")), np.float64)
        arrays["mu"] = np.asarray(o["_mu"], np.float64)
        arrays["sd"] = np.asarray(o["_sd"], np.float64)
    elif algo == "naivebayes":
        meta["family"] = "naivebayes"
        arrays["log_cat_table"] = np.asarray(o["_log_cat_table"])
        arrays["log_prior"] = np.asarray(o["_log_prior"])
        arrays["num_idx"] = np.asarray(o["_num_idx"])
        arrays["num_mu"] = np.asarray(o["_num_mu"])
        arrays["num_inv2var"] = np.asarray(o["_num_inv2var"])
        arrays["num_logsd"] = np.asarray(o["_num_logsd"])
    elif algo == "isotonicregression":
        meta["family"] = "isotonic"
        meta["feature"] = o["feature"]
        meta["out_of_bounds"] = model.params.out_of_bounds
        arrays["thresholds_x"] = np.asarray(o["thresholds_x"])
        arrays["thresholds_y"] = np.asarray(o["thresholds_y"])
    else:
        raise ValueError(f"no portable export for algo {algo!r}")
    return meta, arrays


def export_mojo(model, path: str) -> str:
    """Write the portable artifact — Model.download_mojo analog."""
    meta, arrays = _extract(model)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.json", json.dumps(meta, indent=1))
        z.writestr("arrays.npz", buf.getvalue())
    return path


def import_mojo(path: str):
    """Load a portable artifact for offline scoring — MojoModel.load.

    Accepts BOTH this package's archives (model.json + arrays.npz) and
    REAL reference-produced H2O MOJO zips (model.ini + blobs; GBM/DRF/
    GLM) — the migration path for existing H2O users
    (hex/genmodel/ModelMojoReader.java:25)."""
    from .h2o_mojo import is_h2o_mojo, load_h2o_mojo
    if is_h2o_mojo(path):
        return load_h2o_mojo(path)
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("model.json"))
        npz = np.load(io.BytesIO(z.read("arrays.npz")))
        arrays = {k: npz[k] for k in npz.files}
    return ScoringModel(meta, arrays)
