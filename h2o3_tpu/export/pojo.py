"""POJO codegen: tree ensembles as self-contained Java (and C) source.

Reference: ``hex/tree/TreeJCodeGen.java`` + ``hex/ModelBuilder`` POJO
download (``/3/Models/<id>/java``): H2O renders a trained tree model as a
dependency-free Java class whose ``score0(double[] data, double[] preds)``
re-implements the ensemble as nested conditionals.

This emitter produces the same artifact from this framework's per-level
array trees.  The decision logic is generated once and rendered through a
tiny syntax table into BOTH Java (the POJO deliverable) and C (the same
trees as a compilable shared library).  The image has no javac, so the
test suite compiles the C twin with gcc and asserts bit-identical
predictions against the in-framework scorer — validating the generated
conditionals themselves; the Java rendering differs only in spelling
(``Double.isNaN`` vs ``isnan``).

Input convention (same as the reference POJO): ``data[j]`` holds the j-th
feature, numerics as-is, categoricals as the code in ``DOMAINS[j]``
(NaN = missing / unseen).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

_JAVA = {"isnan": "Double.isNaN", "static": "static double",
         "array_arg": "double[] data"}
_C = {"isnan": "isnan", "static": "static double",
      "array_arg": "const double* data"}


def _fmt(v: float) -> str:
    """Shortest round-trip double literal, valid in both Java and C."""
    return repr(float(v))


def _tree_source(tree, depth: int, name: str, lang: dict) -> str:
    """One tree -> one static function of nested conditionals."""
    feat = [np.asarray(a) for a in tree.feat]
    thr = [np.asarray(a) for a in tree.thr]
    na_left = [np.asarray(a) for a in tree.na_left]
    valid = [np.asarray(a) for a in tree.valid]
    values = np.asarray(tree.values)
    lines: List[str] = [f"{lang['static']} {name}({lang['array_arg']}) {{"]

    def is_leaf(d, i):
        return d == depth or not bool(valid[d][i])

    def emit(d, i, indent):
        pad = "  " * indent
        if is_leaf(d, i):
            lines.append(f"{pad}return {_fmt(values[i << (depth - d)])};")
            return
        f, t = int(feat[d][i]), float(thr[d][i])
        nl = bool(na_left[d][i])
        # missing goes left iff na_left; otherwise split on value >= thr
        go_right = (f"!{lang['isnan']}(data[{f}]) && data[{f}] >= {_fmt(t)}"
                    if nl else
                    f"{lang['isnan']}(data[{f}]) || data[{f}] >= {_fmt(t)}")
        lines.append(f"{pad}if ({go_right}) {{")
        emit(d + 1, 2 * i + 1, indent + 1)
        lines.append(f"{pad}}} else {{")
        emit(d + 1, 2 * i, indent + 1)
        lines.append(f"{pad}}}")

    emit(0, 0, 1)
    lines.append("}")
    return "\n".join(lines)


def _model_trees(model):
    trees = list(model.output["trees"])
    K = model.output.get("nclass_trees", 1)
    if K > 1:
        return [[t[k] for k in range(K)] for t in trees], K
    return [[t] for t in trees], 1


def _score_body(model, matrix, K: int, lang: dict) -> List[str]:
    """score0 body: sum trees per class, apply init + link."""
    init = np.atleast_1d(np.asarray(model.output["init_score"], np.float64))
    dist = str(model.output.get("distribution", "gaussian"))
    nclasses = model.datainfo.nclasses
    is_drf = model.algo == "drf"
    T = len(matrix)
    exp = "Math.exp" if lang is _JAVA else "exp"
    out: List[str] = []
    for k in range(K):
        terms = " + ".join(f"tree_{k}_{g}(data)" for g in range(T))
        out.append(f"  double f{k} = {terms};")
    if K > 1:                                         # multinomial softmax
        for k in range(K):
            out.append(f"  f{k} += {_fmt(init[k])};")
        if not is_drf:
            out.append("  double mx = f0;")
            for k in range(1, K):
                out.append(f"  if (f{k} > mx) mx = f{k};")
            out.append("  double tot = 0.0;")
            for k in range(K):
                out.append(f"  double e{k} = {exp}(f{k} - mx); tot += e{k};")
            for k in range(K):
                out.append(f"  preds[{k + 1}] = e{k} / tot;")
        else:                                          # DRF: normalized votes
            out.append("  double tot = 0.0;")
            for k in range(K):
                out.append(f"  f{k} /= {_fmt(T)}; if (f{k} < 0.0) f{k} = "
                           f"0.0; tot += f{k};")
            for k in range(K):
                out.append(f"  preds[{k + 1}] = tot > 0.0 ? f{k} / tot "
                           ": 0.0;")
    elif nclasses == 2:
        if is_drf:
            out.append(f"  double p1 = f0 / {_fmt(T)};")
            out.append("  if (p1 < 0.0) p1 = 0.0; if (p1 > 1.0) p1 = 1.0;")
        else:
            out.append(f"  double p1 = 1.0 / (1.0 + {exp}(-(f0 "
                       f"+ {_fmt(init[0])})));")
        out.append("  preds[1] = 1.0 - p1;")
        out.append("  preds[2] = p1;")
        out.append("  preds[0] = p1 >= "
                   f"{_fmt(model.default_threshold())} ? 1.0 : 0.0;")
    else:                                              # regression
        if is_drf:
            out.append(f"  preds[0] = f0 / {_fmt(T)};")
        elif dist in ("poisson", "gamma", "tweedie"):
            out.append(f"  preds[0] = {exp}(f0 + {_fmt(init[0])});")
        else:
            out.append(f"  preds[0] = f0 + {_fmt(init[0])};")
    return out


def _domains_java(di) -> List[str]:
    from ..frame.vec import T_CAT
    rows = []
    for s in di.specs:
        if s.type == T_CAT and s.domain:
            levels = ", ".join('"%s"' % str(x).replace('"', '\\"')
                               for x in s.domain)
            rows.append(f"    new String[] {{{levels}}},")
        else:
            rows.append("    null,")
    return rows


def _glm_score_body(model, lang: dict) -> List[str]:
    """GLM linear predictor + link inverse as generated conditionals.

    Raw-space coefficients (``output["beta"]`` — the destandardized
    vector) over the POJO input convention; the learned NA buckets and
    mean imputation are kept, so scoring matches the in-framework model
    on every row including missing values.  Reference analog:
    ``GLMModel.toJavaPredictBody``.
    """
    from ..frame.vec import T_CAT
    di = model.datainfo
    fam = model.output["family"]
    if fam in ("multinomial", "ordinal"):
        raise ValueError(
            "GLM POJO export covers binomial/regression families")
    beta = np.asarray(model.output["beta"], np.float64)
    isnan = lang["isnan"]
    out = []
    intercept = float(beta[-1]) if di.add_intercept else 0.0
    out.append(f"    double lp = {_fmt(intercept)};")
    out.append("    double v;")
    lo = 0 if di.use_all_factor_levels else 1
    for j, s in enumerate(di.specs):
        out.append(f"    v = data[{j}];")
        if s.type == T_CAT:
            width = s.width - 1          # one-hot slots before the NA slot
            na_b = float(beta[s.offset + width])
            out.append(f"    if ({isnan}(v) || v < 0) "
                       f"lp += {_fmt(na_b)};")
            out.append("    else {")
            out.append(f"      int k = (int) v - {lo};")
            betas = ", ".join(_fmt(float(b))
                              for b in beta[s.offset: s.offset + width])
            if lang is _JAVA:
                out.append(f"      double[] cb = new double[] {{{betas}}};")
            else:
                out.append(f"      const double cb[] = {{{betas}}};")
            out.append(f"      if (k >= 0 && k < {width}) lp += cb[k];")
            out.append("    }")
        else:
            b = float(beta[s.offset])
            out.append(f"    if ({isnan}(v)) v = {_fmt(float(s.mean))};")
            out.append(f"    lp += {_fmt(b)} * v;")
    link = {"binomial": "logit", "quasibinomial": "logit",
            "fractionalbinomial": "logit", "poisson": "log",
            "gamma": "log", "tweedie": "log",
            "negativebinomial": "log"}.get(fam, "identity")
    if link == "logit":
        out.append("    double mu = 1.0 / (1.0 + exp(-lp));"
                   if lang is _C else
                   "    double mu = 1.0 / (1.0 + Math.exp(-lp));")
    elif link == "log":
        out.append("    double mu = exp(lp);" if lang is _C else
                   "    double mu = Math.exp(lp);")
    else:
        out.append("    double mu = lp;")
    if di.nclasses == 2:
        thr = float(model.default_threshold())
        out.append("    preds[1] = 1.0 - mu;")
        out.append("    preds[2] = mu;")
        out.append(f"    preds[0] = mu >= {_fmt(thr)} ? 1 : 0;")
    else:
        out.append("    preds[0] = mu;")
    return out


def export_pojo(model, path: str, class_name: Optional[str] = None) -> str:
    """Write a dependency-free Java scoring class (TreeJCodeGen analog;
    GLM via the generic Model.toJava pattern, Model.java:2484)."""
    if model.algo == "glm":
        return _export_pojo_glm_java(model, path, class_name)
    if model.algo not in ("gbm", "drf", "xgboost"):
        raise ValueError("POJO export covers tree ensembles "
                         "(gbm/drf/xgboost) and GLM")
    di = model.datainfo
    matrix, K = _model_trees(model)
    depth = model.params.max_depth
    cname = class_name or "".join(
        ch if ch.isalnum() else "_" for ch in model.key)
    if not cname[0].isalpha():
        cname = "M_" + cname
    names = ", ".join(f'"{s.name}"' for s in di.specs)
    nclasses = max(di.nclasses, 1)
    preds_len = 1 if nclasses == 1 else nclasses + 1
    parts = [
        "// Generated scoring POJO — self-contained, no h2o-genmodel",
        f"// dependency.  Columns: data[j] = NAMES[j]; categorical columns",
        "// carry the code of the level in DOMAINS[j] (NaN = missing).",
        f"public class {cname} {{",
        f"  public static final String[] NAMES = new String[] {{{names}}};",
        "  public static final String[][] DOMAINS = new String[][] {",
        *_domains_java(di),
        "  };",
        f"  public static final int NCLASSES = {nclasses};",
        "",
        f"  public static double[] score0(double[] data, double[] preds) {{",
        *_score_body(model, matrix, K, _JAVA),
        "    return preds;",
        "  }",
        "",
        f"  public static double[] score0(double[] data) {{",
        f"    return score0(data, new double[{preds_len}]);",
        "  }",
        "",
    ]
    for g, per_class in enumerate(matrix):
        for k, tree in enumerate(per_class):
            src = _tree_source(tree, depth, f"tree_{k}_{g}", _JAVA)
            parts.append("  " + src.replace("\n", "\n  "))
            parts.append("")
    parts.append("}")
    with open(path, "w") as fh:
        fh.write("\n".join(parts) + "\n")
    return path


def _export_pojo_glm_java(model, path: str,
                          class_name: Optional[str] = None) -> str:
    di = model.datainfo
    cname = class_name or "".join(
        ch if ch.isalnum() else "_" for ch in model.key)
    if not cname[0].isalpha():
        cname = "M_" + cname
    names = ", ".join(f'"{s.name}"' for s in di.specs)
    nclasses = max(di.nclasses, 1)
    preds_len = 1 if nclasses == 1 else nclasses + 1
    parts = [
        "// Generated GLM scoring POJO — self-contained, no h2o-genmodel",
        "// dependency.  Columns: data[j] = NAMES[j]; categorical columns",
        "// carry the code of the level in DOMAINS[j] (NaN = missing).",
        f"public class {cname} {{",
        f"  public static final String[] NAMES = new String[] {{{names}}};",
        "  public static final String[][] DOMAINS = new String[][] {",
        *_domains_java(di),
        "  };",
        f"  public static final int NCLASSES = {nclasses};",
        "",
        "  public static double[] score0(double[] data, double[] preds) {",
        *_glm_score_body(model, _JAVA),
        "    return preds;",
        "  }",
        "",
        "  public static double[] score0(double[] data) {",
        f"    return score0(data, new double[{preds_len}]);",
        "  }",
        "}",
    ]
    with open(path, "w") as fh:
        fh.write("\n".join(parts) + "\n")
    return path


def export_pojo_c(model, path: str) -> str:
    """The same generated trees as a C translation unit exporting
    ``score0(const double* data, double* preds)`` — compiled by the test
    suite to validate the codegen, and usable as a native scorer."""
    if model.algo == "glm":
        parts = ["#include <math.h>", "",
                 "double* score0(const double* data, double* preds) {",
                 *_glm_score_body(model, _C),
                 "  return preds;",
                 "}"]
        with open(path, "w") as fh:
            fh.write("\n".join(parts) + "\n")
        return path
    if model.algo not in ("gbm", "drf", "xgboost"):
        raise ValueError("POJO export covers tree ensembles "
                         "(gbm/drf/xgboost) and GLM")
    matrix, K = _model_trees(model)
    depth = model.params.max_depth
    body = _score_body(model, matrix, K, _C)
    parts = ["#include <math.h>", ""]
    for g, per_class in enumerate(matrix):
        for k, tree in enumerate(per_class):
            parts.append(_tree_source(tree, depth, f"tree_{k}_{g}", _C))
            parts.append("")
    parts.append("double* score0(const double* data, double* preds) {")
    parts.extend(body)
    parts.append("  return preds;")
    parts.append("}")
    with open(path, "w") as fh:
        fh.write("\n".join(parts) + "\n")
    return path
