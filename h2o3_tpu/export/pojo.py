"""POJO codegen: tree ensembles as self-contained Java (and C) source.

Reference: ``hex/tree/TreeJCodeGen.java`` + ``hex/ModelBuilder`` POJO
download (``/3/Models/<id>/java``): H2O renders a trained tree model as a
dependency-free Java class whose ``score0(double[] data, double[] preds)``
re-implements the ensemble as nested conditionals.

This emitter produces the same artifact from this framework's per-level
array trees.  The decision logic is generated once and rendered through a
tiny syntax table into BOTH Java (the POJO deliverable) and C (the same
trees as a compilable shared library).  The image has no javac, so the
test suite compiles the C twin with gcc and asserts bit-identical
predictions against the in-framework scorer — validating the generated
conditionals themselves; the Java rendering differs only in spelling
(``Double.isNaN`` vs ``isnan``).

Input convention (same as the reference POJO): ``data[j]`` holds the j-th
feature, numerics as-is, categoricals as the code in ``DOMAINS[j]``
(NaN = missing / unseen).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

_JAVA = {"isnan": "Double.isNaN", "static": "static double",
         "array_arg": "double[] data"}
_C = {"isnan": "isnan", "static": "static double",
      "array_arg": "const double* data"}


def _fmt(v: float) -> str:
    """Shortest round-trip double literal, valid in both Java and C."""
    return repr(float(v))


def _tree_source(tree, depth: int, name: str, lang: dict) -> str:
    """One tree -> one static function of nested conditionals."""
    feat = [np.asarray(a) for a in tree.feat]
    thr = [np.asarray(a) for a in tree.thr]
    na_left = [np.asarray(a) for a in tree.na_left]
    valid = [np.asarray(a) for a in tree.valid]
    values = np.asarray(tree.values)
    lines: List[str] = [f"{lang['static']} {name}({lang['array_arg']}) {{"]

    def is_leaf(d, i):
        return d == depth or not bool(valid[d][i])

    def emit(d, i, indent):
        pad = "  " * indent
        if is_leaf(d, i):
            lines.append(f"{pad}return {_fmt(values[i << (depth - d)])};")
            return
        f, t = int(feat[d][i]), float(thr[d][i])
        nl = bool(na_left[d][i])
        # missing goes left iff na_left; otherwise split on value >= thr
        go_right = (f"!{lang['isnan']}(data[{f}]) && data[{f}] >= {_fmt(t)}"
                    if nl else
                    f"{lang['isnan']}(data[{f}]) || data[{f}] >= {_fmt(t)}")
        lines.append(f"{pad}if ({go_right}) {{")
        emit(d + 1, 2 * i + 1, indent + 1)
        lines.append(f"{pad}}} else {{")
        emit(d + 1, 2 * i, indent + 1)
        lines.append(f"{pad}}}")

    emit(0, 0, 1)
    lines.append("}")
    return "\n".join(lines)


def _model_trees(model):
    trees = list(model.output["trees"])
    K = model.output.get("nclass_trees", 1)
    if K > 1:
        return [[t[k] for k in range(K)] for t in trees], K
    return [[t] for t in trees], 1


def _score_body(model, matrix, K: int, lang: dict) -> List[str]:
    """score0 body: sum trees per class, apply init + link."""
    init = np.atleast_1d(np.asarray(model.output["init_score"], np.float64))
    dist = str(model.output.get("distribution", "gaussian"))
    nclasses = model.datainfo.nclasses
    is_drf = model.algo == "drf"
    T = len(matrix)
    exp = "Math.exp" if lang is _JAVA else "exp"
    out: List[str] = []
    for k in range(K):
        terms = " + ".join(f"tree_{k}_{g}(data)" for g in range(T))
        out.append(f"  double f{k} = {terms};")
    if K > 1:                                         # multinomial softmax
        for k in range(K):
            out.append(f"  f{k} += {_fmt(init[k])};")
        if not is_drf:
            out.append("  double mx = f0;")
            for k in range(1, K):
                out.append(f"  if (f{k} > mx) mx = f{k};")
            out.append("  double tot = 0.0;")
            for k in range(K):
                out.append(f"  double e{k} = {exp}(f{k} - mx); tot += e{k};")
            for k in range(K):
                out.append(f"  preds[{k + 1}] = e{k} / tot;")
        else:                                          # DRF: normalized votes
            out.append("  double tot = 0.0;")
            for k in range(K):
                out.append(f"  f{k} /= {_fmt(T)}; if (f{k} < 0.0) f{k} = "
                           f"0.0; tot += f{k};")
            for k in range(K):
                out.append(f"  preds[{k + 1}] = tot > 0.0 ? f{k} / tot "
                           ": 0.0;")
    elif nclasses == 2:
        if is_drf:
            out.append(f"  double p1 = f0 / {_fmt(T)};")
            out.append("  if (p1 < 0.0) p1 = 0.0; if (p1 > 1.0) p1 = 1.0;")
        else:
            out.append(f"  double p1 = 1.0 / (1.0 + {exp}(-(f0 "
                       f"+ {_fmt(init[0])})));")
        out.append("  preds[1] = 1.0 - p1;")
        out.append("  preds[2] = p1;")
        out.append("  preds[0] = p1 >= "
                   f"{_fmt(model.default_threshold())} ? 1.0 : 0.0;")
    else:                                              # regression
        if is_drf:
            out.append(f"  preds[0] = f0 / {_fmt(T)};")
        elif dist in ("poisson", "gamma", "tweedie"):
            out.append(f"  preds[0] = {exp}(f0 + {_fmt(init[0])});")
        else:
            out.append(f"  preds[0] = f0 + {_fmt(init[0])};")
    return out


def _domains_java(di) -> List[str]:
    from ..frame.vec import T_CAT
    rows = []
    for s in di.specs:
        if s.type == T_CAT and s.domain:
            levels = ", ".join('"%s"' % str(x).replace('"', '\\"')
                               for x in s.domain)
            rows.append(f"    new String[] {{{levels}}},")
        else:
            rows.append("    null,")
    return rows


def export_pojo(model, path: str, class_name: Optional[str] = None) -> str:
    """Write a dependency-free Java scoring class (TreeJCodeGen analog)."""
    if model.algo not in ("gbm", "drf", "xgboost"):
        raise ValueError("POJO export covers tree ensembles "
                         "(gbm/drf/xgboost)")
    di = model.datainfo
    matrix, K = _model_trees(model)
    depth = model.params.max_depth
    cname = class_name or "".join(
        ch if ch.isalnum() else "_" for ch in model.key)
    if not cname[0].isalpha():
        cname = "M_" + cname
    names = ", ".join(f'"{s.name}"' for s in di.specs)
    nclasses = max(di.nclasses, 1)
    preds_len = 1 if nclasses == 1 else nclasses + 1
    parts = [
        "// Generated scoring POJO — self-contained, no h2o-genmodel",
        f"// dependency.  Columns: data[j] = NAMES[j]; categorical columns",
        "// carry the code of the level in DOMAINS[j] (NaN = missing).",
        f"public class {cname} {{",
        f"  public static final String[] NAMES = new String[] {{{names}}};",
        "  public static final String[][] DOMAINS = new String[][] {",
        *_domains_java(di),
        "  };",
        f"  public static final int NCLASSES = {nclasses};",
        "",
        f"  public static double[] score0(double[] data, double[] preds) {{",
        *_score_body(model, matrix, K, _JAVA),
        "    return preds;",
        "  }",
        "",
        f"  public static double[] score0(double[] data) {{",
        f"    return score0(data, new double[{preds_len}]);",
        "  }",
        "",
    ]
    for g, per_class in enumerate(matrix):
        for k, tree in enumerate(per_class):
            src = _tree_source(tree, depth, f"tree_{k}_{g}", _JAVA)
            parts.append("  " + src.replace("\n", "\n  "))
            parts.append("")
    parts.append("}")
    with open(path, "w") as fh:
        fh.write("\n".join(parts) + "\n")
    return path


def export_pojo_c(model, path: str) -> str:
    """The same generated trees as a C translation unit exporting
    ``score0(const double* data, double* preds)`` — compiled by the test
    suite to validate the codegen, and usable as a native scorer."""
    if model.algo not in ("gbm", "drf", "xgboost"):
        raise ValueError("POJO export covers tree ensembles "
                         "(gbm/drf/xgboost)")
    matrix, K = _model_trees(model)
    depth = model.params.max_depth
    body = _score_body(model, matrix, K, _C)
    parts = ["#include <math.h>", ""]
    for g, per_class in enumerate(matrix):
        for k, tree in enumerate(per_class):
            parts.append(_tree_source(tree, depth, f"tree_{k}_{g}", _C))
            parts.append("")
    parts.append("double* score0(const double* data, double* preds) {")
    parts.extend(body)
    parts.append("  return preds;")
    parts.append("}")
    with open(path, "w") as fh:
        fh.write("\n".join(parts) + "\n")
    return path
