"""Scoring pipelines: transformer chain + model in ONE portable artifact.

Reference: ``h2o-extensions/mojo-pipeline/`` — H2O scores pipeline MOJOs
(transformations + model bundled by Driverless AI) inside the cluster via
``MojoPipeline`` models.  The TPU-native analog bundles this framework's
own fitted transformers (target encoders — the transformer the reference
itself ships as an extension) with a trained model in a single zip that
scores standalone (numpy only, no cluster), mirroring the portable MOJO
contract of ``export/mojo.py``.

Format: ``pipeline.json`` (step specs: encoder tables as lists, blending
constants, source column domains) + ``model.zip`` (the portable model
artifact).  ``load_pipeline`` -> ``ScoringPipeline.predict(dict)``:
applies each encoder in inference mode (no leakage handling, blending as
trained), appends ``<col>_te`` columns, then scores the model.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Sequence

import numpy as np

_FORMAT_VERSION = 1


def _te_spec(te_model) -> dict:
    """Serialize a fitted TargetEncoderModel's inference state."""
    out = te_model.output
    p = te_model.params
    cols = {}
    for col, tbl in out["encoding_tables"].items():
        spec = next(s for s in te_model.datainfo.specs if s.name == col)
        cols[col] = {
            "domain": list(spec.domain or []),
            "sums": np.asarray(tbl["sums"], np.float64).tolist(),
            "counts": np.asarray(tbl["counts"], np.float64).tolist(),
        }
    return {
        "kind": "target_encoder",
        "columns": cols,
        "prior_mean": float(out["prior_mean"]),
        "blending": bool(p.blending),
        "inflection_point": float(p.inflection_point),
        "smoothing": float(p.smoothing),
    }


def export_pipeline(model, path: str, transformers: Sequence = ()) -> str:
    """Bundle fitted transformers + a trained model into one zip."""
    from .mojo import export_mojo
    steps: List[dict] = []
    for t in transformers:
        if getattr(t, "algo", None) == "targetencoder":
            steps.append(_te_spec(t))
        else:
            raise ValueError(
                f"unsupported pipeline transformer {t!r} "
                "(fitted TargetEncoder models are supported)")
    buf = io.BytesIO()
    export_mojo(model, buf)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("pipeline.json", json.dumps({
            "format_version": _FORMAT_VERSION,
            "steps": steps,
        }))
        zf.writestr("model.zip", buf.getvalue())
    return path


class ScoringPipeline:
    """Standalone pipeline scorer (numpy only, cluster-free)."""

    def __init__(self, steps: List[dict], scorer):
        self.steps = steps
        self.scorer = scorer

    def _apply_te(self, step: dict, data: Dict[str, list]) -> None:
        prior = step["prior_mean"]
        for col, spec in step["columns"].items():
            if col not in data:
                continue
            lookup = {s: i for i, s in enumerate(spec["domain"])}
            sums = np.asarray(spec["sums"])
            counts = np.asarray(spec["counts"])
            vals = data[col]
            codes = np.array([lookup.get(str(v), -1)
                              if v is not None else -1 for v in vals])
            ok = (codes >= 0) & (codes < len(sums))
            cc = np.clip(codes, 0, max(len(sums) - 1, 0))
            s = np.where(ok, sums[cc], 0.0)
            c = np.where(ok, counts[cc], 0.0)
            mean = np.where(c > 0, s / np.maximum(c, 1e-12), prior)
            if step["blending"]:
                lam = 1.0 / (1.0 + np.exp(
                    -(c - step["inflection_point"])
                    / max(step["smoothing"], 1e-6)))
                mean = lam * mean + (1 - lam) * prior
            data[f"{col}_te"] = mean.tolist()

    def predict(self, data: Dict[str, Sequence]) -> dict:
        data = {k: list(v) for k, v in data.items()}
        for step in self.steps:
            if step["kind"] == "target_encoder":
                self._apply_te(step, data)
            else:                       # pragma: no cover — format guard
                raise ValueError(f"unknown pipeline step {step['kind']!r}")
        return self.scorer.predict(data)


def load_pipeline(path) -> ScoringPipeline:
    from .mojo import import_mojo
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read("pipeline.json"))
        if meta["format_version"] > _FORMAT_VERSION:
            raise ValueError("pipeline artifact from a newer format")
        model_bytes = zf.read("model.zip")
    return ScoringPipeline(meta["steps"],
                           import_mojo(io.BytesIO(model_bytes)))
