"""Tree inspection API — the h2o.tree.H2OTree analog.

Reference: ``h2o-py/h2o/tree/tree.py`` exposes a fitted tree's node
structure (children, thresholds, split features, NA directions, leaf
predictions) for inspection and plotting.  Here the source of truth is
the level-wise ``Tree`` arrays (models/tree/shared.py Tree): a node at
level d, index i has children (d+1, 2i) and (d+1, 2i+1); a node whose
``valid`` flag is False is terminal, predicting the value of the
left-most leaf its rows fall through to (the partition convention for
un-split nodes).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["H2OTree", "tree_from_model", "feature_interactions"]


class H2OTree:
    """Flattened node arrays in h2o.tree conventions: index 0 is the
    root; ``left_children``/``right_children`` hold node ids (-1 = no
    child); leaves carry ``predictions``; decision nodes carry
    ``features``/``thresholds``/``na_directions`` ("LEFT"/"RIGHT")."""

    def __init__(self, tree, feature_names: Sequence[str],
                 tree_number: int = 0, tree_class: Optional[str] = None):
        self.tree_number = tree_number
        self.tree_class = tree_class
        self.left_children: List[int] = []
        self.right_children: List[int] = []
        self.features: List[Optional[str]] = []
        self.thresholds: List[float] = []
        self.na_directions: List[Optional[str]] = []
        self.predictions: List[Optional[float]] = []
        self.covers: List[Optional[float]] = []
        feat = [np.asarray(f) for f in tree.feat]
        thr = [np.asarray(t) for t in tree.thr]
        nal = [np.asarray(n) for n in tree.na_left]
        valid = [np.asarray(v) for v in tree.valid]
        values = np.asarray(tree.values)
        cover = None if tree.cover is None else np.asarray(tree.cover)
        depth = len(feat)

        def add(d: int, i: int) -> int:
            nid = len(self.features)
            for lst in (self.left_children, self.right_children,
                        self.features, self.thresholds,
                        self.na_directions, self.predictions, self.covers):
                lst.append(None)
            self.thresholds[nid] = float("nan")
            self.left_children[nid] = -1
            self.right_children[nid] = -1
            if cover is not None:
                # node cover = its subtree's leaf-cover span (leaves AND
                # decision nodes — feature_interactions reads both)
                leftmost = i << (depth - d)
                span = cover[leftmost: (i + 1) << (depth - d)]
                self.covers[nid] = float(span.sum())
            if d == depth or not bool(valid[d][i]):
                self.predictions[nid] = float(values[i << (depth - d)])
                return nid
            self.features[nid] = feature_names[int(feat[d][i])]
            self.thresholds[nid] = float(thr[d][i])
            self.na_directions[nid] = "LEFT" if bool(nal[d][i]) else "RIGHT"
            self.left_children[nid] = add(d + 1, 2 * i)
            self.right_children[nid] = add(d + 1, 2 * i + 1)
            return nid

        add(0, 0)

    def __len__(self) -> int:
        return len(self.features)

    @property
    def root_node_id(self) -> int:
        return 0

    def to_dot(self) -> str:
        """Graphviz DOT rendering (h2o's tree plotting feed)."""
        def esc(s: str) -> str:
            return s.replace("\\", "\\\\").replace('"', '\\"')
        lines = ["digraph tree {", "  node [shape=box];"]
        for n in range(len(self)):
            if self.features[n] is not None:
                lines.append(
                    f'  n{n} [label="{esc(self.features[n])} < '
                    f'{self.thresholds[n]:.6g}\\nNA -> '
                    f'{self.na_directions[n]}"];')
                lines.append(f"  n{n} -> n{self.left_children[n]} "
                             f'[label="<"];')
                lines.append(f"  n{n} -> n{self.right_children[n]} "
                             f'[label=">="];')
            else:
                cov = "" if self.covers[n] is None else \
                    f"\\ncover={self.covers[n]:.6g}"
                lines.append(
                    f'  n{n} [label="{self.predictions[n]:.6g}{cov}", '
                    "style=rounded];")
        lines.append("}")
        return "\n".join(lines)


def tree_from_model(model, tree_number: int = 0,
                    tree_class: Optional[str] = None) -> H2OTree:
    """h2o.tree.H2OTree(model, tree_number, tree_class) analog."""
    trees = model.output["trees"]
    names = [s.name for s in model.datainfo.specs]
    t = trees[tree_number]
    if isinstance(t, (list, tuple)):        # multinomial: one per class
        domain = model.datainfo.response_domain
        k = domain.index(tree_class) if tree_class is not None else 0
        t = t[k]
        tree_class = domain[k]
    elif tree_class is not None:
        raise ValueError("tree_class is only valid for multinomial models")
    return H2OTree(t, names, tree_number=tree_number,
                   tree_class=tree_class)


def feature_interactions(model, max_trees: Optional[int] = None):
    """Split-interaction statistics — the h2o.feature_interaction analog.

    Walks every tree's node structure and aggregates, for single
    features and parent-child feature pairs along root-to-leaf paths,
    the split count and summed cover (weighted rows through the split).
    The reference's XGBoost table also reports per-node gain, which the
    compressed level-wise trees do not retain — counts and covers are
    the retained, exactly-reconstructable statistics.

    Returns {"singles": {feature, count, cover}, "pairs":
    {feature_pair, count, cover}} sorted by count descending.
    """
    from collections import defaultdict
    trees = model.output["trees"]
    names = [s.name for s in model.datainfo.specs]
    first = trees[0]
    probe = first[0] if isinstance(first, (list, tuple)) else first
    if probe.cover is None:
        raise ValueError(
            "model's trees carry no recorded covers; retrain with a "
            "builder that records them (GBM/DRF/XGBoost do)")
    singles = defaultdict(lambda: [0, 0.0])
    pairs = defaultdict(lambda: [0, 0.0])

    def walk(t):
        ht = H2OTree(t, names)

        def visit(nid, parent_feat):
            f = ht.features[nid]
            if f is None:
                return
            cov = float(ht.covers[nid])
            s = singles[f]
            s[0] += 1
            s[1] += cov
            if parent_feat is not None and parent_feat != f:
                key = "|".join(sorted((parent_feat, f)))
                p = pairs[key]
                p[0] += 1
                p[1] += cov
            visit(ht.left_children[nid], f)
            visit(ht.right_children[nid], f)

        visit(0, None)

    flat = []
    for t in trees if max_trees is None else list(trees)[:max_trees]:
        flat.extend(t if isinstance(t, (list, tuple)) else [t])
    for t in flat:
        walk(t)

    def table(d, key_name):
        items = sorted(d.items(), key=lambda kv: -kv[1][0])
        return {key_name: np.asarray([k for k, _ in items], dtype=object),
                "count": np.asarray([v[0] for _, v in items]),
                "cover": np.asarray([v[1] for _, v in items])}
    return {"singles": table(singles, "feature"),
            "pairs": table(pairs, "feature_pair")}

