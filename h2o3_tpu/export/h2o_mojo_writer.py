"""Writer for REAL H2O-3 MOJO archives (GBM / DRF / XGBoost-as-GBM / GLM).

The deployment contract (SURVEY §2.7) is *bidirectional* portability:
``export/h2o_mojo.py`` imports reference-produced MOJOs; this module is the
inverse — models trained here are written in the reference's own zip format
(``hex/ModelMojoWriter.java:1``) so the reference's genmodel (and this repo's
own format reader) can score them.

Format pinning: ``mojo_version = 1.30`` for tree models (the current
SharedTreeMojoModel node-stream layout — nodeType masks, little-endian skip
offsets, bare-float leaf children; ``SharedTreeMojoModel.java:134``) and
``1.00`` for GLM (coefficients inline in model.ini; ``GlmMojoModel.java:26``).
The ini key surface mirrors a reference-produced archive (see the golden
fixtures under ``h2o-genmodel/src/test/resources``).

Semantics notes (documented deltas, all exactness-tested in
``tests/test_h2o_mojo_writer.py``):
 - Tree splits are always numeric threshold splits (``d >= split``) on the
   domain code for categoricals — this framework's trees are ordinal-split
   (hist.py bins cat codes), and the reference walker scores numeric splits
   on categorical columns natively, so scoring is exact.
 - GBM multinomial: the per-class init scores are folded into the first
   tree's leaves of each class (softmax is shift-per-class invariant in the
   folded form: sum_t leaf + init_k is preserved), since the reference
   multinomial path reads no init_f.
 - GLM: this framework learns an explicit ``.missing(NA)`` coefficient per
   categorical; the reference format has no NA bucket, so rows with missing
   categoricals score as "contribute 0" (reference semantics) rather than
   the NA-bucket coefficient.  Rows without missing categoricals are exact.
"""

from __future__ import annotations

import dataclasses
import struct
import zipfile
from typing import List

import numpy as np

_MOJO_TREE_VERSION = "1.30"
_MOJO_GLM_VERSION = "1.00"
_NA_LEFT, _NA_RIGHT = 2, 3


# -------------------------------------------------------------- tree bytecode

def encode_tree(tree, depth: int) -> bytes:
    """Serialize one per-level-array Tree to the reference node stream.

    Inverse of ``h2o_mojo._score_tree``: nodeType byte (left-leaf 0x30 /
    skip-size bits 0..3, right-leaf 0xC0), colId u16, NA-direction byte,
    float32 split, little-endian left-subtree size, then the subtrees
    (leaf children are bare float32 payloads).
    """
    feat = [np.asarray(a) for a in tree.feat]
    thr = [np.asarray(a) for a in tree.thr]
    na_left = [np.asarray(a) for a in tree.na_left]
    valid = [np.asarray(a) for a in tree.valid]
    values = np.asarray(tree.values)

    def is_leaf(d: int, i: int) -> bool:
        return d == depth or not bool(valid[d][i])

    def leaf_value(d: int, i: int) -> bytes:
        # invalid subtrees descend left: leaf index doubles per level
        return struct.pack("<f", float(values[i << (depth - d)]))

    def enc(d: int, i: int) -> bytes:
        lkid, rkid = 2 * i, 2 * i + 1
        lleaf, rleaf = is_leaf(d + 1, lkid), is_leaf(d + 1, rkid)
        left = leaf_value(d + 1, lkid) if lleaf else enc(d + 1, lkid)
        right = leaf_value(d + 1, rkid) if rleaf else enc(d + 1, rkid)
        nt = 0
        if lleaf:
            nt |= 0x30
            offs = b""
        else:
            n = len(left)
            nbytes = 1 if n < 1 << 8 else 2 if n < 1 << 16 else \
                3 if n < 1 << 24 else 4
            nt |= nbytes - 1
            offs = n.to_bytes(nbytes, "little")
        if rleaf:
            nt |= 0xC0
        col = int(feat[d][i])
        head = bytes([nt, col & 0xFF, (col >> 8) & 0xFF,
                      _NA_LEFT if na_left[d][i] else _NA_RIGHT])
        return head + struct.pack("<f", float(thr[d][i])) + offs + left + right

    if is_leaf(0, 0):
        return bytes([0, 0xFF, 0xFF]) + leaf_value(0, 0)
    return enc(0, 0)


# ----------------------------------------------------------------- model.ini

def _format_val(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(
            str(x) if isinstance(x, (int, np.integer)) else repr(float(x))
            for x in v) + "]"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _build_ini(info: dict, columns: List[str], domains: dict) -> str:
    lines = ["[info]"]
    for k, v in info.items():
        lines.append(f"{k} = {_format_val(v)}")
    lines.append("")
    lines.append("[columns]")
    lines.extend(columns)
    lines.append("")
    lines.append("[domains]")
    for k, idx in enumerate(sorted(domains)):
        lines.append(f"{idx}: {len(domains[idx])} d{k:03d}.txt")
    lines.append("")
    return "\n".join(lines)


def _write_archive(path: str, info: dict, columns: List[str],
                   domains: dict, blobs: dict) -> str:
    """domains: {col_index: levels}; blobs: {zip_name: bytes}."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.ini", _build_ini(info, columns, domains))
        for k, idx in enumerate(sorted(domains)):
            for lvl in domains[idx]:
                if "\n" in str(lvl):
                    raise ValueError(
                        f"domain level with newline not exportable: {lvl!r}")
            zf.writestr(f"domains/d{k:03d}.txt",
                        "\n".join(str(x) for x in domains[idx]))
        for name, data in blobs.items():
            zf.writestr(name, data)
    return path


def _common_info(model, algo: str) -> tuple:
    """(info dict, columns, domains) shared by all families."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    specs = list(di.specs)
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    n_features = len(specs)
    nclasses = di.nclasses
    if di.response_column:
        columns.append(di.response_column)
        if di.response_domain:
            domains[n_features] = list(di.response_domain)
    category = ("Binomial" if nclasses == 2 else
                "Multinomial" if nclasses > 2 else "Regression")
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": _MOJO_TREE_VERSION,
        "license": "Apache License Version 2.0",
        "algo": algo,
        "endianness": "LITTLE_ENDIAN",
        "category": category,
        "supervised": True,
        "n_features": n_features,
        "n_classes": max(nclasses, 1),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": float(model.default_threshold())
        if nclasses == 2 else 0.5,
    }
    return info, columns, domains


def _tree_matrix(model) -> List[List]:
    """[group][class] host Tree objects + per-class init folding plan."""
    trees = list(model.output["trees"])
    K = model.output.get("nclass_trees", 1)
    if K > 1:
        return [[t[k] for k in range(K)] for t in trees], K
    return [[t] for t in trees], 1


def write_tree_mojo(model, path: str) -> str:
    """GBM / DRF / XGBoost model -> reference-format shared-tree MOJO zip.

    XGBoost models export with ``algo = gbm`` — this framework's XGBoost is
    the same additive-margin family (sigmoid/identity link over summed
    leaves), which is exactly the reference gbm scoring contract; the
    reference's own xgboost MOJO format is a native-booster dump that does
    not apply here.
    """
    algo = "drf" if model.algo == "drf" else "gbm"
    info, columns, domains = _common_info(model, algo)
    matrix, K = _tree_matrix(model)
    depth = model.params.max_depth
    init = np.atleast_1d(np.asarray(model.output["init_score"],
                                    np.float64)).copy()
    dist = model.output.get("distribution", "gaussian")
    nclasses = info["n_classes"]
    if algo == "gbm":
        if K > 1:
            # fold per-class init into the first round's leaves
            matrix = [list(g) for g in matrix]
            matrix[0] = [
                dataclasses.replace(
                    t, values=np.asarray(t.values, np.float32)
                    + np.float32(init[k]))
                for k, t in enumerate(matrix[0])]
            info["init_f"] = 0.0
            info["distribution"] = "multinomial"
        else:
            info["init_f"] = float(init[0])
            info["distribution"] = ("bernoulli" if nclasses == 2 and
                                    dist not in ("quasibinomial",)
                                    else dist)
        info["link_function"] = {
            "bernoulli": "logit", "quasibinomial": "logit",
            "poisson": "log", "gamma": "log", "tweedie": "log",
        }.get(info["distribution"], "identity")
    else:
        info["init_f"] = 0.0
        info["distribution"] = dist
        info["link_function"] = "identity"
        if nclasses == 2:
            info["binomial_double_trees"] = False
            # reference binomial DRF trees vote for CLASS 0
            # (DrfMojoModel.unifyPreds: p0 = sum/T) — this framework's
            # DRF leaves carry class-1 fractions, so flip on export
            matrix = [[dataclasses.replace(
                t, values=np.float32(1.0)
                - np.asarray(t.values, np.float32))
                for t in per_class] for per_class in matrix]
    info["n_trees"] = len(matrix)
    info["n_trees_per_class"] = K
    blobs = {}
    for group, per_class in enumerate(matrix):
        for cls, tree in enumerate(per_class):
            blobs[f"trees/t{cls:02d}_{group:03d}.bin"] = \
                encode_tree(tree, depth)
    return _write_archive(path, info, columns, domains, blobs)


def write_glm_mojo(model, path: str) -> str:
    """GLM model -> reference-format GLM MOJO (coefficients in model.ini).

    Columns are emitted categoricals-first (the reference GLM layout,
    ``GlmMojoModel.java:26``); the learned per-cat NA-bucket coefficient has
    no reference representation and is dropped (see module docstring).
    """
    from ..frame.vec import T_CAT
    di = model.datainfo
    fam = model.output["family"]
    if fam == "multinomial":
        raise ValueError("reference GLM MOJO format is binomial/regression "
                         "only (GlmMojoModel.score0)")
    cat_specs = [s for s in di.specs if s.type == T_CAT]
    num_specs = [s for s in di.specs if s.type != T_CAT]
    beta = np.asarray(model.output["beta"], np.float64)

    # per-spec slices of this framework's interleaved layout
    h2o_beta: List[float] = []
    cat_offsets = [0]
    for s in cat_specs:
        h2o_beta.extend(beta[s.offset: s.offset + s.width - 1])  # drop NA
        cat_offsets.append(len(h2o_beta))
    for s in num_specs:
        h2o_beta.append(float(beta[s.offset]))
    h2o_beta.append(float(beta[-1]) if di.add_intercept else 0.0)

    specs = cat_specs + num_specs
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    if di.response_column:
        columns.append(di.response_column)
        if di.response_domain:
            domains[len(specs)] = list(di.response_domain)
    nclasses = di.nclasses
    link = {"binomial": "logit", "quasibinomial": "logit",
            "fractionalbinomial": "logit", "poisson": "log",
            "gamma": "log", "tweedie": "log",
            "negativebinomial": "log"}.get(fam, "identity")
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": _MOJO_GLM_VERSION,
        "license": "Apache License Version 2.0",
        "algo": "glm",
        "endianness": "LITTLE_ENDIAN",
        "category": "Binomial" if nclasses == 2 else "Regression",
        "supervised": True,
        "n_features": len(specs),
        "n_classes": max(nclasses, 1),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": float(model.default_threshold())
        if nclasses == 2 else 0.5,
        "family": "binomial" if fam in ("binomial", "quasibinomial",
                                        "fractionalbinomial") else fam,
        "link": link,
        "beta": h2o_beta,
        "cats": len(cat_specs),
        "cat_offsets": [int(x) for x in cat_offsets],
        "nums": len(num_specs),
        "use_all_factor_levels": bool(di.use_all_factor_levels),
        "mean_imputation": True,
        "num_means": [float(s.mean) for s in num_specs],
        "cat_modes": [-1.0] * len(cat_specs),
    }
    return _write_archive(path, info, columns, domains, {})


def write_h2o_mojo(model, path: str) -> str:
    """Dispatch: model trained here -> reference-format MOJO archive."""
    if model.algo in ("gbm", "drf", "xgboost"):
        return write_tree_mojo(model, path)
    if model.algo == "glm":
        return write_glm_mojo(model, path)
    raise ValueError(
        f"no reference MOJO format writer for algo {model.algo!r} "
        "(gbm, drf, xgboost, glm are supported)")
