"""Writer for REAL H2O-3 MOJO archives (GBM / DRF / XGBoost-as-GBM / GLM).

The deployment contract (SURVEY §2.7) is *bidirectional* portability:
``export/h2o_mojo.py`` imports reference-produced MOJOs; this module is the
inverse — models trained here are written in the reference's own zip format
(``hex/ModelMojoWriter.java:1``) so the reference's genmodel (and this repo's
own format reader) can score them.

Format pinning: ``mojo_version = 1.30`` for tree models (the current
SharedTreeMojoModel node-stream layout — nodeType masks, little-endian skip
offsets, bare-float leaf children; ``SharedTreeMojoModel.java:134``) and
``1.00`` for GLM (coefficients inline in model.ini; ``GlmMojoModel.java:26``).
The ini key surface mirrors a reference-produced archive (see the golden
fixtures under ``h2o-genmodel/src/test/resources``).

Semantics notes (documented deltas, all exactness-tested in
``tests/test_h2o_mojo_writer.py``):
 - Tree splits are always numeric threshold splits (``d >= split``) on the
   domain code for categoricals — this framework's trees are ordinal-split
   (hist.py bins cat codes), and the reference walker scores numeric splits
   on categorical columns natively, so scoring is exact.
 - GBM multinomial: the per-class init scores are folded into the first
   tree's leaves of each class (softmax is shift-per-class invariant in the
   folded form: sum_t leaf + init_k is preserved), since the reference
   multinomial path reads no init_f.
 - GLM: this framework learns an explicit ``.missing(NA)`` coefficient per
   categorical; the reference format has no NA bucket, so rows with missing
   categoricals score as "contribute 0" (reference semantics) rather than
   the NA-bucket coefficient.  Rows without missing categoricals are exact.
"""

from __future__ import annotations

import dataclasses
import struct
import zipfile
from typing import List

import numpy as np

_MOJO_TREE_VERSION = "1.30"
_MOJO_GLM_VERSION = "1.00"
_NA_LEFT, _NA_RIGHT = 2, 3


# -------------------------------------------------------------- tree bytecode

def encode_tree(tree, depth: int) -> bytes:
    """Serialize one per-level-array Tree to the reference node stream.

    Inverse of ``h2o_mojo._score_tree``: nodeType byte (left-leaf 0x30 /
    skip-size bits 0..3, right-leaf 0xC0), colId u16, NA-direction byte,
    float32 split, little-endian left-subtree size, then the subtrees
    (leaf children are bare float32 payloads).
    """
    feat = [np.asarray(a) for a in tree.feat]
    thr = [np.asarray(a) for a in tree.thr]
    na_left = [np.asarray(a) for a in tree.na_left]
    valid = [np.asarray(a) for a in tree.valid]
    values = np.asarray(tree.values)

    def is_leaf(d: int, i: int) -> bool:
        return d == depth or not bool(valid[d][i])

    def leaf_value(d: int, i: int) -> bytes:
        # invalid subtrees descend left: leaf index doubles per level
        return struct.pack("<f", float(values[i << (depth - d)]))

    def enc(d: int, i: int) -> bytes:
        lkid, rkid = 2 * i, 2 * i + 1
        lleaf, rleaf = is_leaf(d + 1, lkid), is_leaf(d + 1, rkid)
        left = leaf_value(d + 1, lkid) if lleaf else enc(d + 1, lkid)
        right = leaf_value(d + 1, rkid) if rleaf else enc(d + 1, rkid)
        nt = 0
        if lleaf:
            nt |= 0x30
            offs = b""
        else:
            n = len(left)
            nbytes = 1 if n < 1 << 8 else 2 if n < 1 << 16 else \
                3 if n < 1 << 24 else 4
            nt |= nbytes - 1
            offs = n.to_bytes(nbytes, "little")
        if rleaf:
            nt |= 0xC0
        col = int(feat[d][i])
        head = bytes([nt, col & 0xFF, (col >> 8) & 0xFF,
                      _NA_LEFT if na_left[d][i] else _NA_RIGHT])
        return head + struct.pack("<f", float(thr[d][i])) + offs + left + right

    if is_leaf(0, 0):
        return bytes([0, 0xFF, 0xFF]) + leaf_value(0, 0)
    return enc(0, 0)


# ----------------------------------------------------------------- model.ini

def _format_val(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(
            str(x) if isinstance(x, (int, np.integer)) else repr(float(x))
            for x in v) + "]"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _build_ini(info: dict, columns: List[str], domains: dict) -> str:
    lines = ["[info]"]
    for k, v in info.items():
        lines.append(f"{k} = {_format_val(v)}")
    lines.append("")
    lines.append("[columns]")
    lines.extend(columns)
    lines.append("")
    lines.append("[domains]")
    for k, idx in enumerate(sorted(domains)):
        lines.append(f"{idx}: {len(domains[idx])} d{k:03d}.txt")
    lines.append("")
    return "\n".join(lines)


def _write_entries(zf: zipfile.ZipFile, info: dict, columns: List[str],
                   domains: dict, blobs: dict, prefix: str = "") -> None:
    """Write one logical MOJO archive into ``zf`` under ``prefix``
    (nested archives — StackedEnsemble submodels — use a dir prefix the
    reader's _PrefixBackend mirrors)."""
    zf.writestr(prefix + "model.ini", _build_ini(info, columns, domains))
    for k, idx in enumerate(sorted(domains)):
        for lvl in domains[idx]:
            if "\n" in str(lvl):
                raise ValueError(
                    f"domain level with newline not exportable: {lvl!r}")
        zf.writestr(prefix + f"domains/d{k:03d}.txt",
                    "\n".join(str(x) for x in domains[idx]))
    for name, data in blobs.items():
        zf.writestr(prefix + name, data)


def _write_archive(path: str, info: dict, columns: List[str],
                   domains: dict, blobs: dict) -> str:
    """domains: {col_index: levels}; blobs: {zip_name: bytes}."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        _write_entries(zf, info, columns, domains, blobs)
    return path


def _common_info(model, algo: str) -> tuple:
    """(info dict, columns, domains) shared by all families."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    specs = list(di.specs)
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    n_features = len(specs)
    nclasses = di.nclasses
    if di.response_column:
        columns.append(di.response_column)
        if di.response_domain:
            domains[n_features] = list(di.response_domain)
    category = ("Binomial" if nclasses == 2 else
                "Multinomial" if nclasses > 2 else "Regression")
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": _MOJO_TREE_VERSION,
        "license": "Apache License Version 2.0",
        "algo": algo,
        "endianness": "LITTLE_ENDIAN",
        "category": category,
        "supervised": True,
        "n_features": n_features,
        "n_classes": max(nclasses, 1),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": float(model.default_threshold())
        if nclasses == 2 else 0.5,
    }
    return info, columns, domains


def _tree_matrix(model) -> List[List]:
    """[group][class] host Tree objects + per-class init folding plan."""
    trees = list(model.output["trees"])
    K = model.output.get("nclass_trees", 1)
    if K > 1:
        return [[t[k] for k in range(K)] for t in trees], K
    return [[t] for t in trees], 1


def write_tree_mojo(model, path: str) -> str:
    """GBM / DRF / XGBoost model -> reference-format shared-tree MOJO zip."""
    return _write_archive(path, *_tree_entries(model))


def _tree_entries(model):
    """GBM / DRF / XGBoost -> (info, columns, domains, blobs).

    XGBoost models export with ``algo = gbm`` — this framework's XGBoost is
    the same additive-margin family (sigmoid/identity link over summed
    leaves), which is exactly the reference gbm scoring contract; the
    reference's own xgboost MOJO format is a native-booster dump that does
    not apply here.
    """
    algo = "drf" if model.algo == "drf" else "gbm"
    info, columns, domains = _common_info(model, algo)
    matrix, K = _tree_matrix(model)
    depth = model.params.max_depth
    init = np.atleast_1d(np.asarray(model.output["init_score"],
                                    np.float64)).copy()
    dist = model.output.get("distribution", "gaussian")
    nclasses = info["n_classes"]
    if algo == "gbm":
        if K > 1:
            # fold per-class init into the first round's leaves
            matrix = [list(g) for g in matrix]
            matrix[0] = [
                dataclasses.replace(
                    t, values=np.asarray(t.values, np.float32)
                    + np.float32(init[k]))
                for k, t in enumerate(matrix[0])]
            info["init_f"] = 0.0
            info["distribution"] = "multinomial"
        else:
            info["init_f"] = float(init[0])
            info["distribution"] = ("bernoulli" if nclasses == 2 and
                                    dist not in ("quasibinomial",)
                                    else dist)
        info["link_function"] = {
            "bernoulli": "logit", "quasibinomial": "logit",
            "poisson": "log", "gamma": "log", "tweedie": "log",
        }.get(info["distribution"], "identity")
    else:
        info["init_f"] = 0.0
        info["distribution"] = dist
        info["link_function"] = "identity"
        if nclasses == 2:
            info["binomial_double_trees"] = False
            # reference binomial DRF trees vote for CLASS 0
            # (DrfMojoModel.unifyPreds: p0 = sum/T) — this framework's
            # DRF leaves carry class-1 fractions, so flip on export
            matrix = [[dataclasses.replace(
                t, values=np.float32(1.0)
                - np.asarray(t.values, np.float32))
                for t in per_class] for per_class in matrix]
    info["n_trees"] = len(matrix)
    info["n_trees_per_class"] = K
    blobs = {}
    for group, per_class in enumerate(matrix):
        for cls, tree in enumerate(per_class):
            blobs[f"trees/t{cls:02d}_{group:03d}.bin"] = \
                encode_tree(tree, depth)
    return info, columns, domains, blobs


def write_glm_mojo(model, path: str) -> str:
    """GLM model -> reference-format GLM MOJO (coefficients in model.ini)."""
    return _write_archive(path, *_glm_entries(model))


def _glm_entries(model):
    """GLM -> (info, columns, domains, blobs).

    Columns are emitted categoricals-first (the reference GLM layout,
    ``GlmMojoModel.java:26``); the learned per-cat NA-bucket coefficient has
    no reference representation and is dropped (see module docstring).
    """
    from ..frame.vec import T_CAT
    di = model.datainfo
    fam = model.output["family"]
    if fam == "multinomial":
        raise ValueError("reference GLM MOJO format is binomial/regression "
                         "only (GlmMojoModel.score0)")
    cat_specs = [s for s in di.specs if s.type == T_CAT]
    num_specs = [s for s in di.specs if s.type != T_CAT]
    beta = np.asarray(model.output["beta"], np.float64)

    # per-spec slices of this framework's interleaved layout
    h2o_beta: List[float] = []
    cat_offsets = [0]
    for s in cat_specs:
        h2o_beta.extend(beta[s.offset: s.offset + s.width - 1])  # drop NA
        cat_offsets.append(len(h2o_beta))
    for s in num_specs:
        h2o_beta.append(float(beta[s.offset]))
    h2o_beta.append(float(beta[-1]) if di.add_intercept else 0.0)

    specs = cat_specs + num_specs
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    if di.response_column:
        columns.append(di.response_column)
        if di.response_domain:
            domains[len(specs)] = list(di.response_domain)
    nclasses = di.nclasses
    link = {"binomial": "logit", "quasibinomial": "logit",
            "fractionalbinomial": "logit", "poisson": "log",
            "gamma": "log", "tweedie": "log",
            "negativebinomial": "log"}.get(fam, "identity")
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": _MOJO_GLM_VERSION,
        "license": "Apache License Version 2.0",
        "algo": "glm",
        "endianness": "LITTLE_ENDIAN",
        "category": "Binomial" if nclasses == 2 else "Regression",
        "supervised": True,
        "n_features": len(specs),
        "n_classes": max(nclasses, 1),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": float(model.default_threshold())
        if nclasses == 2 else 0.5,
        "family": "binomial" if fam in ("binomial", "quasibinomial",
                                        "fractionalbinomial") else fam,
        "link": link,
        "beta": h2o_beta,
        "cats": len(cat_specs),
        "cat_offsets": [int(x) for x in cat_offsets],
        "nums": len(num_specs),
        "use_all_factor_levels": bool(di.use_all_factor_levels),
        "mean_imputation": True,
        "num_means": [float(s.mean) for s in num_specs],
        "cat_modes": [-1.0] * len(cat_specs),
    }
    return info, columns, domains, {}


# ------------------------------------------------------------- more algos

def _unsup_info(model, algo: str, version: str) -> tuple:
    """(info, columns, domains) for unsupervised families (no response)."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    specs = list(di.specs)
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": version,
        "license": "Apache License Version 2.0",
        "algo": algo,
        "endianness": "LITTLE_ENDIAN",
        "category": "Unknown",
        "supervised": False,
        "n_features": len(specs),
        "n_classes": 1,
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": 0.5,
    }
    return info, columns, domains


def _kmeans_entries(model):
    """KMeans -> reference format (KMeansMojoReader: center_num,
    center_i rows in STANDARDIZED space, standardize means/mults)."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    if any(s.type == T_CAT for s in di.specs):
        raise ValueError(
            "reference KMeans MOJO export supports numeric columns only "
            "(this framework clusters one-hot cats; the reference format "
            "stores per-column cat modes)")
    info, columns, domains = _unsup_info(model, "kmeans", "1.00")
    centers_std = np.asarray(model.output["centers_std"], np.float64)
    info["center_num"] = len(centers_std)
    for i, row in enumerate(centers_std):
        info[f"center_{i}"] = [float(x) for x in row]
    info["standardize"] = bool(di.standardize)
    if di.standardize:
        info["standardize_means"] = [float(s.mean) for s in di.specs]
        info["standardize_mults"] = [
            1.0 / float(s.sigma) if s.sigma else 1.0 for s in di.specs]
        info["standardize_modes"] = [-1] * len(di.specs)
    return info, columns, domains, {}


def _isofor_entries(model):
    """IsolationForest -> reference format (IsolationForestMojoModel:
    summed per-tree path lengths normalized by min/max path length).

    The reference records min/max path length over TRAINING scores; here
    they are the trees' structural bounds (sum of each tree's min/max
    leaf), a documented delta — per-row path lengths are exact either
    way, only the affine normalization differs.
    """
    info, columns, domains = _unsup_info(model, "isolationforest",
                                         _MOJO_TREE_VERSION)
    trees = list(model.output["trees"])
    depth = model.params.max_depth
    lo = sum(float(np.min(np.asarray(t.values))) for t in trees)
    hi = sum(float(np.max(np.asarray(t.values))) for t in trees)
    info.update({
        "n_trees": len(trees), "n_trees_per_class": 1,
        "min_path_length": lo, "max_path_length": hi,
        "distribution": "gaussian", "link_function": "identity",
        "init_f": 0.0,
    })
    blobs = {f"trees/t00_{g:03d}.bin": encode_tree(t, depth)
             for g, t in enumerate(trees)}
    return info, columns, domains, blobs


def _word2vec_entries(model):
    """Word2Vec -> reference format (Word2VecMojoReader: vocabulary text
    + BIG-endian float32 vectors — Java ByteBuffer default order)."""
    E = np.asarray(model.output["embeddings"], np.float32)
    words = list(model.output["words"])
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": "1.00",
        "license": "Apache License Version 2.0",
        "algo": "word2vec",
        "endianness": "LITTLE_ENDIAN",
        "category": "Unknown",
        "supervised": False,
        "n_features": 1,
        "n_classes": 1,
        "n_columns": 1,
        "n_domains": 0,
        "balance_classes": False,
        "default_threshold": 0.5,
        "vec_size": int(E.shape[1]),
        "vocab_size": len(words),
    }
    vocab_txt = "\n".join(str(w).replace("\n", "\\n") for w in words)
    blobs = {"vocabulary": vocab_txt.encode(),
             "vectors": E[: len(words)].astype(">f4").tobytes()}
    return info, ["word"], {}, blobs


def _deeplearning_entries(model):
    """DeepLearning MLP -> reference format (DeeplearningMojoReader:
    everything in model.ini — neural_network_sizes, norm stats, per-layer
    ``weight_layerK``/``bias_layerK`` flattened [out, in]-major).

    The framework's design layout interleaves each categorical's one-hot
    block (with a trailing NA bucket) at its column position; the
    reference expects cats-first one-hot (no NA bucket) then numerics, so
    input-layer weight rows are permuted and NA-bucket rows dropped
    (exact for rows without missing categoricals, the GLM-writer rule).
    """
    from ..frame.vec import T_CAT
    di = model.datainfo
    p = model.params
    if p.autoencoder:
        raise ValueError("reference DL MOJO export: autoencoder scoring "
                         "is unsupported by genmodel itself")
    cat_specs = [s for s in di.specs if s.type == T_CAT]
    num_specs = [s for s in di.specs if s.type != T_CAT]
    # input permutation: reference order = cats' one-hot then nums
    perm = []
    for s in cat_specs:
        perm.extend(range(s.offset, s.offset + s.width - 1))  # drop NA
    for s in num_specs:
        perm.append(s.offset)
    weights = [(np.asarray(W, np.float64), np.asarray(b, np.float64))
               for W, b in model.output["weights"]]
    W0, b0 = weights[0]
    if di.add_intercept:
        # the design matrix carries a constant-1 intercept column (last
        # row of W0) with no MOJO representation — fold it into the bias
        b0 = b0 + W0[-1, :]
    W0 = W0[perm, :]
    weights[0] = (W0, b0)

    specs = cat_specs + num_specs
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    nclasses = di.nclasses
    if di.response_column:
        columns.append(di.response_column)
        if di.response_domain:
            domains[len(specs)] = list(di.response_domain)
    cat_offsets = [0]
    for s in cat_specs:
        cat_offsets.append(cat_offsets[-1] + s.width - 1)
    units = [len(perm)] + [W.shape[1] for W, _ in weights]
    act = {"rectifier": "Rectifier", "tanh": "Tanh", "maxout": "Maxout",
           "rectifier_with_dropout": "RectifierWithDropout",
           "tanh_with_dropout": "TanhWithDropout",
           "maxout_with_dropout": "MaxoutWithDropout"}[p.activation]
    dist = ("bernoulli" if nclasses == 2 else
            "multinomial" if nclasses > 2 else "gaussian")
    info = {
        "h2o_version": "3.46.0.1",
        "mojo_version": "1.10",
        "license": "Apache License Version 2.0",
        "algo": "deeplearning",
        "endianness": "LITTLE_ENDIAN",
        "category": ("Binomial" if nclasses == 2 else
                     "Multinomial" if nclasses > 2 else "Regression"),
        "supervised": True,
        "n_features": len(specs),
        "n_classes": max(nclasses, 1),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "balance_classes": False,
        "default_threshold": float(model.default_threshold())
        if nclasses == 2 else 0.5,
        "mini_batch_size": int(p.mini_batch_size),
        "nums": len(num_specs),
        "cats": len(cat_specs),
        "cat_offsets": [int(x) for x in cat_offsets],
        "use_all_factor_levels": bool(di.use_all_factor_levels),
        "activation": act,
        "mean_imputation": True,
        "cat_modes": [0] * len(cat_specs),
        "distribution": dist,
        "neural_network_sizes": [int(u) for u in units],
        "hidden_dropout_ratios": [float(x) for x in
                                  (p.hidden_dropout_ratios or [])],
        "_genmodel_encoding": "AUTO",
    }
    if di.standardize:
        info["norm_sub"] = [float(s.mean) for s in num_specs]
        info["norm_mul"] = [1.0 / float(s.sigma) if s.sigma else 1.0
                            for s in num_specs]
        if nclasses <= 1 and di.response_sigma:
            info["norm_resp_sub"] = float(di.response_mean)
            info["norm_resp_mul"] = 1.0 / float(di.response_sigma)
    for k, (W, b) in enumerate(weights):
        info[f"weight_layer{k}"] = [float(x) for x in W.T.ravel()]
        info[f"bias_layer{k}"] = [float(x) for x in b]
    return info, columns, domains, {}


def _pca_entries(model):
    """PCA -> reference format (PCAMojoReader: eigenvectors_raw blob of
    big-endian doubles, [eigenvector_size, k])."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    if any(s.type == T_CAT for s in di.specs):
        raise ValueError("reference PCA MOJO export supports numeric "
                         "columns only in this framework")
    info, columns, domains = _unsup_info(model, "pca", "1.00")
    V = np.asarray(model.output["eigenvectors"], np.float64)  # [P, k]
    mu = np.asarray(model.output["_mu"], np.float64)
    sd = np.asarray(model.output["_sd"], np.float64)  # multiplier form
    info.update({
        "use_all_factor_levels": bool(di.use_all_factor_levels),
        "pca_methods": str(model.params.pca_method),
        "pca_impl": "mtj_svd_densematrix",
        "k": int(V.shape[1]),
        "permutation": list(range(len(di.specs))),
        "ncats": 0,
        "nnums": len(di.specs),
        "normSub": [float(x) for x in mu],
        "normMul": [float(x) for x in sd],
        "catOffsets": [0],
        "eigenvector_size": int(V.shape[0]),
    })
    blobs = {"eigenvectors_raw": V.astype(">f8").tobytes()}
    return info, columns, domains, blobs


def _coxph_entries(model):
    """CoxPH -> reference format (CoxPHMojoReader: raw-space coef +
    per-column means; lp = coef . (x - mean), matching this framework's
    standardized ``X_std @ beta_std``)."""
    from ..frame.vec import T_CAT
    di = model.datainfo
    cat_specs = [s for s in di.specs if s.type == T_CAT]
    num_specs = [s for s in di.specs if s.type != T_CAT]
    beta = np.asarray(model.output["beta_std"], np.float64)
    coef, means_num, means_cat = [], [], []
    cat_offsets = [0]
    for s in cat_specs:
        for k in range(s.width - 1):
            coef.append(float(beta[s.offset + k]))
        means_cat.append([0.0] * (s.width - 1))
        cat_offsets.append(cat_offsets[-1] + s.width - 1)
    num_offsets = []
    for s in num_specs:
        num_offsets.append(len(coef))
        sig = float(s.sigma) if di.standardize and s.sigma else 1.0
        coef.append(float(beta[s.offset]) / sig)
        means_num.append([float(s.mean) if di.standardize else 0.0])
    specs = cat_specs + num_specs
    columns = [s.name for s in specs]
    domains = {j: list(s.domain) for j, s in enumerate(specs)
               if s.type == T_CAT and s.domain}
    n_cat_coef = sum(len(r) for r in means_cat)
    num_means_flat = [r[0] for r in means_num]
    info, _, _ = _unsup_info(model, "coxph", "1.00")
    info.update({
        "n_features": len(specs),
        "n_columns": len(columns),
        "n_domains": len(domains),
        "coef": coef,
        "cats": len(cat_specs),
        "cat_offsets": [int(x) for x in cat_offsets],
        "num_numerical_columns": len(num_specs),
        "num_offsets": [int(x) for x in num_offsets],
        "use_all_factor_levels": bool(di.use_all_factor_levels),
        "strata_count": 0,
        # rectangular-array convention (ModelMojoReader:232): _size1/_size2
        # ini keys + a big-endian double blob, [1 strata row x coefs]
        "x_mean_cat_size1": 1, "x_mean_cat_size2": n_cat_coef,
        "x_mean_num_size1": 1, "x_mean_num_size2": len(num_means_flat),
    })
    blobs = {
        "x_mean_cat": np.asarray([0.0] * n_cat_coef,
                                 np.float64).astype(">f8").tobytes(),
        "x_mean_num": np.asarray(num_means_flat,
                                 np.float64).astype(">f8").tobytes(),
    }
    return info, columns, domains, blobs


_ENTRY_BUILDERS = {
    "gbm": _tree_entries, "drf": _tree_entries, "xgboost": _tree_entries,
    "glm": _glm_entries, "kmeans": _kmeans_entries,
    "isolationforest": _isofor_entries, "isofor": _isofor_entries,
    "word2vec": _word2vec_entries, "deeplearning": _deeplearning_entries,
    "pca": _pca_entries, "coxph": _coxph_entries,
}


def write_ensemble_mojo(model, path: str) -> str:
    """StackedEnsemble -> reference format: nested base-model archives
    under ``models/<dir>/`` + metalearner, keyed exactly as
    StackedEnsembleMojoReader expects (submodel_count/submodel_key_i/
    submodel_dir_i/base_model{i}/metalearner)."""
    from ..runtime import dkv
    base_keys = list(model.output["base_model_keys"])
    meta_key = model.output["metalearner_key"]
    subs = []
    for key in base_keys + [meta_key]:
        m = dkv.get(key)
        if m is None:
            raise ValueError(f"base model {key!r} not in DKV")
        # any algo with a reference-format writer may appear as a base
        # model (KMeans/PCA/CoxPH included — their readers contribute a
        # single level-one column exactly as training did)
        builder = _ENTRY_BUILDERS.get(m.algo)
        if builder is None:
            raise ValueError(
                f"StackedEnsemble MOJO export: base model algo {m.algo!r} "
                "has no reference-format writer "
                f"(supported: {sorted(set(_ENTRY_BUILDERS))})")
        subs.append((key, m, builder))
    di = model.datainfo
    info, columns, domains = _common_info(model, "stackedensemble")
    info["mojo_version"] = "1.00"
    info["submodel_count"] = len(subs)
    info["base_models_num"] = len(base_keys)
    info["metalearner"] = meta_key
    info["metalearner_transform"] = "NONE"
    del di
    blobs: dict = {}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        for i, (key, m, builder) in enumerate(subs):
            prefix = f"models/m{i}/"
            info[f"submodel_key_{i}"] = key
            info[f"submodel_dir_{i}"] = prefix
            if i < len(base_keys):
                info[f"base_model{i}"] = key
            _write_entries(zf, *builder(m), prefix=prefix)
        _write_entries(zf, info, columns, domains, blobs)
    return path


def write_h2o_mojo(model, path: str) -> str:
    """Dispatch: model trained here -> reference-format MOJO archive."""
    if model.algo == "stackedensemble":
        return write_ensemble_mojo(model, path)
    builder = _ENTRY_BUILDERS.get(model.algo)
    if builder is None:
        raise ValueError(
            f"no reference MOJO format writer for algo {model.algo!r} "
            f"(supported: {sorted(set(_ENTRY_BUILDERS))} + "
            "stackedensemble)")
    return _write_archive(path, *builder(model))
