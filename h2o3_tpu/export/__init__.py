"""Portable model artifacts + standalone scoring (h2o-genmodel analog)."""

from .mojo import export_mojo, import_mojo
from .scoring import ScoringModel
from .tree_api import H2OTree, tree_from_model, feature_interactions
