"""Portable model artifacts + standalone scoring (h2o-genmodel analog)."""

from .mojo import export_mojo, import_mojo
from .scoring import ScoringModel
