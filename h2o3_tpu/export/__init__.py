"""Portable model artifacts + standalone scoring (h2o-genmodel analog)."""

from .mojo import export_mojo, import_mojo
from .scoring import ScoringModel
from .tree_api import H2OTree, tree_from_model, feature_interactions
from .h2o_mojo import load_h2o_mojo
from .h2o_mojo_writer import write_h2o_mojo
from .pojo import export_pojo, export_pojo_c
from .pipeline import export_pipeline, load_pipeline
