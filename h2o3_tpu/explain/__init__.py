"""Model explainability — the h2o-py ``h2o/explain`` analog, data-first.

Reference: ``h2o-py/h2o/explain/_explain.py`` builds matplotlib figures
for PDP/ICE/SHAP-summary/varimp/residuals; here every function returns
the underlying TABLES (plain dicts of numpy arrays) so they work
headless and feed any plotting layer.  The compute path batches each
grid column onto the device through the model's normal scoring stack.

Entry points:
- ``partial_dependence(model, frame, column, nbins)`` — PDP table
  (grid value, mean response, stddev, std error), cats use the domain.
- ``ice(model, frame, column, nbins, sample_rows)`` — per-row ICE
  curves over the same grid.
- ``shap_summary(model, frame, top_n)`` — mean |contribution| ranking
  from TreeSHAP (tree models only).
- ``residual_analysis(model, frame)`` — residuals + summary stats
  (regression).
- ``explain(model, frame)`` — the bundle: varimp, PDPs for the top
  features, SHAP summary and residuals where applicable.
- ``learning_curve(model)`` — scoring-history series.
- ``varimp_heatmap(models)`` — feature x model importance matrix.
- ``model_correlation(models, frame)`` — prediction agreement matrix
  (label-agreement fraction for classifiers, Pearson for regression).
- ``explain_models(models, frame)`` — the multi-model bundle (AutoML
  leaderboards): heatmap + agreement + the leader's explain().
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, T_NUM, Vec

__all__ = ["partial_dependence", "ice", "shap_summary",
           "residual_analysis", "explain", "learning_curve",
           "varimp_heatmap", "model_correlation", "explain_models",
           "permutation_importance", "partial_dependence_2d",
           "partial_dependence_multi"]


def _response_col(model, preds: Frame,
                  target_class: Optional[str] = None) -> np.ndarray:
    """The scalar response curve: predicted value for regression,
    P(target_class) for classification.  Binomial defaults the target
    to the positive (last) class; multinomial defaults to the FIRST
    class — pass ``target_class`` to pick the class of interest (the
    reference's pd_plot requires it for multiclass)."""
    di = model.datainfo
    domain = getattr(di, "response_domain", None)
    if not domain:
        return preds.vec("predict").to_numpy()
    if target_class is None:
        target_class = domain[-1] if len(domain) == 2 else domain[0]
    if target_class not in domain:
        raise ValueError(f"target_class {target_class!r} not in response "
                         f"domain {domain}")
    return preds.vec(target_class).to_numpy()


def _grid_for(vec: Vec, nbins: int) -> List:
    if vec.type == T_CAT:
        return list(range(len(vec.domain)))
    x = vec.to_numpy()
    x = x[np.isfinite(x)]
    if len(x) == 0:
        return [0.0]
    # equally spaced over the observed range, like pd_plot's default
    return list(np.linspace(float(x.min()), float(x.max()),
                            min(nbins, max(len(np.unique(x)), 2))))


def _with_constant(frame: Frame, column: str, value, vec: Vec) -> Frame:
    n = frame.nrows
    if vec.type == T_CAT:
        arr = np.full(n, int(value), dtype=np.int32)
        newv = Vec.from_numpy(arr, T_CAT, domain=vec.domain)
    else:
        newv = Vec.from_numpy(np.full(n, float(value)), T_NUM)
    return frame.with_vec(column, newv)


def partial_dependence(model, frame: Frame, column: str,
                       nbins: int = 20,
                       target_class: Optional[str] = None,
                       ) -> Dict[str, np.ndarray]:
    """One-column PDP — h2o.pd_plot / PartialDependence.java analog.

    For each grid value g: score the frame with ``column`` forced to g
    and average the response.  Returns arrays keyed grid/value labels,
    mean_response, stddev_response, std_error_mean_response.
    """
    vec = frame.vec(column)
    grid = _grid_for(vec, nbins)
    means, sds, ses = [], [], []
    for g in grid:
        r = _response_col(model, model.predict(
            _with_constant(frame, column, g, vec)), target_class)
        means.append(float(np.mean(r)))
        sds.append(float(np.std(r, ddof=1)) if len(r) > 1 else 0.0)
        ses.append(sds[-1] / np.sqrt(len(r)) if len(r) > 1 else 0.0)
    labels = ([vec.domain[int(g)] for g in grid]
              if vec.type == T_CAT else grid)
    return {"column": column, "grid": np.asarray(labels, dtype=object),
            "mean_response": np.asarray(means),
            "stddev_response": np.asarray(sds),
            "std_error_mean_response": np.asarray(ses)}


def ice(model, frame: Frame, column: str, nbins: int = 20,
        sample_rows: int = 50, seed: int = 0,
        target_class: Optional[str] = None,
        centered: bool = False) -> Dict[str, np.ndarray]:
    """Individual Conditional Expectation curves (h2o.ice_plot analog):
    the PDP decomposed per row, on a row subsample.  The grid comes from
    the FULL column distribution; only the sampled rows are scored."""
    vec = frame.vec(column)
    grid = _grid_for(vec, nbins)
    rng = np.random.default_rng(seed)
    rows = (np.sort(rng.choice(frame.nrows, sample_rows, replace=False))
            if frame.nrows > sample_rows else np.arange(frame.nrows))
    sub = frame.rows(rows) if len(rows) < frame.nrows else frame
    subvec = sub.vec(column)
    curves = np.empty((len(rows), len(grid)))
    for j, g in enumerate(grid):
        curves[:, j] = _response_col(model, model.predict(
            _with_constant(sub, column, g, subvec)), target_class)
    if centered:
        # h2o ice_plot centered=True: subtract each curve's first value
        curves = curves - curves[:, :1]
    labels = ([vec.domain[int(g)] for g in grid]
              if vec.type == T_CAT else grid)
    return {"column": column, "grid": np.asarray(labels, dtype=object),
            "rows": rows, "curves": curves,
            "pdp": curves.mean(axis=0)}


def shap_summary(model, frame: Frame, top_n: int = 20) -> Dict[str, np.ndarray]:
    """Mean |TreeSHAP| ranking — shap_summary_plot's table."""
    contribs = model.predict_contributions(frame)
    feats = [c for c in contribs.names if c != "BiasTerm"]
    M = contribs[feats].to_numpy()          # one host transfer
    mean_abs = np.abs(M).mean(axis=0)
    order = np.argsort(-mean_abs)[:top_n]
    return {"feature": np.asarray([feats[i] for i in order], dtype=object),
            "mean_abs_contribution": mean_abs[order]}


def residual_analysis(model, frame: Frame) -> Dict[str, np.ndarray]:
    """Residuals vs fitted (regression) — residual_analysis_plot's data."""
    y = frame.vec(model.params.response_column).to_numpy()
    fitted = model.predict(frame).vec("predict").to_numpy()
    resid = y - fitted
    ok = np.isfinite(resid)
    return {"fitted": fitted, "residual": resid,
            "mean": float(np.mean(resid[ok])),
            "std": float(np.std(resid[ok], ddof=1)) if ok.sum() > 1 else 0.0,
            "rmse": float(np.sqrt(np.mean(resid[ok] ** 2)))}


def explain(model, frame: Frame, top_n: int = 5,
            nbins: int = 20) -> Dict[str, object]:
    """The h2o.explain(model, frame) bundle, as data."""
    out: Dict[str, object] = {}
    vi = _varimp_of(model)
    if vi:
        out["varimp"] = vi
    if vi:
        # fold one-hot coefficient names ("g.b") back onto frame columns
        cols = []
        for k in vi:
            base = k if k in frame.names else k.rsplit(".", 1)[0]
            if base in frame.names and base not in cols:
                cols.append(base)
            if len(cols) == top_n:
                break
    else:
        cols = [c for c in frame.names
                if c != model.params.response_column][:top_n]
    out["pdp"] = {c: partial_dependence(model, frame, c, nbins=nbins)
                  for c in cols}
    if hasattr(model, "predict_contributions"):
        try:
            out["shap_summary"] = shap_summary(model, frame)
        except Exception:                   # noqa: BLE001 — multinomial etc.
            pass
    if not getattr(model.datainfo, "response_domain", None):
        out["residual_analysis"] = residual_analysis(model, frame)
    return out


def learning_curve(model) -> Dict[str, np.ndarray]:
    """Scoring-history curves (h2o.learning_curve_plot's table)."""
    hist = getattr(model, "scoring_history", None) or []
    if not hist:
        return {}
    keys = [k for k in hist[0] if isinstance(hist[0][k], (int, float))]
    return {k: np.asarray([h.get(k, np.nan) for h in hist]) for k in keys}


def _varimp_of(model) -> Optional[dict]:
    try:
        return model.varimp()
    except Exception:                       # noqa: BLE001 — not all models
        coefs = getattr(model, "coef_norm", None) or \
            getattr(model, "coef", None)
        if callable(coefs):
            coefs = coefs()
        if isinstance(coefs, dict):
            c = {k: abs(v) for k, v in coefs.items() if k != "Intercept"}
            if c:
                mx = max(c.values()) or 1.0
                return {k: v / mx for k, v in
                        sorted(c.items(), key=lambda kv: -kv[1])}
    return None


def varimp_heatmap(models: List) -> Dict[str, np.ndarray]:
    """Feature x model importance matrix (h2o.varimp_heatmap's table).

    Rows are the union of features (NaN where a model lacks one),
    ordered by mean importance across models.
    """
    vis = [(getattr(m, "key", f"model_{i}"), _varimp_of(m) or {})
           for i, m in enumerate(models)]
    feats = sorted({f for _, vi in vis for f in vi},
                   key=lambda f: -np.mean([vi.get(f, 0.0)
                                           for _, vi in vis]))
    M = np.full((len(feats), len(vis)), np.nan)
    for j, (_, vi) in enumerate(vis):
        for i, f in enumerate(feats):
            if f in vi:
                M[i, j] = vi[f]
    return {"feature": np.asarray(feats, dtype=object),
            "model": np.asarray([k for k, _ in vis], dtype=object),
            "importance": M}


def model_correlation(models: List, frame: Frame) -> Dict[str, np.ndarray]:
    """Pairwise agreement of model predictions on ``frame``
    (h2o.model_correlation_heatmap's table): for classifiers the
    fraction of identical predicted labels (the reference's measure for
    categorical responses), for regression the Pearson correlation."""
    classify = bool(getattr(models[0].datainfo, "response_domain", None))
    if classify:
        labels = [np.asarray(m.predict(frame).vec("predict").to_numpy())
                  for m in models]
        k = len(models)
        C = np.eye(k)
        for i in range(k):
            for j in range(i + 1, k):
                C[i, j] = C[j, i] = float(np.mean(labels[i] == labels[j]))
    else:
        P = np.stack([_response_col(m, m.predict(frame)) for m in models])
        C = np.corrcoef(P)
    return {"model": np.asarray([getattr(m, "key", f"model_{i}")
                                 for i, m in enumerate(models)],
                                dtype=object),
            "correlation": C}


def explain_models(models: List, frame: Frame, top_n: int = 5,
                   nbins: int = 20) -> Dict[str, object]:
    """Multi-model explain — the h2o.explain(aml/list) analog: global
    varimp heatmap + prediction-agreement matrix + the single-model
    bundle for the leader (first model)."""
    if not models:
        return {"varimp_heatmap": varimp_heatmap([])}
    return {
        "varimp_heatmap": varimp_heatmap(models),
        "model_correlation": model_correlation(models, frame),
        "leader": explain(models[0], frame, top_n=top_n, nbins=nbins),
    }


def permutation_importance(model, frame: Frame, metric: str = "auto",
                           n_repeats: int = 1,
                           seed: int = 0) -> Dict[str, np.ndarray]:
    """Permutation variable importance — h2o.permutation_varimp analog.

    Shuffles one model feature at a time (from the model's own DataInfo
    specs, so ignored/weights/offset columns are excluded) and reports
    the scoring-metric degradation through the model's metrics stack —
    observation weights are honored.  ``metric``: "auto" (logloss for
    classifiers, mse for regression), or an explicit metric attribute
    ("logloss", "mse", "rmse", "mae").  Importance = scrambled score -
    baseline (bigger = more important), averaged over ``n_repeats``.
    ``relative_importance`` is NaN when no feature degrades the score.
    """
    rng = np.random.default_rng(seed)
    classifier = bool(getattr(model.datainfo, "response_domain", None))
    key = metric
    if metric == "auto":
        key = "logloss" if classifier else "mse"
    perf0 = model.model_performance(frame)
    if not hasattr(perf0, key):
        raise ValueError(
            f"metric {metric!r} not available for this model "
            f"(have: {sorted(perf0.describe())})")

    def score(fr) -> float:
        return float(getattr(model.model_performance(fr), key))
    base = float(getattr(perf0, key))
    feats = [sp.name for sp in model.datainfo.specs
             if sp.name in frame.names]
    imp = np.zeros(len(feats))
    for i, col in enumerate(feats):
        v = frame.vec(col)
        vals = v.to_numpy()
        deltas = []
        for _ in range(n_repeats):
            perm = vals[rng.permutation(len(vals))]
            if v.type == T_CAT:
                pv = Vec.from_numpy(perm.astype(np.int32), T_CAT,
                                    domain=v.domain)
            else:
                pv = Vec.from_numpy(perm, v.type)
            deltas.append(score(frame.with_vec(col, pv)) - base)
        imp[i] = float(np.mean(deltas))
    order = np.argsort(-imp)
    rel = imp / imp[order[0]] if imp[order[0]] > 0 else         np.full_like(imp, np.nan)
    return {"feature": np.asarray([feats[i] for i in order], dtype=object),
            "importance": imp[order],
            "relative_importance": rel[order],
            "baseline_score": base}


def partial_dependence_2d(model, frame: Frame, col1: str, col2: str,
                          nbins: int = 10,
                          target_class: Optional[str] = None,
                          ) -> Dict[str, np.ndarray]:
    """Two-way PDP — the reference's col_pairs_2dpdp table: the mean
    response over the grid product of two columns."""
    if col1 == col2:
        raise ValueError("partial_dependence_2d needs two distinct columns")
    v1, v2 = frame.vec(col1), frame.vec(col2)
    g1, g2 = _grid_for(v1, nbins), _grid_for(v2, nbins)
    M = np.empty((len(g1), len(g2)))
    for i, a in enumerate(g1):
        fa = _with_constant(frame, col1, a, v1)
        for j, b in enumerate(g2):
            r = _response_col(model, model.predict(
                _with_constant(fa, col2, b, v2)), target_class)
            M[i, j] = float(np.mean(r))
    lab1 = ([v1.domain[int(g)] for g in g1] if v1.type == T_CAT else g1)
    lab2 = ([v2.domain[int(g)] for g in g2] if v2.type == T_CAT else g2)
    return {"col1": col1, "col2": col2,
            "grid1": np.asarray(lab1, dtype=object),
            "grid2": np.asarray(lab2, dtype=object),
            "mean_response": M}


def partial_dependence_multi(models: List, frame: Frame, column: str,
                             nbins: int = 20,
                             target_class: Optional[str] = None,
                             ) -> Dict[str, object]:
    """Multi-model PDP overlay — h2o.pd_multi_plot's table: every
    model's mean-response curve over ONE shared grid (the grid is a
    deterministic function of frame/column/nbins, so per-model calls to
    partial_dependence line up).  Returns positional parallel arrays so
    duplicate model keys are preserved, like varimp_heatmap."""
    tables = [partial_dependence(m, frame, column, nbins=nbins,
                                 target_class=target_class)
              for m in models]
    grid = tables[0]["grid"] if tables else np.asarray([], dtype=object)
    return {"column": column, "grid": grid,
            "model": np.asarray(
                [getattr(m, "key", f"model_{i}")
                 for i, m in enumerate(models)], dtype=object),
            "curves": np.stack([t["mean_response"] for t in tables])
            if tables else np.zeros((0, 0))}
