"""Built-in demo datasets — the h2o.demo() / smalldata starter analog.

The reference ships starter datasets for examples and docs; here the
classic small tables come from scikit-learn's bundled data (no
download) and arrive as ready-to-model Frames.
"""

from __future__ import annotations

import numpy as np

from .frame.frame import Frame

__all__ = ["load_dataset"]

_LOADERS = {}


def _register(name):
    def deco(fn):
        _LOADERS[name] = fn
        return fn
    return deco


@_register("iris")
def _iris() -> Frame:
    from sklearn.datasets import load_iris
    d = load_iris()
    cols = {n.replace(" (cm)", "").replace(" ", "_"): d.data[:, j]
            for j, n in enumerate(d.feature_names)}
    cols["class"] = np.asarray(
        [d.target_names[t] for t in d.target], dtype=object)
    return Frame.from_numpy(cols)


@_register("wine")
def _wine() -> Frame:
    from sklearn.datasets import load_wine
    d = load_wine()
    cols = {n: d.data[:, j] for j, n in enumerate(d.feature_names)}
    cols["class"] = np.asarray(
        [d.target_names[t] for t in d.target], dtype=object)
    return Frame.from_numpy(cols)


@_register("breast_cancer")
def _bc() -> Frame:
    from sklearn.datasets import load_breast_cancer
    d = load_breast_cancer()
    cols = {n.replace(" ", "_"): d.data[:, j]
            for j, n in enumerate(d.feature_names)}
    cols["diagnosis"] = np.asarray(
        [d.target_names[t] for t in d.target], dtype=object)
    return Frame.from_numpy(cols)


@_register("diabetes")
def _diabetes() -> Frame:
    from sklearn.datasets import load_diabetes
    d = load_diabetes()
    cols = {n: d.data[:, j] for j, n in enumerate(d.feature_names)}
    cols["progression"] = d.target.astype(np.float64)
    return Frame.from_numpy(cols)


def load_dataset(name: str, destination_frame=None) -> Frame:
    """Load a bundled demo dataset by name (h2o demo-data analog).

    Available: iris, wine, breast_cancer, diabetes.
    """
    if name not in _LOADERS:
        raise ValueError(
            f"unknown dataset {name!r}; available: {sorted(_LOADERS)}")
    try:
        fr = _LOADERS[name]()
    except ImportError as e:
        raise ImportError(
            "load_dataset needs scikit-learn for the bundled data "
            "(pip install scikit-learn)") from e
    from .runtime import dkv
    fr.key = destination_frame or dkv.make_key(name)
    dkv.put(fr.key, fr)
    return fr
