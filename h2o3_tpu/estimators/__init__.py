"""Generated remote estimators (bindings-codegen output).

``from h2o3_tpu.estimators import H2OGBMEstimator`` — classes mirror the
server's /3/Metadata/schemas parameter surface; see bindings/gen.py.
"""

from ._generated import *          # noqa: F401,F403
from ._generated import __all__    # noqa: F401
