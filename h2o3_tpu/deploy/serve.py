"""Launcher: boot the runtime + REST server — the water.H2OApp analog.

Single host:   python -m h2o3_tpu.deploy.serve --port 54321
Multi-host:    ... --coordinator host:port --num-processes N --process-id I
Pod-native:    ... --discover <headless-service> --cluster-size N
               (DNS-record clouding, H2OCluster.java analog; an Indexed
               Job sets H2O3_TPU_POD_INDEX for race-free ordinals)
(REST serves from process 0; workers join the mesh and block.)
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser("h2o3_tpu.deploy.serve")
    from h2o3_tpu.runtime.config import config
    ap.add_argument("--port", type=int, default=config().port)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (multi-host)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--discover", default=None, metavar="SERVICE",
                    help="headless-service DNS discovery instead of an "
                         "explicit --coordinator (k8s pod clouding)")
    ap.add_argument("--cluster-size", type=int, default=None,
                    help="expected process count for --discover")
    ap.add_argument("--discover-port", type=int, default=None,
                    help="rendezvous port (default 8476); for --flatfile "
                         "it also disambiguates this process's rank when "
                         "several members share the host")
    ap.add_argument("--flatfile", default=None,
                    help="cloud from a host:port member file (assisted "
                         "clustering analog; polled until --cluster-size "
                         "lines exist)")
    ap.add_argument("--username", default="")
    ap.add_argument("--password", default="")
    ap.add_argument("--auth", default=None,
                    help="authenticator spec (static:/hash_file:/cmd:/"
                         "module:) — see h2o3_tpu.api.auth")
    ap.add_argument("--https", action="store_true")
    ap.add_argument("--https-cert", default=None)
    ap.add_argument("--https-key", default=None)
    args = ap.parse_args(argv)
    if args.discover and not args.coordinator:
        from h2o3_tpu.runtime.discovery import discover
        (args.coordinator, args.num_processes,
         args.process_id) = discover(args.discover,
                                     port=args.discover_port or 8476,
                                     expected=args.cluster_size)
    elif args.flatfile and not args.coordinator:
        from h2o3_tpu.runtime.discovery import from_flatfile
        # own_port only when EXPLICITLY given: a defaulted port would
        # satisfy the multi-member-per-host ambiguity guard with the
        # wrong member instead of erroring
        (args.coordinator, args.num_processes,
         args.process_id) = from_flatfile(args.flatfile,
                                          expected=args.cluster_size,
                                          own_port=args.discover_port)
    if args.num_processes is not None and args.num_processes <= 1:
        # an EXPLICIT 1-member cloud needs no rendezvous/control plane —
        # boot the plain single-host path.  num_processes=None stays
        # multi-host: the TPU environment auto-detects slice topology.
        args.coordinator = None

    import os
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # some images pre-import jax with a baked-in platform (e.g. a TPU
        # plugin from sitecustomize); the env var must win for the launcher
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import h2o3_tpu
    cl = h2o3_tpu.init(coordinator=args.coordinator,
                       num_processes=args.num_processes,
                       process_id=args.process_id)
    server = None
    if jax.process_index() == 0:
        from h2o3_tpu.api.server import start_server
        server = start_server(port=args.port, username=args.username,
                              password=args.password, auth=args.auth,
                              https=args.https, https_cert=args.https_cert,
                              https_key=args.https_key)
        print(f"h2o3_tpu serving on {server.url} "
              f"(mesh: {dict(cl.mesh.shape)})", flush=True)
        if os.environ.get("H2O3_TPU_RECOVERY_DIR"):
            # relaunched coordinator: re-import journaled frames from
            # their source URIs and retrain interrupted jobs
            from h2o3_tpu.runtime import recovery
            resumed = recovery.resume()
            if resumed:
                print(f"h2o3_tpu recovery resumed {len(resumed)} job(s): "
                      f"{resumed}", flush=True)
            # bring the serving plane back too: every `!serve/`-journaled
            # model is re-published into the micro-batcher registry
            from h2o3_tpu.serving import batcher as _serving_batcher
            republished = _serving_batcher.republish_journaled()
            if republished:
                print(f"h2o3_tpu serving re-published "
                      f"{len(republished)} model(s): {republished}",
                      flush=True)
    else:
        print(f"h2o3_tpu worker {jax.process_index()} joined "
              f"(mesh: {dict(cl.mesh.shape)})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    # graceful rollout (k8s sends SIGTERM first): stop accepting new
    # requests and drain in-flight handlers — bounded by
    # H2O3_TPU_REST_DRAIN_TIMEOUT — then stop the serving batchers and
    # detach the cluster, so pod restarts never drop scoring requests
    if server is not None:
        try:
            server.stop()
            print("h2o3_tpu REST drained", flush=True)
        except Exception as e:          # noqa: BLE001 — still detach
            print(f"h2o3_tpu REST drain failed: {e!r}", flush=True)
    try:
        from h2o3_tpu.serving import batcher as _serving_batcher
        _serving_batcher.shutdown_all()
    except Exception:                   # noqa: BLE001 — optional plane
        pass
    h2o3_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
