"""Launcher: boot the runtime + REST server — the water.H2OApp analog.

Single host:   python -m h2o3_tpu.deploy.serve --port 54321
Multi-host:    ... --coordinator host:port --num-processes N --process-id I
(REST serves from process 0; workers join the mesh and block.)
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser("h2o3_tpu.deploy.serve")
    from h2o3_tpu.runtime.config import config
    ap.add_argument("--port", type=int, default=config().port)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (multi-host)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--username", default="")
    ap.add_argument("--password", default="")
    args = ap.parse_args(argv)

    import os
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # some images pre-import jax with a baked-in platform (e.g. a TPU
        # plugin from sitecustomize); the env var must win for the launcher
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import h2o3_tpu
    cl = h2o3_tpu.init(coordinator=args.coordinator,
                       num_processes=args.num_processes,
                       process_id=args.process_id)
    import jax
    if jax.process_index() == 0:
        from h2o3_tpu.api.server import start_server
        server = start_server(port=args.port, username=args.username,
                              password=args.password)
        print(f"h2o3_tpu serving on {server.url} "
              f"(mesh: {dict(cl.mesh.shape)})", flush=True)
    else:
        print(f"h2o3_tpu worker {jax.process_index()} joined "
              f"(mesh: {dict(cl.mesh.shape)})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
