"""Deployment entry points (launcher analog of the reference's h2oapp)."""
