"""MLflow model flavor for h2o3_tpu models.

Reference: ``h2o-py-mlflow-flavor/h2o_mlflow_flavor/__init__.py`` — an
MLflow flavor that save_model/log_model's an H2O model directory with an
``MLmodel`` descriptor carrying both the native flavor and a
``python_function`` flavor so generic MLflow tooling can serve it.

This implementation writes the portable scoring artifact (export/mojo —
numpy-only standalone scorer) as the model payload, so loading does NOT
require a running cluster; ``load_model`` returns a pyfunc-style wrapper
with ``predict(pandas_or_dict)``.  ``mlflow`` itself is optional: saving
and loading work without it (the MLmodel yaml is written directly), and
``log_model`` uses the real mlflow APIs when the library is present.
"""

from __future__ import annotations

import os
from typing import Optional

FLAVOR_NAME = "h2o3_tpu"
_ARTIFACT = "model.h2o3tpu.zip"


def _mlmodel_dict(run_id: Optional[str] = None) -> dict:
    from . import __version__
    return {
        "flavors": {
            FLAVOR_NAME: {
                "artifact": _ARTIFACT,
                "h2o3_tpu_version": __version__,
            },
            "python_function": {
                "loader_module": "h2o3_tpu.mlflow_flavor",
                "python_version": ".".join(map(str, __import__(
                    "sys").version_info[:3])),
                "data": _ARTIFACT,
            },
        },
        **({"run_id": run_id} if run_id else {}),
    }


def save_model(model, path: str, run_id: Optional[str] = None) -> str:
    """Write an MLflow-layout model directory (mlflow not required)."""
    import yaml
    from .export.mojo import export_mojo
    os.makedirs(path, exist_ok=True)
    export_mojo(model, os.path.join(path, _ARTIFACT))
    with open(os.path.join(path, "MLmodel"), "w") as fh:
        yaml.safe_dump(_mlmodel_dict(run_id), fh, sort_keys=False)
    with open(os.path.join(path, "requirements.txt"), "w") as fh:
        # the python_function loader imports h2o3_tpu.mlflow_flavor, so a
        # serving env built from this file must carry the package itself
        fh.write("numpy\nh2o3_tpu\n")
    return path


class _PyFuncModel:
    """python_function wrapper: predict(DataFrame | dict-of-columns)."""

    def __init__(self, scorer):
        self.scorer = scorer

    def predict(self, data):
        cols = ({c: data[c].tolist() for c in data.columns}
                if hasattr(data, "columns") else dict(data))
        return self.scorer.predict(cols)


def load_model(path: str) -> _PyFuncModel:
    """Load a save_model directory (or the artifact inside a run)."""
    from .export.mojo import import_mojo
    artifact = path
    if os.path.isdir(path):
        artifact = os.path.join(path, _ARTIFACT)
    return _PyFuncModel(import_mojo(artifact))


def _load_pyfunc(data_path: str) -> _PyFuncModel:
    """MLflow python_function entry point."""
    return load_model(data_path)


def log_model(model, artifact_path: str = "model",
              registered_model_name: Optional[str] = None):
    """Log to the active MLflow run (needs the mlflow library)."""
    try:
        import mlflow
    except ImportError as e:               # pragma: no cover — not in image
        raise ImportError(
            "log_model needs the mlflow library; use save_model for a "
            "library-free MLflow-layout directory") from e
    import tempfile
    run = mlflow.active_run()
    with tempfile.TemporaryDirectory() as d:
        local = os.path.join(d, "model")
        save_model(model, local, run_id=run.info.run_id if run else None)
        mlflow.log_artifacts(local, artifact_path=artifact_path)
    if registered_model_name:              # pragma: no cover — needs mlflow
        # log_artifacts auto-creates a run when none was active
        run = run or mlflow.active_run() or mlflow.last_active_run()
        if run is None:
            raise RuntimeError(
                "registered_model_name given but no MLflow run exists")
        mlflow.register_model(
            f"runs:/{run.info.run_id}/{artifact_path}",
            registered_model_name)
    return artifact_path
