"""Remote REST client — the h2o-py H2OConnection/H2OFrame-over-HTTP analog.

Reference: ``h2o-py/h2o/backend/connection.py`` (H2OConnection: versioned
REST with retries) and ``h2o-py/h2o/h2o.py`` module functions that drive
/3/Parse, /3/ModelBuilders, /3/Predictions.  Everything here talks ONLY
HTTP — no shared memory with the server process — so it exercises the same
contract a remote notebook would.

Usage::

    import h2o3_tpu.client as h2oc
    conn = h2oc.connect("http://127.0.0.1:54321")
    fr = conn.import_file("/data/train.csv")
    model = conn.train("gbm", training_frame=fr, response_column="y")
    preds = model.predict(fr)
    head = preds.head()
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Union

from .rapids.expr import Backend, LazyFrame


class H2OConnectionError(Exception):
    pass


def _frame_key(frame) -> str:
    """Accept a RemoteFrame/Frame handle or a bare key string."""
    return frame.key if hasattr(frame, "key") else str(frame)


class H2OConnection(Backend):
    """HTTP(S) connection to a running h2o3_tpu REST server.

    TLS: ``cafile`` pins the server certificate (self-signed deployments
    pass the cert PEM itself); ``insecure=True`` skips verification (dev
    only).  ``use_session=True`` exchanges the credentials for a form-login
    session cookie (POST /3/Login) so the password is sent exactly once —
    the h2o-security form-login flow.
    """

    def __init__(self, url: str, username: str = "", password: str = "",
                 cafile: Optional[str] = None, insecure: bool = False,
                 use_session: bool = False):
        self.url = url.rstrip("/")
        self._auth = None
        self._cookie = None
        self._ssl_ctx = None
        if self.url.startswith("https"):
            import ssl
            if insecure:
                self._ssl_ctx = ssl._create_unverified_context()
            else:
                self._ssl_ctx = ssl.create_default_context(cafile=cafile)
        if username:
            import base64
            self._auth = "Basic " + base64.b64encode(
                f"{username}:{password}".encode()).decode()
        if use_session:
            out = self.post("/3/Login", username=username, password=password)
            if out.get("login") != "ok":     # pragma: no cover — server 401s
                raise H2OConnectionError("login failed")
            self._auth = None                # cookie replaces the header
        self.cloud = self.get("/3/Cloud")

    # ------------------------------------------------------------- transport
    def _req(self, method: str, route: str, params: Optional[dict] = None,
             raw_body: Optional[bytes] = None, binary: bool = False):
        url = f"{self.url}{route}"
        data = raw_body
        if raw_body is None:
            if method == "GET" and params:
                url += "?" + urllib.parse.urlencode(params)
            elif params is not None:
                data = json.dumps(params).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/octet-stream"
                       if raw_body is not None else "application/json")
        if self._auth:
            req.add_header("Authorization", self._auth)
        if self._cookie:
            req.add_header("Cookie", self._cookie)
        try:
            with urllib.request.urlopen(req, context=self._ssl_ctx) as resp:
                set_cookie = resp.headers.get("Set-Cookie")
                if set_cookie and "h2o3-session=" in set_cookie:
                    self._cookie = set_cookie.split(";")[0]
                body = resp.read()
                payload = body if binary else json.loads(body.decode())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode())
            except Exception:
                payload = {"error": str(e)}
            raise H2OConnectionError(
                f"{method} {route} -> {e.code}: "
                f"{payload.get('error', payload)}") from None
        return payload

    def get(self, _route: str, **params):
        return self._req("GET", _route, params or None)

    def post(self, _route: str, **params):
        return self._req("POST", _route, params)

    def delete(self, _route: str):
        return self._req("DELETE", _route)

    # ---------------------------------------------------- Backend (rapids)
    def rapids(self, text: str):
        out = self.post("/99/Rapids", ast=text)
        if "scalar" in out:
            return out["scalar"]
        return out

    def frame_by_key(self, key: str) -> "RemoteFrame":
        return RemoteFrame(self, key)

    # -------------------------------------------------------------- actions
    def import_file(self, path: str,
                    destination_frame: Optional[str] = None,
                    col_types: Optional[Dict[str, str]] = None
                    ) -> "RemoteFrame":
        kw = {"col_types": col_types} if col_types else {}
        out = self.post("/3/Parse", path=path,
                        destination_frame=destination_frame, **kw)
        return RemoteFrame(self, out["destination_frame"]["name"])

    def frames(self) -> List[str]:
        return [f["frame_id"]["name"] for f in self.get("/3/Frames")["frames"]]

    def models(self) -> List[str]:
        return [m["model_id"]["name"] for m in self.get("/3/Models")["models"]]

    def train(self, algo: str, training_frame, validation_frame=None,
              **params) -> "RemoteModel":
        if validation_frame is not None:
            params["validation_frame"] = _frame_key(validation_frame)
        out = self.post(f"/3/ModelBuilders/{algo}",
                        training_frame=_frame_key(training_frame), **params)
        return RemoteModel(self, out["model"]["model_id"]["name"])

    def schemas(self) -> dict:
        return self.get("/3/Metadata/schemas")

    def model_builders(self, algo: Optional[str] = None) -> dict:
        """Parameter metadata — /3/ModelBuilders (drives codegen)."""
        route = "/3/ModelBuilders" + (f"/{algo}" if algo else "")
        return self.get(route)["model_builders"]

    def grid(self, algo: str, hyper_params: dict, training_frame,
             validation_frame=None, search_criteria: Optional[dict] = None,
             sort_metric: Optional[str] = None, **base_params) -> "RemoteGrid":
        """Hyperparameter search over REST — h2o.grid analog."""
        params = dict(base_params, training_frame=_frame_key(training_frame),
                      hyper_parameters=hyper_params)
        if validation_frame is not None:
            params["validation_frame"] = _frame_key(validation_frame)
        if search_criteria:
            params["search_criteria"] = search_criteria
        if sort_metric:
            params["sort_metric"] = sort_metric
        out = self.post(f"/99/Grid/{algo}", **params)
        return RemoteGrid(self, out)

    def automl(self, training_frame, validation_frame=None,
               **params) -> "RemoteAutoML":
        """Run AutoML over REST — H2OAutoML analog."""
        params["training_frame"] = _frame_key(training_frame)
        if validation_frame is not None:
            params["validation_frame"] = _frame_key(validation_frame)
        out = self.post("/99/AutoMLBuilder", **params)
        return RemoteAutoML(self, out)

    def upload_frame(self, frame_or_bytes,
                     destination_frame: Optional[str] = None,
                     filename: str = "upload.csv") -> "RemoteFrame":
        """Push a LOCAL frame (or raw csv bytes) to the server:
        /3/PostFile + /3/Parse (h2o.upload_file analog)."""
        col_types = None
        if isinstance(frame_or_bytes, (bytes, bytearray)):
            raw = bytes(frame_or_bytes)
        else:
            import tempfile
            import os
            from .frame.parse import export_file
            with tempfile.TemporaryDirectory() as d:
                p = os.path.join(d, "f.csv")
                export_file(frame_or_bytes, p)
                with open(p, "rb") as fh:
                    raw = fh.read()
            # the CSV carries no typing — forward the local frame's column
            # types so the server does not re-infer cats/times as numerics
            col_types = frame_or_bytes.types()
        out = self._req("POST",
                        f"/3/PostFile?filename={urllib.parse.quote(filename)}",
                        raw_body=raw)
        return self.import_file(out["destination_key"],
                                destination_frame=destination_frame,
                                col_types=col_types)

    def upload_model(self, path: str) -> "RemoteModel":
        """Install a locally saved model artifact on the server."""
        with open(path, "rb") as f:
            raw = f.read()
        out = self._req("POST", "/3/Models.upload.bin", raw_body=raw)
        return RemoteModel(self, out["models"][0]["model_id"]["name"])

    def _fetch_bytes(self, route: str) -> bytes:
        return self._req("GET", route, binary=True)

    def remove(self, key: str):
        self.delete(f"/3/DKV/{key}")

    def lazy(self, frame: "RemoteFrame") -> LazyFrame:
        return LazyFrame.from_key(frame.key, backend=self)


class RemoteFrame:
    """Handle to a server-side Frame, driven entirely over REST."""

    def __init__(self, conn: H2OConnection, key: str):
        self.conn = conn
        self.key = key

    @property
    def schema(self) -> dict:
        return self.conn.get(f"/3/Frames/{self.key}")["frames"][0]

    @property
    def nrows(self) -> int:
        return int(self.schema["rows"])

    @property
    def names(self) -> List[str]:
        return [c["label"] for c in self.schema["columns"]]

    def types(self) -> Dict[str, str]:
        return {c["label"]: c["type"] for c in self.schema["columns"]}

    def summary(self) -> dict:
        return self.conn.get(
            f"/3/Frames/{self.key}/summary")["frames"][0]["summary"]

    def head(self, n: int = 10) -> Dict[str, list]:
        return self.conn.get(f"/3/Frames/{self.key}/data",
                             row_offset=0, row_count=n)["data"]

    def export(self, path: str) -> str:
        return self.conn.post(f"/3/Frames/{self.key}/export",
                              path=path)["path"]

    def split_frame(self, ratios: Sequence[float],
                    seed: int = 0) -> List["RemoteFrame"]:
        out = self.conn.post("/3/SplitFrame", key=self.key,
                             ratios=json.dumps(list(ratios)), seed=seed)
        return [RemoteFrame(self.conn, k)
                for k in out["destination_frames"]]

    def lazy(self) -> LazyFrame:
        return LazyFrame.from_key(self.key, backend=self.conn)

    def __repr__(self):
        return f"<RemoteFrame {self.key}>"


class RemoteModel:
    """Handle to a server-side Model."""

    def __init__(self, conn: H2OConnection, key: str):
        self.conn = conn
        self.key = key

    @property
    def schema(self) -> dict:
        return self.conn.get(f"/3/Models/{self.key}")["models"][0]

    @property
    def algo(self) -> str:
        return self.schema["algo"]

    def metrics(self) -> dict:
        return self.schema["training_metrics"]

    def scoring_history(self) -> list:
        return self.conn.get(
            f"/3/Models/{self.key}/scoring_history")["scoring_history"]

    def predict(self, frame: Union[RemoteFrame, str]) -> RemoteFrame:
        fk = _frame_key(frame)
        out = self.conn.post(
            f"/3/Predictions/models/{self.key}/frames/{fk}")
        return RemoteFrame(self.conn, out["predictions_frame"]["name"])

    def model_performance(self, frame: Union[RemoteFrame, str]) -> dict:
        fk = _frame_key(frame)
        return self.conn.post(
            f"/3/ModelMetrics/models/{self.key}/frames/{fk}"
        )["model_metrics"][0]

    def varimp(self) -> List[dict]:
        return self.conn.get(f"/3/Models/{self.key}/varimp")["varimp"]

    def partial_dependence(self, frame: Union[RemoteFrame, str],
                           column: str, nbins: int = 20) -> dict:
        fk = _frame_key(frame)
        return self.conn.post("/3/PartialDependence", model=self.key,
                              frame=fk, column=column,
                              nbins=nbins)["partial_dependence"]

    def download(self, path: str) -> str:
        """Download the binary model artifact (h2o.download_model)."""
        raw = self.conn._fetch_bytes(f"/3/Models.fetch.bin/{self.key}")
        with open(path, "wb") as f:
            f.write(raw)
        return path

    def download_mojo(self, path: str) -> str:
        """Download the portable scoring artifact (h2o.download_mojo)."""
        raw = self.conn._fetch_bytes(f"/3/Models/{self.key}/mojo")
        with open(path, "wb") as f:
            f.write(raw)
        return path

    def save(self, directory: str) -> str:
        """Server-side save (h2o.save_model)."""
        return self.conn.post(f"/99/Models.bin/{self.key}",
                              dir=directory)["path"]

    def __repr__(self):
        return f"<RemoteModel {self.key}>"


class RemoteGrid:
    """Handle to a server-side Grid."""

    def __init__(self, conn: H2OConnection, schema: dict):
        self.conn = conn
        self.key = schema["grid_id"]["name"]
        self._schema = schema

    @property
    def model_ids(self) -> List[str]:
        return [m["name"] for m in self._schema["model_ids"]]

    @property
    def models(self) -> List[RemoteModel]:
        return [RemoteModel(self.conn, k) for k in self.model_ids]

    def summary_table(self) -> List[dict]:
        return self._schema["summary_table"]

    @property
    def failed_entries(self) -> List[dict]:
        """Per-member build failures (combo params + error), if any."""
        return self._schema.get("failed_entries", [])

    @property
    def best_model(self) -> RemoteModel:
        return RemoteModel(self.conn,
                           self.summary_table()[0]["model_id"])

    def refresh(self) -> "RemoteGrid":
        self._schema = self.conn.get(f"/99/Grids/{self.key}")
        return self

    def __repr__(self):
        return f"<RemoteGrid {self.key}: {len(self.model_ids)} models>"


class RemoteAutoML:
    """Handle to a finished server-side AutoML run."""

    def __init__(self, conn: H2OConnection, schema: dict):
        self.conn = conn
        self.project_name = schema["project_name"]
        self._schema = schema

    @property
    def leader(self) -> RemoteModel:
        return RemoteModel(self.conn, self._schema["leader"]["name"])

    def leaderboard(self) -> List[dict]:
        return self.conn.get(
            f"/99/Leaderboards/{self.project_name}")["leaderboard_table"]

    def __repr__(self):
        return f"<RemoteAutoML {self.project_name}>"


def connect(url: str = "http://127.0.0.1:54321", username: str = "",
            password: str = "", **kw) -> H2OConnection:
    """h2o.connect analog (kw: cafile=, insecure=, use_session=)."""
    return H2OConnection(url, username, password, **kw)
