"""Bindings codegen: REST schema metadata -> client estimator classes.

Reference: ``h2o-bindings/bin/gen_python.py`` — the reference generates its
Python/R estimator classes from the server's schema metadata endpoint so
clients never drift from the server's parameter surface.  SURVEY.md §2.8:
"replicate this pattern".
"""

from .gen import generate_estimators_source, write_estimators

__all__ = ["generate_estimators_source", "write_estimators"]
