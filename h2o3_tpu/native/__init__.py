"""Native runtime components (C++ via ctypes — no pybind11 in-image).

``fastcsv`` is the byte-level CSV tokenizer for the parse hot path (the
water/parser/CsvParser fast-path analog): numeric cells go straight into
column-major double buffers with no per-cell Python objects; text cells
are flagged with byte ranges for the host-side categorical/string pass.

The shared object builds on first use with the in-image g++ (cached next
to the source); every caller must handle ``load() is None`` and fall back
to the portable tokenizer — builds can be unavailable in stripped
deployment images.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_fastcsv.so")


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def load():
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fastcsv_parse.restype = ctypes.c_longlong
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
            ctypes.c_int, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.fastcsv_ncols.restype = ctypes.c_int
        lib.fastcsv_ncols.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                      ctypes.c_char]
        lib.fastcsv_parse_range.restype = ctypes.c_longlong
        lib.fastcsv_parse_range.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_char, ctypes.c_int, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.fastcsv_count_lines.restype = ctypes.c_longlong
        lib.fastcsv_count_lines.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int)]
        _lib = lib
        return _lib


def parse_bytes(data: bytes, sep: str = ",", ncols: Optional[int] = None,
                threads: Optional[int] = None):
    """Tokenize a CSV byte buffer natively, multi-threaded when safe.

    Quote-free buffers split at newline boundaries into per-thread byte
    ranges parsed concurrently (ctypes releases the GIL) — the
    MultiFileParseTask chunk layout (ParseDataset.java:688) on one host.
    A buffer containing any double-quote parses single-threaded: quoted
    cells may hide newlines, so ranges cannot be aligned safely.

    Returns (values [rows, ncols] f64 with NaN for non-numeric, flags
    [rows, ncols] uint8 text markers, offsets [rows, ncols, 2] byte
    ranges, consumed bytes) — or None when the native library is
    unavailable (callers fall back to the portable parser).
    """
    lib = load()
    if lib is None:
        return None
    n = len(data)
    if n > (1 << 31) - 16:               # int32 offsets: pre-split or defer
        return None
    sepc = sep.encode()[0:1]
    if ncols is None:
        ncols = int(lib.fastcsv_ncols(data, n, sepc))
    has_quotes = ctypes.c_int(0)
    total_lines = int(lib.fastcsv_count_lines(data, 0, n,
                                              ctypes.byref(has_quotes)))
    max_rows = max(total_lines + 2, 4)
    values = np.empty(ncols * max_rows, np.float64)
    flags = np.zeros(ncols * max_rows, np.uint8)
    offsets = np.zeros(ncols * max_rows * 2, np.int32)
    vp = values.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    fp = flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    op = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    if threads is None:
        threads = min(16, os.cpu_count() or 1)
    if has_quotes.value or threads <= 1 or n < (1 << 22):
        consumed = ctypes.c_longlong(0)
        rows = int(lib.fastcsv_parse_range(
            data, 0, n, sepc, ncols, max_rows, 0, max_rows, vp, fp, op,
            ctypes.byref(consumed)))
        keep = [(0, rows)]
        tail = int(consumed.value)
    else:
        # newline-aligned byte ranges
        bounds = [0]
        for t in range(1, threads):
            pos = data.find(b"\n", n * t // threads)
            pos = n if pos < 0 else pos + 1
            if pos > bounds[-1]:
                bounds.append(pos)
        bounds.append(n)
        ranges = [(bounds[i], bounds[i + 1])
                  for i in range(len(bounds) - 1)
                  if bounds[i + 1] > bounds[i]]
        # row_base per range = cumulative newline counts (upper bound:
        # blank lines produce gaps, compacted below)
        counts = [int(lib.fastcsv_count_lines(data, a, b, None))
                  for a, b in ranges]
        counts[-1] += 1 if not data.endswith(b"\n") else 0
        bases = np.concatenate([[0], np.cumsum(counts)])[:-1]

        import concurrent.futures

        def work(k):
            a, b = ranges[k]
            consumed = ctypes.c_longlong(0)
            got = int(lib.fastcsv_parse_range(
                data, a, b, sepc, ncols, max_rows, int(bases[k]),
                int(bases[k]) + counts[k], vp, fp, op,
                ctypes.byref(consumed)))
            return got, int(consumed.value)

        with concurrent.futures.ThreadPoolExecutor(len(ranges)) as ex:
            results = list(ex.map(work, range(len(ranges))))
        keep = [(int(bases[k]), results[k][0]) for k in range(len(ranges))]
        # a range that stopped early (over-wide row) invalidates the
        # later ranges' row_bases — fall back to the strict engines
        for k in range(len(ranges) - 1):
            if results[k][1] != ranges[k][1]:
                return None
        tail = results[-1][1]
    keep = [(b, c) for b, c in keep if c > 0]
    contiguous = all(keep[i][0] + keep[i][1] == keep[i + 1][0]
                     for i in range(len(keep) - 1))
    V = values.reshape(ncols, max_rows)
    F = flags.reshape(ncols, max_rows)
    O = offsets.reshape(ncols, max_rows, 2)
    if keep and contiguous:
        # the common case (no blank lines): strided VIEWS, no gather copy
        a = keep[0][0]
        b = keep[-1][0] + keep[-1][1]
        return V.T[a:b], F.T[a:b], O.transpose(1, 0, 2)[a:b], tail
    rows_idx = np.concatenate([np.arange(b, b + c) for b, c in keep]) \
        if keep else np.zeros(0, np.int64)
    return (V.T[rows_idx], F.T[rows_idx],
            O.transpose(1, 0, 2)[rows_idx], tail)
