"""Native runtime components (C++ via ctypes — no pybind11 in-image).

``fastcsv`` is the byte-level CSV tokenizer for the parse hot path (the
water/parser/CsvParser fast-path analog): numeric cells go straight into
column-major double buffers with no per-cell Python objects; text cells
are flagged with byte ranges for the host-side categorical/string pass.

The buffer API is pointer-based (``c_void_p`` + length), so the same
entry points tokenize plain ``bytes`` AND zero-copy ``mmap`` views (a
1-D ``np.uint8`` array over the mapping) — the parse pipeline never
materializes a second copy of the file.  ``parse_view`` fans
newline-aligned byte ranges over a bounded thread pool (ctypes releases
the GIL, so ranges tokenize truly in parallel) and invokes an optional
``on_range`` callback as each range lands, letting the caller overlap
device transfer of early ranges with tokenization of later ones.

The shared object builds on first use with the in-image g++ (cached next
to the source); every caller must handle ``load() is None`` and fall back
to the portable tokenizer — builds can be unavailable in stripped
deployment images.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_fastcsv.so")


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def load():
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fastcsv_parse.restype = ctypes.c_longlong
        lib.fastcsv_parse.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_char,
            ctypes.c_int, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.fastcsv_ncols.restype = ctypes.c_int
        lib.fastcsv_ncols.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                      ctypes.c_char]
        lib.fastcsv_parse_range.restype = ctypes.c_longlong
        lib.fastcsv_parse_range.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_char, ctypes.c_int, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.fastcsv_count_lines.restype = ctypes.c_longlong
        lib.fastcsv_count_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int)]
        lib.fastcsv_find_newline.restype = ctypes.c_longlong
        lib.fastcsv_find_newline.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]
        lib.fastcsv_count_quotes.restype = ctypes.c_longlong
        lib.fastcsv_count_quotes.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]
        lib.fastcsv_gather_cells.restype = None
        lib.fastcsv_gather_cells.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong,
            ctypes.c_int, ctypes.c_void_p]
        _lib = lib
        return _lib


def _as_view(data) -> np.ndarray:
    """Zero-copy 1-D uint8 view over bytes / mmap / numpy input."""
    if isinstance(data, np.ndarray):
        if data.dtype != np.uint8 or data.ndim != 1 \
                or not data.flags.c_contiguous:
            raise ValueError("parse view must be a contiguous 1-D uint8 "
                             "array")
        return data
    return np.frombuffer(data, dtype=np.uint8)


def gather_cells(view, starts: np.ndarray, ends: np.ndarray,
                 width: int) -> Optional[np.ndarray]:
    """Gather variable-length cells into a fixed-width ``|S width|`` column.

    Returns an ``[n]``-shaped bytes array (NUL-padded) whose vectorized
    ``np.unique``/compare path replaces the per-cell Python decode loop,
    or None when the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    view = _as_view(view)
    starts = np.ascontiguousarray(starts, dtype=np.int32)
    ends = np.ascontiguousarray(ends, dtype=np.int32)
    n = len(starts)
    width = max(int(width), 1)
    out = np.empty(n * width, dtype=np.uint8)
    lib.fastcsv_gather_cells(
        view.ctypes.data,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, width, out.ctypes.data)
    return out.view(dtype=f"S{width}")


def ncols_of(view, sep: str = ",") -> Optional[int]:
    lib = load()
    if lib is None:
        return None
    view = _as_view(view)
    return int(lib.fastcsv_ncols(view.ctypes.data, len(view),
                                 sep.encode()[0:1]))


def _range_bounds(lib, addr, n: int, threads: int, quoted: bool) -> list:
    """Newline-aligned byte cut points (per-process span logic from
    dparse._byte_assignments, applied intra-host: even byte cuts, each
    aligned forward to the next line start).  When the buffer holds
    quotes, a cut whose quote-count prefix parity is ODD sits inside a
    quoted field (the "" escape preserves parity) — merge it into the
    previous range.  Benign quoting (no embedded newlines) keeps every
    cut, so writer-quoted files still tokenize in parallel."""
    bounds = [0]
    for t in range(1, threads):
        pos = int(lib.fastcsv_find_newline(addr, n * t // threads, n))
        pos = n if pos < 0 else pos + 1
        if pos > bounds[-1]:
            bounds.append(pos)
    bounds.append(n)
    if quoted and len(bounds) > 2:
        safe = [0]
        parity = 0
        for k in range(1, len(bounds) - 1):
            parity += int(lib.fastcsv_count_quotes(
                addr, bounds[k - 1], bounds[k]))
            if parity % 2 == 0:
                safe.append(bounds[k])
        safe.append(n)
        bounds = safe
    return bounds


def range_plan(view, sep: str = ",", threads: Optional[int] = None):
    """The ranged-parse plan for a CSV body WITHOUT tokenizing it:
    ``[(byte_lo, byte_hi, row_lo, rows)]`` newline-aligned, quote-parity
    safe ranges with cumulative row bases.  The streaming ingest plane
    plans landings and lineage stamps from this before any range parses
    (``parse_view`` executes the same plan).  ``rows`` counts lines —
    an upper bound when blank lines are present; consumers must check
    it against the tokenizer's actual row count.  None when the native
    library is unavailable or the buffer doesn't fit its fast path."""
    lib = load()
    if lib is None:
        return None
    view = _as_view(view)
    n = len(view)
    if n == 0 or n > (1 << 31) - 16:
        return None
    addr = view.ctypes.data
    has_quotes = ctypes.c_int(0)
    lib.fastcsv_count_lines(addr, 0, n, ctypes.byref(has_quotes))
    if threads is None:
        threads = int(os.environ.get("H2O3_PARSE_THREADS", 0)) \
            or min(16, os.cpu_count() or 1)
    range_min = int(os.environ.get("H2O3_PARSE_RANGE_MIN", 1 << 22))
    if threads <= 1 or n < range_min:
        bounds = [0, n]
    else:
        bounds = _range_bounds(lib, addr, n, threads,
                               bool(has_quotes.value))
    ranges = [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)
              if bounds[i + 1] > bounds[i]]
    counts = [int(lib.fastcsv_count_lines(addr, a, b, None))
              for a, b in ranges]
    counts[-1] += 0 if view[-1] == 0x0A else 1
    plan, base = [], 0
    for (a, b), c in zip(ranges, counts):
        plan.append((a, b, base, c))
        base += c
    return plan


def parse_view(view, sep: str = ",", ncols: Optional[int] = None,
               threads: Optional[int] = None,
               on_range: Optional[Callable] = None,
               stats: Optional[dict] = None):
    """Tokenize a CSV byte view natively, multi-threaded when safe.

    ``view`` is a contiguous 1-D uint8 array — over ``bytes`` or an mmap,
    so no full-file copy is ever made.  Quote-free buffers split at
    newline boundaries into per-thread byte ranges parsed concurrently
    (ctypes releases the GIL) — the MultiFileParseTask chunk layout
    (ParseDataset.java:688) on one host.  A buffer containing any
    double-quote parses single-threaded: quoted cells may hide newlines,
    so ranges cannot be aligned safely.

    ``on_range(row_lo, nrows, values_T, flags_T)`` fires on the calling
    thread as each range's tokenization completes (in completion order),
    with zero-copy row-major views of that range's rows — callers use it
    to start device transfers of early ranges while later ranges still
    tokenize.  Ranges whose callbacks already fired are never invalidated:
    a misaligned range (over-wide row mid-buffer) aborts the whole parse
    (returns None) and callers fall back to the strict engines.

    Returns (values [rows, ncols] f64 with NaN for non-numeric, flags
    [rows, ncols] uint8 text markers, offsets [rows, ncols, 2] byte
    ranges, consumed bytes) — or None when the native library is
    unavailable (callers fall back to the portable parser).
    """
    lib = load()
    if lib is None:
        return None
    view = _as_view(view)
    n = len(view)
    if n > (1 << 31) - 16:               # int32 offsets: pre-split or defer
        return None
    addr = view.ctypes.data
    sepc = sep.encode()[0:1]
    if ncols is None:
        ncols = int(lib.fastcsv_ncols(addr, n, sepc))
    import time as _time
    t0 = _time.perf_counter()
    has_quotes = ctypes.c_int(0)
    total_lines = int(lib.fastcsv_count_lines(addr, 0, n,
                                              ctypes.byref(has_quotes)))
    t_scan = _time.perf_counter() - t0
    max_rows = max(total_lines + 2, 4)
    # np.empty everywhere: every returned row slot is written by the
    # tokenizer (missing trailing columns included), and zero-filling
    # ~2.6x the input volume costs real first-touch page time at scale
    values = np.empty(ncols * max_rows, np.float64)
    flags = np.empty(ncols * max_rows, np.uint8)
    offsets = np.empty(ncols * max_rows * 2, np.int32)
    vp = values.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    fp = flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    op = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    V = values.reshape(ncols, max_rows)
    F = flags.reshape(ncols, max_rows)
    O = offsets.reshape(ncols, max_rows, 2)

    if threads is None:
        threads = int(os.environ.get("H2O3_PARSE_THREADS", 0)) \
            or min(16, os.cpu_count() or 1)
    # buffers below this size take the single-range path (pool overhead
    # dominates); tests shrink it to force ranged parsing on tiny files
    range_min = int(os.environ.get("H2O3_PARSE_RANGE_MIN", 1 << 22))
    t0 = _time.perf_counter()
    if threads <= 1 or n < range_min:
        consumed = ctypes.c_longlong(0)
        rows = int(lib.fastcsv_parse_range(
            addr, 0, n, sepc, ncols, max_rows, 0, max_rows, vp, fp, op,
            ctypes.byref(consumed)))
        keep = [(0, rows)]
        tail = int(consumed.value)
        if on_range is not None and rows > 0:
            on_range(0, rows, V.T[:rows], F.T[:rows])
    else:
        bounds = _range_bounds(lib, addr, n, threads,
                               bool(has_quotes.value))
        ranges = [(bounds[i], bounds[i + 1])
                  for i in range(len(bounds) - 1)
                  if bounds[i + 1] > bounds[i]]
        # row_base per range = cumulative newline counts (upper bound:
        # blank lines produce gaps, compacted below)
        counts = [int(lib.fastcsv_count_lines(addr, a, b, None))
                  for a, b in ranges]
        counts[-1] += 0 if view[-1] == 0x0A else 1
        bases = np.concatenate([[0], np.cumsum(counts)])[:-1]

        import concurrent.futures

        def work(k):
            a, b = ranges[k]
            consumed = ctypes.c_longlong(0)
            got = int(lib.fastcsv_parse_range(
                addr, a, b, sepc, ncols, max_rows, int(bases[k]),
                int(bases[k]) + counts[k], vp, fp, op,
                ctypes.byref(consumed)))
            return k, got, int(consumed.value)

        results = [None] * len(ranges)
        with concurrent.futures.ThreadPoolExecutor(len(ranges)) as ex:
            futs = [ex.submit(work, k) for k in range(len(ranges))]
            for fut in concurrent.futures.as_completed(futs):
                k, got, consumed_k = fut.result()
                results[k] = (got, consumed_k)
                if on_range is not None and got > 0:
                    # a later-discovered misaligned range aborts the whole
                    # parse (None below), so eagerly-fired chunks can never
                    # leak into a successful result they don't belong to
                    b0 = int(bases[k])
                    on_range(b0, got, V.T[b0:b0 + got], F.T[b0:b0 + got])
        keep = [(int(bases[k]), results[k][0]) for k in range(len(ranges))]
        # a range that stopped early (over-wide row) invalidates the
        # later ranges' row_bases — fall back to the strict engines
        for k in range(len(ranges) - 1):
            if results[k][1] != ranges[k][1]:
                return None
        tail = results[-1][1]
    if stats is not None:
        stats["scan_s"] = round(t_scan, 4)
        stats["tokenize_s"] = round(_time.perf_counter() - t0, 4)
        stats["ranges"] = len(keep)
        stats["has_quotes"] = bool(has_quotes.value)
    keep = [(b, c) for b, c in keep if c > 0]
    contiguous = all(keep[i][0] + keep[i][1] == keep[i + 1][0]
                     for i in range(len(keep) - 1))
    if keep and contiguous:
        # the common case (no blank lines): strided VIEWS, no gather copy
        a = keep[0][0]
        b = keep[-1][0] + keep[-1][1]
        return V.T[a:b], F.T[a:b], O.transpose(1, 0, 2)[a:b], tail
    rows_idx = np.concatenate([np.arange(b, b + c) for b, c in keep]) \
        if keep else np.zeros(0, np.int64)
    return (V.T[rows_idx], F.T[rows_idx],
            O.transpose(1, 0, 2)[rows_idx], tail)


def parse_bytes(data: bytes, sep: str = ",", ncols: Optional[int] = None,
                threads: Optional[int] = None):
    """Tokenize a CSV byte buffer natively — ``parse_view`` over bytes.

    Kept as the stable entry point for callers holding materialized
    buffers (dparse spans, REST PostFile bodies); the mmap'd file path
    goes straight to ``parse_view`` with no copy.
    """
    return parse_view(_as_view(data), sep, ncols=ncols, threads=threads)
