"""Native runtime components (C++ via ctypes — no pybind11 in-image).

``fastcsv`` is the byte-level CSV tokenizer for the parse hot path (the
water/parser/CsvParser fast-path analog): numeric cells go straight into
column-major double buffers with no per-cell Python objects; text cells
are flagged with byte ranges for the host-side categorical/string pass.

The shared object builds on first use with the in-image g++ (cached next
to the source); every caller must handle ``load() is None`` and fall back
to the portable tokenizer — builds can be unavailable in stripped
deployment images.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_fastcsv.so")


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def load():
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fastcsv_parse.restype = ctypes.c_longlong
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
            ctypes.c_int, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_longlong)]
        lib.fastcsv_ncols.restype = ctypes.c_int
        lib.fastcsv_ncols.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                      ctypes.c_char]
        _lib = lib
        return _lib


def parse_bytes(data: bytes, sep: str = ",", ncols: Optional[int] = None):
    """Tokenize a CSV byte buffer natively.

    Returns (values [rows, ncols] f64 with NaN for non-numeric, flags
    [rows, ncols] uint8 text markers, offsets [rows, ncols, 2] byte
    ranges, consumed bytes) — or None when the native library is
    unavailable (callers fall back to the portable parser).
    """
    lib = load()
    if lib is None:
        return None
    n = len(data)
    if n > (1 << 31) - 16:               # int32 offsets: pre-split or defer
        return None
    if ncols is None:
        ncols = int(lib.fastcsv_ncols(data, n, sep.encode()[0:1]))
    max_rows = max(data.count(b"\n") + 2, 4)
    values = np.empty(ncols * max_rows, np.float64)
    flags = np.zeros(ncols * max_rows, np.uint8)
    offsets = np.zeros(ncols * max_rows * 2, np.int32)
    consumed = ctypes.c_longlong(0)
    rows = lib.fastcsv_parse(
        data, n, sep.encode()[0:1], ncols, max_rows,
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(consumed))
    rows = int(rows)
    vals = values.reshape(ncols, max_rows).T[:rows]
    flg = flags.reshape(ncols, max_rows).T[:rows]
    offs = offsets.reshape(ncols, max_rows, 2).transpose(1, 0, 2)[:rows]
    return vals, flg, offs, int(consumed.value)
