// fastcsv: native CSV tokenizer for the parse hot path.
//
// Reference: the parse fast path in water/parser/CsvParser.java — a
// byte-level tokenizer over raw chunks that never materializes Java
// Strings for numeric cells.  This is its native analog for the TPU
// framework's coordinator: one pass over the buffer, quote-aware, writing
// numeric cells straight into a preallocated double column-major matrix
// and flagging cells that need host-side (string/categorical) handling.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>

extern "C" {

// Tokenize up to max_rows lines of `buf` (len bytes) with `ncols` columns.
// Outputs:
//   values  [max_rows * ncols] column-major doubles (NaN when not numeric)
//   flags   [max_rows * ncols] uint8: 0 = numeric/empty, 1 = text cell
//   offsets [max_rows * ncols * 2] int32 (start, end) byte ranges per cell
//           (callers must keep buffers under 2 GB or pre-split them)
// Returns number of complete rows parsed; *consumed is set to the number
// of bytes consumed (ending on a row boundary).  A row WIDER than ncols
// stops the parse at that row (consumed < len) so callers fail over to a
// stricter engine instead of silently truncating cells.
long long fastcsv_parse(const char* buf, long long len, char sep,
                        int ncols, long long max_rows,
                        double* values, uint8_t* flags,
                        int32_t* offsets, long long* consumed) {
    long long row = 0;
    long long i = 0;
    while (row < max_rows && i < len) {
        long long line_start = i;
        int col = 0;
        bool in_quotes = false;
        long long cell_start = i;
        bool saw_any = false;
        bool complete = false;
        while (i <= len) {
            char c = (i < len) ? buf[i] : '\n';
            if (in_quotes) {
                if (c == '"') {
                    if (i + 1 < len && buf[i + 1] == '"') { i += 2; continue; }
                    in_quotes = false;
                }
                ++i;
                continue;
            }
            if (c == '"') { in_quotes = true; saw_any = true; ++i; continue; }
            if (c == sep || c == '\n' || c == '\r') {
                if (col < ncols) {
                    long long s = cell_start, e = i;
                    // trim spaces and symmetric quotes
                    while (s < e && (buf[s] == ' ' || buf[s] == '\t')) ++s;
                    while (e > s && (buf[e-1] == ' ' || buf[e-1] == '\t')) --e;
                    if (e - s >= 2 && buf[s] == '"' && buf[e-1] == '"') {
                        ++s; --e;
                    }
                    long long idx = (long long)col * max_rows + row;
                    offsets[2 * idx] = (int32_t)s;
                    offsets[2 * idx + 1] = (int32_t)e;
                    if (s == e) {                      // empty -> NA
                        values[idx] = NAN;
                        flags[idx] = 0;
                    } else {
                        char* endp = nullptr;
                        // strtod needs NUL-terminated input; copy small cell
                        char tmp[64];
                        long long m = e - s;
                        if (m < 63) {
                            memcpy(tmp, buf + s, m);
                            tmp[m] = 0;
                            double v = strtod(tmp, &endp);
                            if (endp == tmp + m) {
                                values[idx] = v;
                                flags[idx] = 0;
                            } else {
                                values[idx] = NAN;
                                flags[idx] = 1;        // text cell
                            }
                        } else {
                            values[idx] = NAN;
                            flags[idx] = 1;
                        }
                    }
                }
                ++col;
                if (c == sep) { ++i; cell_start = i; continue; }
                // end of line (real newline, or the synthetic one at EOF
                // that closes a final unterminated row)
                if (i < len) {
                    if (c == '\r' && i + 1 < len && buf[i + 1] == '\n') ++i;
                    ++i;
                } else {
                    i = len;
                }
                complete = true;
                break;
            }
            saw_any = true;
            ++i;
        }
        if (!complete || col > ncols) {   // mid-quote EOF or over-wide row
            i = line_start;
            break;
        }
        if (!saw_any && col <= 1) continue;             // blank line
        // short rows: pad remaining cells with NA
        for (int c2 = col; c2 < ncols; ++c2) {
            long long idx = (long long)c2 * max_rows + row;
            values[idx] = NAN;
            flags[idx] = 0;
            offsets[2 * idx] = offsets[2 * idx + 1] = 0;
        }
        ++row;
    }
    *consumed = (i > len) ? len : i;
    return row;
}

// Count columns of the first line (quote-aware) — ParseSetup's guess.
int fastcsv_ncols(const char* buf, long long len, char sep) {
    int cols = 1;
    bool in_quotes = false;
    for (long long i = 0; i < len; ++i) {
        char c = buf[i];
        if (in_quotes) {
            if (c == '"') in_quotes = false;
            continue;
        }
        if (c == '"') in_quotes = true;
        else if (c == sep) ++cols;
        else if (c == '\n' || c == '\r') break;
    }
    return cols;
}

}  // extern "C"
