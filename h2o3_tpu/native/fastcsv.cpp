// fastcsv: native CSV tokenizer for the parse hot path.
//
// Reference: the parse fast path in water/parser/CsvParser.java — a
// byte-level tokenizer over raw chunks that never materializes Java
// Strings for numeric cells — and the distributed layout of
// MultiFileParseTask (ParseDataset.java:688): raw byte ranges parsed
// independently.  This is the native analog for the TPU framework's
// coordinator: one pass over the buffer, quote-aware, writing numeric
// cells straight into a preallocated double column-major matrix and
// flagging cells that need host-side (string/categorical) handling.
// `fastcsv_parse_range` takes (start, row_base) so quote-free buffers
// tokenize in parallel threads over newline-aligned byte ranges.
//
// Number parsing: a hand-rolled digits/exponent scanner (~20 ns/cell)
// for the forms that dominate real CSVs; anything else (inf, nan, hex
// floats, >18 significant digits) falls back to strtod for exactness.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>

namespace {

const double kPow10[] = {
    1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11, 1e12,
    1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// Parse [s, e) as a double.  Returns false when the cell is not a plain
// decimal/scientific number (caller flags it as text or retries strtod).
inline bool parse_num(const char* s, const char* e, double* out) {
    if (s == e) return false;
    bool neg = false;
    if (*s == '+' || *s == '-') { neg = *s == '-'; ++s; if (s == e) return false; }
    uint64_t mant = 0;
    int digits = 0, frac = 0;
    bool any = false;
    while (s < e && *s >= '0' && *s <= '9') {
        if (digits < 18) { mant = mant * 10 + (*s - '0'); ++digits; }
        else return false;                       // too long: strtod path
        any = true; ++s;
    }
    if (s < e && *s == '.') {
        ++s;
        while (s < e && *s >= '0' && *s <= '9') {
            if (digits < 18) { mant = mant * 10 + (*s - '0'); ++digits; ++frac; }
            else return false;
            any = true; ++s;
        }
    }
    if (!any) return false;
    int exp10 = -frac;
    if (s < e && (*s == 'e' || *s == 'E')) {
        ++s;
        bool eneg = false;
        if (s < e && (*s == '+' || *s == '-')) { eneg = *s == '-'; ++s; }
        if (s == e) return false;
        int ev = 0;
        while (s < e && *s >= '0' && *s <= '9') {
            ev = ev * 10 + (*s - '0');
            if (ev > 400) return false;
            ++s;
        }
        exp10 += eneg ? -ev : ev;
    }
    if (s != e) return false;
    double v = (double)mant;
    // one multiply/divide by an exact power of ten keeps the result
    // correctly rounded for |exp10| <= 22 and mant < 2^53 (Clinger)
    if (exp10 > 0) {
        if (exp10 > 22) return false;
        v *= kPow10[exp10];
    } else if (exp10 < 0) {
        if (exp10 < -22) return false;
        v /= kPow10[-exp10];
    }
    *out = neg ? -v : v;
    return true;
}

}  // namespace

extern "C" {

// Tokenize rows of buf[start, end) with `ncols` columns, writing row
// row_base onward.  values/flags are column-major with stride max_rows;
// offsets hold absolute (into buf) byte ranges per cell.  Returns rows
// parsed; *consumed = absolute end position (on a row boundary).
long long fastcsv_parse_range(const char* buf, long long start,
                              long long end, char sep, int ncols,
                              long long max_rows, long long row_base,
                              long long row_cap,
                              double* values, uint8_t* flags,
                              int32_t* offsets, long long* consumed) {
    long long row = row_base;
    long long i = start;
    long long len = end;
    while (row < row_cap && i < len) {
        long long line_start = i;
        int col = 0;
        bool in_quotes = false;
        long long cell_start = i;
        bool saw_any = false;
        bool complete = false;
        while (i <= len) {
            char c = (i < len) ? buf[i] : '\n';
            if (in_quotes) {
                if (c == '"') {
                    if (i + 1 < len && buf[i + 1] == '"') { i += 2; continue; }
                    in_quotes = false;
                }
                ++i;
                continue;
            }
            if (c == '"') { in_quotes = true; saw_any = true; ++i; continue; }
            if (c == sep || c == '\n' || c == '\r') {
                if (col < ncols) {
                    long long s = cell_start, e = i;
                    while (s < e && (buf[s] == ' ' || buf[s] == '\t')) ++s;
                    while (e > s && (buf[e-1] == ' ' || buf[e-1] == '\t')) --e;
                    if (e - s >= 2 && buf[s] == '"' && buf[e-1] == '"') {
                        ++s; --e;
                    }
                    long long idx = (long long)col * max_rows + row;
                    offsets[2 * idx] = (int32_t)s;
                    offsets[2 * idx + 1] = (int32_t)e;
                    if (s == e) {                      // empty -> NA
                        values[idx] = NAN;
                        flags[idx] = 0;
                    } else {
                        double v;
                        if (parse_num(buf + s, buf + e, &v)) {
                            values[idx] = v;
                            flags[idx] = 0;
                        } else {
                            // exotic forms (inf/nan/hex/long mantissas):
                            // strtod on a NUL-terminated copy
                            char tmp[64];
                            long long m = e - s;
                            char* endp = nullptr;
                            if (m < 63) {
                                memcpy(tmp, buf + s, m);
                                tmp[m] = 0;
                                double sv = strtod(tmp, &endp);
                                if (endp == tmp + m) {
                                    values[idx] = sv;
                                    flags[idx] = 0;
                                } else {
                                    values[idx] = NAN;
                                    flags[idx] = 1;    // text cell
                                }
                            } else {
                                values[idx] = NAN;
                                flags[idx] = 1;
                            }
                        }
                    }
                }
                ++col;
                if (c == sep) { ++i; cell_start = i; continue; }
                if (i < len) {
                    if (c == '\r' && i + 1 < len && buf[i + 1] == '\n') ++i;
                    ++i;
                } else {
                    i = len;
                }
                complete = true;
                break;
            }
            saw_any = true;
            ++i;
        }
        if (!complete || col > ncols) {   // mid-quote EOF or over-wide row
            i = line_start;
            break;
        }
        if (!saw_any && col <= 1) continue;             // blank line
        for (int c2 = col; c2 < ncols; ++c2) {
            long long idx = (long long)c2 * max_rows + row;
            values[idx] = NAN;
            flags[idx] = 0;
            offsets[2 * idx] = offsets[2 * idx + 1] = 0;
        }
        ++row;
    }
    *consumed = (i > len) ? len : i;
    return row - row_base;
}

// Single-range compatibility entry (the original ABI).
long long fastcsv_parse(const char* buf, long long len, char sep,
                        int ncols, long long max_rows,
                        double* values, uint8_t* flags,
                        int32_t* offsets, long long* consumed) {
    return fastcsv_parse_range(buf, 0, len, sep, ncols, max_rows, 0,
                               max_rows, values, flags, offsets, consumed);
}

// Count columns of the first line (quote-aware) — ParseSetup's guess.
int fastcsv_ncols(const char* buf, long long len, char sep) {
    int cols = 1;
    bool in_quotes = false;
    for (long long i = 0; i < len; ++i) {
        char c = buf[i];
        if (in_quotes) {
            if (c == '"') in_quotes = false;
            continue;
        }
        if (c == '"') in_quotes = true;
        else if (c == sep) ++cols;
        else if (c == '\n' || c == '\r') break;
    }
    return cols;
}

// memchr-rate scan: newline count in [start, end) and whether any quote
// appears anywhere (quotes may hide newlines -> single-thread parse).
long long fastcsv_count_lines(const char* buf, long long start,
                              long long end, int* has_quotes) {
    long long n = 0;
    const char* p = buf + start;
    const char* stop = buf + end;
    if (has_quotes) {
        *has_quotes = memchr(p, '"', (size_t)(stop - p)) != nullptr;
    }
    while (p < stop) {
        const char* q = (const char*)memchr(p, '\n', (size_t)(stop - p));
        if (!q) break;
        ++n;
        p = q + 1;
    }
    return n;
}

}  // extern "C"
