// fastcsv: native CSV tokenizer for the parse hot path.
//
// Reference: the parse fast path in water/parser/CsvParser.java — a
// byte-level tokenizer over raw chunks that never materializes Java
// Strings for numeric cells — and the distributed layout of
// MultiFileParseTask (ParseDataset.java:688): raw byte ranges parsed
// independently.  This is the native analog for the TPU framework's
// coordinator: one pass over the buffer, quote-aware, writing numeric
// cells straight into a preallocated double column-major matrix and
// flagging cells that need host-side (string/categorical) handling.
// `fastcsv_parse_range` takes (start, row_base) so newline-aligned byte
// ranges tokenize in parallel threads; range boundaries inside quoted
// fields are rejected host-side by quote-parity (`fastcsv_count_quotes`).
//
// Row tokenization is a fused fast path: the numeric scan IS the
// delimiter scan for plain-number cells, and simple quoted cells
// ("payload" followed by a delimiter — the pyarrow/excel writer shape)
// jump straight to their closing quote via memchr.  Any hairy row
// (escaped "" quotes, mid-cell quotes, quoted newlines) restarts under
// the exact quote-state machine, so the fast path never changes results.
//
// Number parsing: a hand-rolled digits/exponent scanner (~20 ns/cell)
// for the forms that dominate real CSVs; anything else (inf, nan, hex
// floats, >18 significant digits) falls back to strtod for exactness.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>

namespace {

const double kPow10[] = {
    1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11, 1e12,
    1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// Scan a plain decimal/scientific number starting at s.  Returns the first
// unconsumed position, or nullptr when the prefix is not a plain number
// (caller falls back to the delimiter scan / strtod / text flag).
inline const char* scan_num(const char* s, const char* e, double* out) {
    const char* p = s;
    bool neg = false;
    if (p < e && (*p == '+' || *p == '-')) { neg = *p == '-'; ++p; }
    uint64_t mant = 0;
    int digits = 0, frac = 0;
    bool any = false;
    while (p < e && *p >= '0' && *p <= '9') {
        if (digits >= 18) return nullptr;        // too long: strtod path
        mant = mant * 10 + (*p - '0'); ++digits;
        any = true; ++p;
    }
    if (p < e && *p == '.') {
        ++p;
        while (p < e && *p >= '0' && *p <= '9') {
            if (digits >= 18) return nullptr;
            mant = mant * 10 + (*p - '0'); ++digits; ++frac;
            any = true; ++p;
        }
    }
    if (!any) return nullptr;
    int exp10 = -frac;
    if (p < e && (*p == 'e' || *p == 'E')) {
        ++p;
        bool eneg = false;
        if (p < e && (*p == '+' || *p == '-')) { eneg = *p == '-'; ++p; }
        const char* d0 = p;
        int ev = 0;
        while (p < e && *p >= '0' && *p <= '9') {
            ev = ev * 10 + (*p - '0');
            if (ev > 400) return nullptr;
            ++p;
        }
        if (p == d0) return nullptr;
        exp10 += eneg ? -ev : ev;
    }
    double v = (double)mant;
    // one multiply/divide by an exact power of ten keeps the result
    // correctly rounded for |exp10| <= 22 and mant < 2^53 (Clinger)
    if (exp10 > 0) {
        if (exp10 > 22) return nullptr;
        v *= kPow10[exp10];
    } else if (exp10 < 0) {
        if (exp10 < -22) return nullptr;
        v /= kPow10[-exp10];
    }
    *out = neg ? -v : v;
    return p;
}

// Parse [s, e) as a double: the whole cell must be one plain number.
inline bool parse_num(const char* s, const char* e, double* out) {
    const char* p = scan_num(s, e, out);
    return p == e && s != e;
}

// Store one tokenized cell (trims already applied; [s, e) is the
// payload, idx the column-major slot).
inline void store_cell(const char* buf, long long s, long long e,
                       long long idx, double* values, uint8_t* flags,
                       int32_t* offsets) {
    offsets[2 * idx] = (int32_t)s;
    offsets[2 * idx + 1] = (int32_t)e;
    if (s == e) {                          // empty -> NA
        values[idx] = NAN;
        flags[idx] = 0;
        return;
    }
    double v;
    if (parse_num(buf + s, buf + e, &v)) {
        values[idx] = v;
        flags[idx] = 0;
        return;
    }
    // exotic forms (inf/nan/hex/long mantissas): strtod on a copy
    char tmp[64];
    long long m = e - s;
    char* endp = nullptr;
    if (m < 63) {
        memcpy(tmp, buf + s, m);
        tmp[m] = 0;
        double sv = strtod(tmp, &endp);
        if (endp == tmp + m) {
            values[idx] = sv;
            flags[idx] = 0;
            return;
        }
    }
    values[idx] = NAN;
    flags[idx] = 1;                        // text cell
}

}  // namespace

extern "C" {

// Tokenize rows of buf[start, end) with `ncols` columns, writing row
// row_base onward.  values/flags are column-major with stride max_rows;
// offsets hold absolute (into buf) byte ranges per cell.  Returns rows
// parsed; *consumed = absolute end position (on a row boundary).
long long fastcsv_parse_range(const char* buf, long long start,
                              long long end, char sep, int ncols,
                              long long max_rows, long long row_base,
                              long long row_cap,
                              double* values, uint8_t* flags,
                              int32_t* offsets, long long* consumed) {
    long long row = row_base;
    long long i = start;
    const long long len = end;
    while (row < row_cap && i < len) {
        long long line_start = i;
        int col = 0;
        bool saw_any = false;
        bool complete = false;

        // ---- fused fast row: numeric scan doubles as delimiter scan;
        //      simple quoted cells jump to their closing quote
        for (;;) {
            long long cell_start = i;
            while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
            long long s = i;
            long long e = -1;
            double v = 0.0;
            bool numeric = false;
            if (i < len && buf[i] == '"') {
                long long qs = i + 1;
                const void* qp = memchr(buf + qs, '"', (size_t)(len - qs));
                if (qp == nullptr) goto careful_row;     // mid-quote EOF
                long long q = (const char*)qp - buf;
                if (q + 1 < len && buf[q + 1] == '"') goto careful_row;
                long long t = q + 1;
                while (t < len && (buf[t] == ' ' || buf[t] == '\t')) ++t;
                char c2 = (t < len) ? buf[t] : '\n';
                if (c2 != sep && c2 != '\n' && c2 != '\r')
                    goto careful_row;                    // "x"y junk cell
                s = qs;
                e = q;
                i = t;
            } else {
                const char* np = scan_num(buf + i, buf + len, &v);
                if (np != nullptr && np != buf + i) {
                    long long q = np - buf;
                    long long t = q;
                    while (t < len && (buf[t] == ' ' || buf[t] == '\t'))
                        ++t;
                    char c2 = (t < len) ? buf[t] : '\n';
                    if (c2 == sep || c2 == '\n' || c2 == '\r') {
                        numeric = true;
                        e = q;
                        i = t;
                    }
                }
                if (!numeric) {
                    long long t = i;
                    while (t < len && buf[t] != sep && buf[t] != '\n'
                           && buf[t] != '\r') {
                        if (buf[t] == '"') goto careful_row;  // mid-cell "
                        ++t;
                    }
                    e = t;
                    while (e > s && (buf[e - 1] == ' '
                                     || buf[e - 1] == '\t')) --e;
                    i = t;
                }
            }
            if (col < ncols) {
                long long idx = (long long)col * max_rows + row;
                if (numeric) {
                    offsets[2 * idx] = (int32_t)s;
                    offsets[2 * idx + 1] = (int32_t)e;
                    values[idx] = v;
                    flags[idx] = 0;
                } else {
                    store_cell(buf, s, e, idx, values, flags, offsets);
                }
            }
            if (i > cell_start) saw_any = true;
            ++col;
            {
                char c = (i < len) ? buf[i] : '\n';
                if (i < len && c == sep) { ++i; continue; }
                if (i < len) {
                    if (c == '\r' && i + 1 < len && buf[i + 1] == '\n') ++i;
                    ++i;
                }
                complete = true;
            }
            break;
        }
        goto row_done;

careful_row:
        // ---- exact quote-state machine (escaped quotes, quoted
        //      newlines, junk cells); restarts the whole row
        i = line_start;
        col = 0;
        saw_any = false;
        complete = false;
        {
            bool in_quotes = false;
            long long cell_start = i;
            while (i <= len) {
                char c = (i < len) ? buf[i] : '\n';
                if (in_quotes) {
                    if (c == '"') {
                        if (i + 1 < len && buf[i + 1] == '"') {
                            i += 2;
                            continue;
                        }
                        in_quotes = false;
                    }
                    ++i;
                    continue;
                }
                if (c == '"') {
                    in_quotes = true;
                    saw_any = true;
                    ++i;
                    continue;
                }
                if (c == sep || c == '\n' || c == '\r') {
                    if (col < ncols) {
                        long long s = cell_start, e = i;
                        while (s < e && (buf[s] == ' ' || buf[s] == '\t'))
                            ++s;
                        while (e > s && (buf[e - 1] == ' '
                                         || buf[e - 1] == '\t')) --e;
                        if (e - s >= 2 && buf[s] == '"'
                            && buf[e - 1] == '"') {
                            ++s; --e;
                        }
                        store_cell(buf, s, e,
                                   (long long)col * max_rows + row,
                                   values, flags, offsets);
                    }
                    ++col;
                    if (c == sep) { ++i; cell_start = i; continue; }
                    if (i < len) {
                        if (c == '\r' && i + 1 < len && buf[i + 1] == '\n')
                            ++i;
                        ++i;
                    } else {
                        i = len;
                    }
                    complete = true;
                    break;
                }
                saw_any = true;
                ++i;
            }
        }

row_done:
        if (!complete || col > ncols) {   // mid-quote EOF or over-wide row
            i = line_start;
            break;
        }
        if (!saw_any && col <= 1) continue;             // blank line
        for (int c2 = col; c2 < ncols; ++c2) {
            long long idx = (long long)c2 * max_rows + row;
            values[idx] = NAN;
            flags[idx] = 0;
            offsets[2 * idx] = offsets[2 * idx + 1] = 0;
        }
        ++row;
    }
    *consumed = (i > len) ? len : i;
    return row - row_base;
}

// Single-range compatibility entry (the original ABI).
long long fastcsv_parse(const char* buf, long long len, char sep,
                        int ncols, long long max_rows,
                        double* values, uint8_t* flags,
                        int32_t* offsets, long long* consumed) {
    return fastcsv_parse_range(buf, 0, len, sep, ncols, max_rows, 0,
                               max_rows, values, flags, offsets, consumed);
}

// Count columns of the first line (quote-aware) — ParseSetup's guess.
int fastcsv_ncols(const char* buf, long long len, char sep) {
    int cols = 1;
    bool in_quotes = false;
    for (long long i = 0; i < len; ++i) {
        char c = buf[i];
        if (in_quotes) {
            if (c == '"') in_quotes = false;
            continue;
        }
        if (c == '"') in_quotes = true;
        else if (c == sep) ++cols;
        else if (c == '\n' || c == '\r') break;
    }
    return cols;
}

// Next newline at/after `start` (before `end`), or -1 — range alignment
// for the parallel fan-out without materializing bytes from an mmap.
long long fastcsv_find_newline(const char* buf, long long start,
                               long long end) {
    if (end <= start) return -1;
    const void* p = memchr(buf + start, '\n', (size_t)(end - start));
    return p ? (long long)((const char*)p - buf) : -1;
}

// Quote count in [start, end) at memchr rate.  A byte position whose
// cumulative quote count is ODD lies inside a quoted field (the ""
// escape toggles twice, preserving parity) — the host uses prefix
// parity to reject range cuts that would split a quoted newline.
long long fastcsv_count_quotes(const char* buf, long long start,
                               long long end) {
    long long nq = 0;
    const char* p = buf + start;
    const char* stop = buf + end;
    while (p < stop) {
        const char* q = (const char*)memchr(p, '"', (size_t)(stop - p));
        if (!q) break;
        ++nq;
        p = q + 1;
    }
    return nq;
}

// Gather n variable-length cells [starts[i], ends[i]) into a fixed-width
// row-major matrix (NUL-padded) — the host-side text pass then factorizes
// the whole column with vectorized numpy on the |S width| view instead of
// a per-cell Python loop.
void fastcsv_gather_cells(const char* buf, const int32_t* starts,
                          const int32_t* ends, long long n, int width,
                          char* out) {
    for (long long i = 0; i < n; ++i) {
        long long m = (long long)ends[i] - starts[i];
        if (m < 0) m = 0;
        if (m > width) m = width;
        char* dst = out + i * (long long)width;
        if (m > 0) memcpy(dst, buf + starts[i], (size_t)m);
        if (m < width) memset(dst + m, 0, (size_t)(width - m));
    }
}

// memchr-rate scan: newline count in [start, end) and whether any quote
// appears anywhere (quotes may hide newlines -> range cuts need the
// quote-parity check; see fastcsv_count_quotes).
long long fastcsv_count_lines(const char* buf, long long start,
                              long long end, int* has_quotes) {
    long long n = 0;
    const char* p = buf + start;
    const char* stop = buf + end;
    if (has_quotes) {
        *has_quotes = memchr(p, '"', (size_t)(stop - p)) != nullptr;
    }
    while (p < stop) {
        const char* q = (const char*)memchr(p, '\n', (size_t)(stop - p));
        if (!q) break;
        ++n;
        p = q + 1;
    }
    return n;
}

}  // extern "C"
