"""Hive table import: SQL mode and direct-metadata mode.

Reference: ``h2o-hive/src/main/java/water/hive/`` —
``HiveTableImporterImpl.java`` (JDBC SELECT import),
``DirectHiveMetadata.java`` / ``JdbcHiveMetadata.java`` (read table
metadata — storage location, format, columns, partitions — then ingest the
underlying files directly, skipping the HiveServer row path), and
``PartitionFrameJoiner.java`` (partition-key values appended as constant
columns per partition).

TPU-native redesign: no thrift client and no JDBC driver manager — both
modes speak plain DB-API 2.0.  SQL mode takes any DB-API connection to a
HiveServer (pyhive/impyla, user-supplied).  Direct mode takes a DB-API
connection to the **metastore's backing database** (the DBS/TBLS/SDS/
COLUMNS_V2/PARTITIONS tables every HMS maintains) — the same metadata
DirectHiveMetadata fetches over thrift — and then imports each storage
location through the persist layer (gcs://, s3://, hdfs://, file paths),
so data never flows through a Hive daemon.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def _bt(name: str) -> str:
    """Backtick-quote a Hive identifier (HiveTableImporterImpl style)."""
    if not name.replace("_", "").replace(".", "").isalnum():
        raise ValueError(f"illegal hive identifier {name!r}")
    return ".".join(f"`{part}`" for part in name.split("."))


def import_hive_table(connection, table: str,
                      partitions: Optional[Dict[str, str]] = None,
                      destination_frame: Optional[str] = None):
    """SQL-mode import: SELECT * over a live HiveServer DB-API connection.

    ``partitions`` pushes equality predicates down (partition pruning):
    ``{"year": "2007", "month": "1"}`` -> ``WHERE `year`='2007' AND ...``.
    """
    from .sql import import_sql_select
    query = f"SELECT * FROM {_bt(table)}"
    if partitions:
        preds = []
        for k, v in partitions.items():
            sv = str(v).replace("'", "''")     # values inlined: DB-API
            preds.append(f"{_bt(k)} = '{sv}'")  # paramstyles vary per driver
        query += " WHERE " + " AND ".join(preds)
    return import_sql_select(connection, query,
                             destination_frame=destination_frame)


# ------------------------------------------------------ direct metadata mode

_TEXT_FORMATS = ("TextInputFormat",)
_PARQUET_FORMATS = ("MapredParquetInputFormat", "ParquetInputFormat")
_ORC_FORMATS = ("OrcInputFormat",)


class HiveMetastore:
    """Reads HMS metadata from its backing RDBMS over DB-API
    (DirectHiveMetadata's table/partition/column view, minus thrift)."""

    def __init__(self, conn):
        self.conn = conn

    def _all(self, query: str, args=()) -> list:
        cur = self.conn.cursor()
        try:
            try:
                cur.execute(query, args)
            except Exception:           # noqa: BLE001 — driver paramstyle
                cur.execute(query.replace("?", "%s"), args)
            return cur.fetchall()
        finally:
            cur.close()

    def table(self, table: str, database: str = "default") -> dict:
        rows = self._all(
            "SELECT t.TBL_ID, t.SD_ID, s.LOCATION, s.INPUT_FORMAT, s.CD_ID "
            "FROM TBLS t JOIN DBS d ON t.DB_ID = d.DB_ID "
            "JOIN SDS s ON t.SD_ID = s.SD_ID "
            "WHERE d.NAME = ? AND t.TBL_NAME = ?", (database, table))
        if not rows:
            raise KeyError(f"hive table {database}.{table} not found "
                           "in metastore")
        tbl_id, sd_id, location, input_format, cd_id = rows[0]
        cols = [(str(r[0]), str(r[1])) for r in self._all(
            "SELECT COLUMN_NAME, TYPE_NAME FROM COLUMNS_V2 "
            "WHERE CD_ID = ? ORDER BY INTEGER_IDX", (cd_id,))]
        pkeys = [(str(r[0]), str(r[1])) for r in self._all(
            "SELECT PKEY_NAME, PKEY_TYPE FROM PARTITION_KEYS "
            "WHERE TBL_ID = ? ORDER BY INTEGER_IDX", (tbl_id,))]
        serde = {str(r[0]): str(r[1]) for r in self._all(
            "SELECT sp.PARAM_KEY, sp.PARAM_VALUE FROM SERDE_PARAMS sp "
            "JOIN SDS s ON s.SERDE_ID = sp.SERDE_ID WHERE s.SD_ID = ?",
            (sd_id,))}
        parts = [(str(r[0]), str(r[1])) for r in self._all(
            "SELECT p.PART_NAME, s.LOCATION FROM PARTITIONS p "
            "JOIN SDS s ON p.SD_ID = s.SD_ID WHERE p.TBL_ID = ?",
            (tbl_id,))]
        return {"location": str(location), "input_format": str(input_format),
                "columns": cols, "partition_keys": pkeys,
                "serde": serde, "partitions": parts}


def _import_location(location: str, meta: dict, col_names: List[str]):
    """One storage directory -> Frame via the matching format parser."""
    import glob
    import os
    from .parse import parse_csv, parse_arrow

    fmt = meta["input_format"].rsplit(".", 1)[-1]
    path = location[7:] if location.startswith("file://") else location
    if os.path.isdir(path):
        files = sorted(f for f in glob.glob(os.path.join(path, "*"))
                       if not os.path.basename(f).startswith(
                           ("_", ".")))                  # skip _SUCCESS etc
    else:
        files = [path]
    if not files:
        raise ValueError(f"no data files under hive location {location!r}")
    if fmt in _TEXT_FORMATS:
        sep = meta["serde"].get("field.delim", "\x01")
        frames = [parse_csv(f, header=False, sep=sep, col_names=col_names)
                  for f in files]
    elif fmt in _PARQUET_FORMATS:
        frames = [parse_arrow(f, "parquet") for f in files]
    elif fmt in _ORC_FORMATS:
        frames = [parse_arrow(f, "orc") for f in files]
    else:
        raise NotImplementedError(
            f"hive input format {meta['input_format']!r} "
            "(text/parquet/orc are supported)")
    if len(frames) == 1:
        return frames[0]
    from ..rapids.ops import rbind
    return rbind(*frames)


def import_hive_metadata(metastore_conn, table: str,
                         database: str = "default",
                         destination_frame: Optional[str] = None):
    """Direct-metadata import: metastore backing DB -> storage files.

    Partitioned tables ingest every partition directory and append the
    partition-key values as constant categorical columns
    (PartitionFrameJoiner semantics); unpartitioned tables ingest the
    table location directly.
    """
    from ..runtime import dkv
    from .frame import Frame
    from .vec import Vec, T_CAT

    ms = HiveMetastore(metastore_conn)
    meta = ms.table(table, database=database)
    col_names = [c[0] for c in meta["columns"]]
    if not meta["partition_keys"]:
        fr = _import_location(meta["location"], meta, col_names)
        key = destination_frame or dkv.make_key(f"hive_{table}")
        fr.key = key
        dkv.put(key, fr)
        return fr

    pkey_names = [k[0] for k in meta["partition_keys"]]
    pieces = []
    for part_name, location in meta["partitions"]:
        fr = _import_location(location, meta, col_names)
        # PART_NAME is "k1=v1/k2=v2"; append each key as a constant column
        values = dict(kv.split("=", 1) for kv in part_name.split("/"))
        for pk in pkey_names:
            v = values.get(pk, "")
            codes = np.zeros(fr.nrows, np.int32)
            fr = fr.with_vec(pk, Vec.from_numpy(codes, T_CAT, domain=[v]))
        pieces.append(fr)
    from ..rapids.ops import rbind
    out = pieces[0] if len(pieces) == 1 else rbind(*pieces)
    key = destination_frame or dkv.make_key(f"hive_{table}")
    out.key = key
    dkv.put(key, out)
    return out
