"""Vec: one column of a distributed Frame.

Reference: ``water/fvec/Vec.java:157`` — a Vec is column metadata + an ESPC
row layout + per-chunk DKV keys, with logical types T_BAD/T_UUID/T_STR/T_NUM/
T_CAT/T_TIME (Vec.java:207-212) and lazily computed, cached ``RollupStats``
(min/max/mean/sigma/histogram; fvec/RollupStats.java:19-30).  Chunks use 20+
compression codecs chosen at write time (fvec/NewChunk.java:1133).

TPU-native redesign: a Vec's payload is ONE row-sharded ``jax.Array`` padded
to the cluster row multiple — XLA wants flat dtypes and static shapes, so the
codec zoo collapses to dtype narrowing (float32 for numeric/time, int32 codes
for categoricals).  Missing values are NaN (numeric) or code -1 (categorical).
Strings/UUIDs stay host-side (numpy object arrays) — they never participate in
device compute (SURVEY.md §7 "keep string columns host-side only").
Rollups are computed lazily in a single fused XLA pass and cached, exactly
mirroring the reference's RollupStats contract.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.cluster import cluster

# Logical column types — mirrors Vec.java:207-212.
T_BAD = "bad"
T_NUM = "num"
T_CAT = "cat"
T_TIME = "time"
T_STR = "str"
T_UUID = "uuid"

_DEVICE_TYPES = (T_NUM, T_CAT, T_TIME, T_BAD)


def encode_domain(svals: np.ndarray, domain: Sequence[str],
                  na_mask: Optional[np.ndarray] = None) -> np.ndarray:
    """int32 codes of string values against an ORDERED domain; values not
    in the domain (and ``na_mask`` positions) code as -1.

    Vectorized via argsort + searchsorted — the per-cell dict lookup this
    replaces was a multi-second Python loop at parse-bench scale.
    """
    svals = np.asarray(svals)
    if svals.dtype.kind not in "US":
        svals = svals.astype(str)
    dom = np.asarray(list(domain), dtype=str)
    if len(dom) == 0:
        codes = np.full(len(svals), -1, np.int32)
    else:
        sorter = np.argsort(dom)
        pos = np.searchsorted(dom, svals, sorter=sorter)
        pos = np.clip(pos, 0, len(dom) - 1)
        hits = sorter[pos]
        codes = np.where(dom[hits] == svals, hits, -1).astype(np.int32)
    if na_mask is not None:
        codes[na_mask] = -1
    return codes


@dataclasses.dataclass
class RollupStats:
    """Lazily computed column statistics (fvec/RollupStats.java:19-30)."""

    nrows: int
    nmissing: int
    mean: float
    sigma: float
    vmin: float
    vmax: float
    nzero: int

    @property
    def is_constant(self) -> bool:
        return self.nrows - self.nmissing > 0 and self.vmin == self.vmax


def _ledger(name, jitted, orig=None, **kw):
    """Register a compiled frame seam with the compile ledger
    (runtime/xprof) — the parse/rollup side of the ledger."""
    from ..runtime import xprof
    return xprof.register_program(name, jitted, orig=orig, **kw)


def _batch_rollup_kernel_impl(X, n: int):
    """Rollups for a whole [C, padded] column block in ONE fused pass —
    per-column eager rollups cost a dispatch round trip each on a
    tunnelled backend (measured 203 s for a 481-column frame)."""
    iota = jax.lax.broadcasted_iota(jnp.int32, X.shape, 1)
    present = (iota < n) & ~jnp.isnan(X)
    x = jnp.where(present, X, 0.0)
    cnt = jnp.sum(present, axis=1)
    nf = jnp.maximum(cnt, 1).astype(jnp.float32)
    s = jnp.sum(x, axis=1, dtype=jnp.float32)
    ss = jnp.sum(x * x, axis=1, dtype=jnp.float32)
    mean = s / nf
    var = jnp.maximum(ss / nf - mean * mean, 0.0)
    big = jnp.float32(np.finfo(np.float32).max)
    vmin = jnp.min(jnp.where(present, X, big), axis=1)
    vmax = jnp.max(jnp.where(present, X, -big), axis=1)
    nzero = jnp.sum(present & (X == 0.0), axis=1)
    return (cnt, mean, var * nf / jnp.maximum(nf - 1.0, 1.0), vmin, vmax,
            nzero)


_batch_rollup_kernel = _ledger(
    "frame_rollup_batch",
    jax.jit(_batch_rollup_kernel_impl, static_argnames=("n",)),
    static_argnums=(1,), static_argnames=("n",),
    orig=_batch_rollup_kernel_impl)


def _rollup_kernel_impl(data, valid):
    """One fused pass computing all rollup stats for a numeric column."""
    present = valid & ~jnp.isnan(data)
    x = jnp.where(present, data, 0.0)
    n = jnp.sum(present)
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    s = jnp.sum(x, dtype=jnp.float32)
    ss = jnp.sum(x * x, dtype=jnp.float32)
    mean = s / nf
    var = jnp.maximum(ss / nf - mean * mean, 0.0)
    big = jnp.float32(np.finfo(np.float32).max)
    vmin = jnp.min(jnp.where(present, data, big))
    vmax = jnp.max(jnp.where(present, data, -big))
    nzero = jnp.sum(present & (data == 0.0))
    return n, mean, var * nf / jnp.maximum(nf - 1.0, 1.0), vmin, vmax, nzero


_rollup_kernel = _ledger("frame_rollup", jax.jit(_rollup_kernel_impl),
                         orig=_rollup_kernel_impl)


class Vec:
    """One column: device payload (or host payload for str/uuid) + metadata."""

    def __init__(self, data, vtype: str, nrows: int,
                 domain: Optional[Sequence[str]] = None,
                 host_data: Optional[np.ndarray] = None,
                 time_base: float = 0.0):
        self.type = vtype
        self.nrows = int(nrows)
        self.domain = list(domain) if domain is not None else None
        self.host_data = host_data          # str/uuid payload (numpy object)
        self.time_base = time_base          # TIME: ms-since-epoch of code 0
        self._spill = None                  # host copy while evicted from HBM
        self._atime = 0.0                   # LRU clock (shared via aliasing)
        self.data = data                    # padded row-sharded jax.Array
        self._rollups: Optional[RollupStats] = None

    # ------------------------------------------------------------ HBM spill
    # The reference's Cleaner evicts cold chunks from the K/V cache to disk
    # (water/Cleaner.java:12); here the scarce tier is HBM and the spill
    # target is host RAM: spill() fetches the device payload to numpy and
    # drops the jax.Array, and the next .data access transparently places
    # it back onto the row sharding.

    @property
    def data(self):
        self._atime = time.monotonic()
        if self._device is None and self._spill is not None:
            from ..runtime.cluster import cluster, put_sharded
            self._device = put_sharded(self._spill, cluster().row_sharding)
            self._spill = None
        return self._device

    @data.setter
    def data(self, value):
        self._device = value
        self._spill = None

    @property
    def is_spilled(self) -> bool:
        return self._device is None and self._spill is not None

    def spill(self) -> int:
        """Evict the device payload to host RAM; returns bytes freed."""
        if self._device is None:
            return 0
        from ..runtime.cluster import fetch
        freed = int(self._device.nbytes)
        self._spill = np.asarray(fetch(self._device))
        self._device = None
        return freed

    # ------------------------------------------------------------------ ctor
    @staticmethod
    def from_numpy(arr: np.ndarray, vtype: str = T_NUM,
                   domain: Optional[Sequence[str]] = None,
                   time_base: Optional[float] = None) -> "Vec":
        """Build a Vec from host data, padding + sharding onto the mesh.

        TIME input is float64 ms-since-epoch.  The device payload is rebased
        to ``(ms - time_base) / 1000`` seconds in float32 (well-conditioned
        for modeling; ~seconds resolution over year ranges) while the exact
        float64 ms stay host-side for round-trips.
        """
        cl = cluster()
        arr = np.asarray(arr)
        n = len(arr)
        if vtype in (T_STR, T_UUID):
            return Vec(None, vtype, n, host_data=np.asarray(arr, dtype=object))
        padded = cl.pad_rows(n)
        host_data = None
        if vtype == T_CAT:
            if arr.dtype == object or arr.dtype.kind in "US":
                labels = list(domain) if domain is not None else \
                    [str(u) for u in np.unique(arr.astype(str))]
                arr = encode_domain(arr, labels)
                domain = labels
            buf = np.full(padded, -1, dtype=np.int32)
            buf[:n] = arr.astype(np.int32)
        else:
            vals = arr.astype(np.float64)
            if vtype == T_TIME:
                host_data = vals
                if time_base is None:
                    finite = vals[np.isfinite(vals)]
                    time_base = float(finite.min()) if len(finite) else 0.0
                vals = (vals - time_base) / 1000.0
            buf = np.full(padded, np.nan, dtype=np.float32)
            buf[:n] = vals.astype(np.float32)
        from ..runtime.cluster import put_sharded
        data = put_sharded(buf, cl.row_sharding)
        return Vec(data, vtype, n, domain=domain, host_data=host_data,
                   time_base=time_base or 0.0)

    # ----------------------------------------------------------------- props
    @property
    def is_numeric(self) -> bool:
        return self.type in (T_NUM, T_TIME)

    @property
    def is_categorical(self) -> bool:
        return self.type == T_CAT

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain is not None else -1

    @property
    def padded_len(self) -> int:
        if self._spill is not None:          # serve from host, no restore
            return int(self._spill.shape[0])
        return int(self.data.shape[0]) if self.data is not None else self.nrows

    def valid_mask(self) -> jax.Array:
        """Boolean [padded] mask of real (non-padding) rows."""
        idx = jnp.arange(self.padded_len)
        return idx < self.nrows

    # --------------------------------------------------------------- rollups
    def rollups(self) -> RollupStats:
        """Lazy cached stats — the RollupStats contract (RollupStats.java:19)."""
        if self._rollups is None:
            if self.data is None:
                miss = int(sum(1 for v in self.host_data[: self.nrows] if v is None))
                self._rollups = RollupStats(self.nrows, miss, float("nan"),
                                            float("nan"), float("nan"),
                                            float("nan"), 0)
            elif self.type == T_TIME and self.host_data is not None:
                x = self.host_data[: self.nrows]
                ok = np.isfinite(x)
                n = int(ok.sum())
                self._rollups = RollupStats(
                    nrows=self.nrows, nmissing=self.nrows - n,
                    mean=float(np.mean(x[ok])) if n else float("nan"),
                    sigma=float(np.std(x[ok], ddof=1)) if n > 1 else float("nan"),
                    vmin=float(np.min(x[ok])) if n else float("nan"),
                    vmax=float(np.max(x[ok])) if n else float("nan"),
                    nzero=int((x[ok] == 0).sum()))
            else:
                x = self.numeric_data()
                n, mean, var, vmin, vmax, nzero = _rollup_kernel(x, self.valid_mask())
                n = int(n)
                self._rollups = RollupStats(
                    nrows=self.nrows, nmissing=self.nrows - n,
                    mean=float(mean) if n else float("nan"),
                    sigma=float(np.sqrt(max(float(var), 0.0))) if n > 1 else float("nan"),
                    vmin=float(vmin) if n else float("nan"),
                    vmax=float(vmax) if n else float("nan"),
                    nzero=int(nzero))
        return self._rollups

    def numeric_data(self) -> jax.Array:
        """Payload as float32 with NaN missing (cat codes -1 -> NaN)."""
        if self.data is None:
            raise TypeError(f"Vec of type {self.type} has no device payload")
        if self.type == T_CAT:
            return jnp.where(self.data < 0, jnp.nan, self.data.astype(jnp.float32))
        return self.data

    def mean(self) -> float:
        return self.rollups().mean

    def sigma(self) -> float:
        return self.rollups().sigma

    def min(self) -> float:
        return self.rollups().vmin

    def max(self) -> float:
        return self.rollups().vmax

    def nmissing(self) -> int:
        return self.rollups().nmissing

    # ---------------------------------------------------------------- export
    def to_numpy(self) -> np.ndarray:
        """Materialize the logical (unpadded) column on host.

        TIME returns the exact float64 ms-since-epoch kept host-side.
        """
        if self.type == T_TIME and self.host_data is not None:
            return self.host_data[: self.nrows]
        if self._spill is not None:          # serve from host, no restore
            return self._spill[: self.nrows]
        if self.data is None:
            return self.host_data[: self.nrows]
        from ..runtime.cluster import fetch
        return fetch(self.data)[: self.nrows]

    def canonical_host(self) -> np.ndarray:
        """Engine-independent host form for lineage hashing/replicas:
        num -> float32, cat -> int32 codes (-1 NA), time -> float64
        ms-since-epoch, str/uuid -> object (None NA).  A re-materialized
        shard is correct iff its canonical bytes match the original's."""
        arr = self.to_numpy()
        if self.type == T_CAT:
            return np.ascontiguousarray(arr, dtype=np.int32)
        if self.type == T_TIME:
            return np.ascontiguousarray(arr, dtype=np.float64)
        if self.type in (T_STR, T_UUID):
            return np.asarray(arr, dtype=object)
        return np.ascontiguousarray(arr, dtype=np.float32)

    def decoded(self) -> np.ndarray:
        """Host column with categorical codes mapped back to labels."""
        arr = self.to_numpy()
        if self.type == T_CAT and self.domain is not None:
            dom = np.asarray(self.domain, dtype=object)
            out = np.empty(len(arr), dtype=object)
            ok = arr >= 0
            out[ok] = dom[arr[ok]]
            out[~ok] = None
            return out
        return arr
