"""Distributed parse: files -> typed, sharded Frame.

Reference: ``water/parser/ParseDataset.java:31,60,133,688`` — a two-phase
parse: (1) ``ParseSetup`` samples raw bytes to guess separator/header/column
types; (2) ``MultiFileParseTask`` (an MRTask) tokenizes each raw chunk on its
home node, writes compressed NewChunks, and merges categorical domains
cluster-wide in the reduce (ParseDataset.java:501-600).

TPU-native redesign: the hot path is a parallel mmap'd pipeline — the file
is mapped (never copied), split at newline-aligned byte ranges, and the
native tokenizer (``native/fastcsv.cpp``) fans the ranges over a bounded
thread pool (ctypes releases the GIL).  As each range's tokenization lands,
its numeric columns start their async device transfer, so ``device_put`` of
early ranges hides tokenization of later ones; text columns take a
vectorized host pass (fixed-width byte gather + ``np.unique``) instead of
per-cell Python.  pandas' C reader and the stdlib tokenizer remain the
strict fallback engines.  Type guessing (phase 1) mirrors ParseSetup:
numeric > time > categorical > string, with a cardinality heuristic for
cat-vs-str.  Categorical domains are unified globally by construction
(single host pass) — the analog of the reference's domain-merge reduce.
"""

from __future__ import annotations

import csv
import io
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .frame import Frame
from .vec import Vec, T_CAT, T_NUM, T_STR, T_TIME
from ..runtime import dkv

_NA = {"", "na", "n/a", "nan", "null", "none", "?", "-", "NA", "NaN", "NULL", "None"}

# Per-stage wall times of the most recent native-path parse on this process
# (PROFILE.md measurement hook + test assertion surface): mmap, scan,
# tokenize, device-dispatch, decode/typing, total.
last_parse_stats: Dict[str, float] = {}


class _DeviceChunks(list):
    """Per-range on-device float32 pieces of one numeric column, in row
    order — produced by the tokenize/transfer overlap, concatenated on
    device at Vec-assembly time."""

# cat-vs-str heuristic: mostly-unique, high-cardinality text is a string column
_STR_UNIQUE_RATIO = 0.95
_STR_MIN_CARD = 100


def _guess_numeric(sample: Sequence[str]) -> bool:
    seen = False
    for s in sample:
        if s in _NA:
            continue
        seen = True
        try:
            float(s)
        except ValueError:
            return False
    return seen


def _parse_time_column(values: np.ndarray):
    """Try to parse an object column as datetimes -> ms since epoch (f64)."""
    try:
        import pandas as pd
        with np.errstate(all="ignore"):
            dt = pd.to_datetime(pd.Series(values), errors="coerce", format="mixed")
        ok = dt.notna().to_numpy()
        real = np.array([v not in _NA for v in values.astype(str)])
        if real.sum() == 0 or ok[real].mean() < 0.9:
            return None
        # robust to pandas ns/us/ms internal resolution
        ms = dt.to_numpy().astype("datetime64[ms]").astype("int64").astype(np.float64)
        ms[~ok] = np.nan
        return ms
    except Exception:
        return None


def _column_to_vec(values: np.ndarray, name: str,
                   coltype: Optional[str] = None) -> Vec:
    """Type-guess one parsed column and build its Vec (ParseSetup analog)."""
    values = np.asarray(values)
    if values.dtype.kind in "ifb" and coltype in (None, T_NUM):
        return Vec.from_numpy(values.astype(np.float32), T_NUM)
    if values.dtype.kind == "M":  # datetime64 from pandas
        ms = values.astype("datetime64[ms]").astype("int64").astype(np.float64)
        ms[np.isnat(values)] = np.nan
        return Vec.from_numpy(ms, T_TIME)
    svals = values.astype(str)
    na = np.isin(svals, list(_NA))
    if coltype in (None, T_NUM):
        sample = [s for s in svals[~na][:1000]]
        if _guess_numeric(sample):
            out = np.full(len(svals), np.nan, dtype=np.float64)
            ok = ~na
            try:
                out[ok] = svals[ok].astype(np.float64)
                return Vec.from_numpy(out, T_NUM)
            except ValueError:
                pass
    if coltype in (None, T_TIME):
        ms = _parse_time_column(values)
        if ms is not None:
            return Vec.from_numpy(ms, T_TIME)
    nz = svals[~na]
    uniq = np.unique(nz)
    if coltype != T_CAT and (coltype == T_STR or (
            len(uniq) >= _STR_MIN_CARD and
            len(uniq) > _STR_UNIQUE_RATIO * max(len(nz), 1))):
        host = svals.astype(object)
        host[na] = None
        return Vec(None, T_STR, len(host), host_data=host)
    # vectorized factorization: uniq is sorted, so searchsorted IS the
    # code lookup (the per-cell dict loop cost seconds at bench scale)
    codes = np.searchsorted(uniq, svals).astype(np.int32)
    codes[na] = -1
    return Vec.from_numpy(codes, T_CAT, domain=[str(u) for u in uniq])


_GATHER_MAX_WIDTH = 512          # cells wider than this take the slow loop


def _decode_text_column(body, offs: np.ndarray, j: int) -> np.ndarray:
    """Decode one column's raw cell bytes (native tokenizer offsets) to
    Python strings, applying RFC-4180 quote unescaping.

    Vectorized: the native fixed-width gather packs the cells into an
    ``|S width|`` column decoded in one ``np.char.decode`` call; only
    cells holding escaped quotes (or trailing NUL bytes, which the S
    dtype cannot represent) fall back to per-cell handling.  ``body``
    may be bytes or a zero-copy uint8 view (mmap).
    """
    from .. import native
    nrows = len(offs)
    starts = offs[:, j, 0]
    ends = offs[:, j, 1]
    width = int((ends - starts).max()) if nrows else 0
    if 0 < width <= _GATHER_MAX_WIDTH:
        fixed = native.gather_cells(body, starts, ends, width)
        if fixed is not None:
            col = np.char.decode(fixed, "utf-8", "replace").astype(object)
            redo = np.char.find(fixed, b'""') >= 0
            # trailing NULs vanish under the S dtype: re-decode those too
            redo |= np.char.str_len(fixed) != np.minimum(
                np.maximum(ends - starts, 0), width)
            if redo.any():
                view = memoryview(body)
                for i in np.flatnonzero(redo):
                    cell = bytes(view[starts[i]:ends[i]]) \
                        .decode(errors="replace")
                    col[i] = cell.replace('""', '"')
            return col
    view = memoryview(body) if not isinstance(body, bytes) else body
    col = np.empty(nrows, dtype=object)
    for i in range(nrows):
        s, e = offs[i, j]
        cell = bytes(view[s:e]).decode(errors="replace")
        if '""' in cell:
            cell = cell.replace('""', '"')
        col[i] = cell
    return col


def _pandas_safe() -> bool:
    """pandas 3.x's pyarrow-backed string arrays segfault when first
    constructed on a non-main thread in a jax-initialized process (this
    image; reproduced via REST-handler-thread read_csv).  The pandas
    reader is therefore main-thread-only; handler threads use the native
    tokenizer or the stdlib fallback."""
    import threading
    return threading.current_thread() is threading.main_thread()


def _parse_csv_native(path_or_buf, header, sep, col_names,
                      col_types: Optional[Dict[str, str]] = None,
                      overlap_device: bool = True,
                      on_range=None):
    """Native tokenizer path — the parallel mmap'd pipeline.

    Paths are mmap'd (no full-file ``read()`` copy); buffers/streams get a
    zero-copy uint8 view.  Newline-aligned byte ranges tokenize in
    parallel (``native.parse_view``); as each range completes, its
    pure-numeric columns are dispatched to the device as float32 chunks,
    overlapping transfer of early ranges with tokenization of later ones.
    Text-flagged columns fall out as vectorized host decodes.

    Returns (names, cols) — ``cols`` values are numpy arrays or
    ``_DeviceChunks`` (already on device, row order) — or None when the
    native library is unavailable or the input doesn't fit its fast path.
    """
    from .. import native
    if native.load() is None:
        return None
    sepc = sep if sep is not None else ","
    if len(sepc) != 1:
        return None
    col_types = col_types or {}
    stats: Dict[str, float] = {}
    t_all = time.perf_counter()
    mm = None
    if isinstance(path_or_buf, str):
        import mmap as _mmap
        t0 = time.perf_counter()
        with open(path_or_buf, "rb") as f:
            try:
                mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            except ValueError:           # empty file: defer to fallbacks
                return None
        view = np.frombuffer(mm, np.uint8)
        first_nl = mm.find(b"\n")
        stats["mmap_s"] = round(time.perf_counter() - t0, 4)
    else:
        data = path_or_buf if isinstance(path_or_buf, bytes) else None
        if data is None:
            data = path_or_buf.read()
            if isinstance(data, str):
                data = data.encode()
        if not len(data):
            return None
        view = np.frombuffer(data, np.uint8)
        first_nl = data.find(b"\n")
    first = bytes(view[: first_nl if first_nl >= 0 else len(view)]) \
        .decode(errors="replace")
    head_cells = [c.strip().strip('"') for c in first.split(sepc)]
    has_header = (not _guess_numeric(head_cells)) if header is None \
        else bool(header)
    body = view[first_nl + 1:] if has_header and first_nl >= 0 else view
    if not len(body):
        return None
    ncols = native.ncols_of(body, sepc)
    if not ncols:
        return None
    if col_names:                        # explicit names override a header
        names = list(col_names)
    elif has_header:
        names = head_cells
    else:
        names = [f"C{i+1}" for i in range(ncols)]
    if len(names) != ncols:
        return None

    # tokenize -> device-transfer overlap: numeric columns of each
    # completed range start their (async) placement while later ranges
    # are still tokenizing on the pool
    dev_chunks: List[Optional[list]] = [
        [] if (overlap_device and col_types.get(nm) in (None, T_NUM))
        else None
        for nm in names]
    dev_time = [0.0]
    consumer = on_range                   # external per-range hook, if any

    def _on_range(row_lo, nrows, Vt, Ft):
        from ..runtime import failure
        failure.maybe_inject("parse_range")
        if consumer is not None:
            consumer(row_lo, nrows, Vt, Ft)
        if not overlap_device:
            return
        t0 = time.perf_counter()
        try:
            import jax.numpy as jnp
        except Exception:
            for j in range(ncols):
                dev_chunks[j] = None
            return
        for j in range(ncols):
            if dev_chunks[j] is None:
                continue
            if Ft[:, j].any():           # text seen: column is host-bound
                dev_chunks[j] = None
                continue
            dev_chunks[j].append(
                (row_lo, jnp.asarray(np.asarray(Vt[:, j], np.float32))))
        dev_time[0] += time.perf_counter() - t0

    # the range hook is wired unconditionally: overlap_device only gates
    # the device-chunk dispatch INSIDE it, so external consumers (the
    # streaming ingest plane, lineage stamping) see every landed range
    # regardless of the device-overlap setting
    out = native.parse_view(body, sepc, ncols=ncols,
                            on_range=_on_range, stats=stats)
    if out is None:
        return None
    vals, flags, offs, consumed = out
    if consumed != len(body):
        return None              # unterminated quote etc.: defer to pandas
    nrows = len(vals)
    t0 = time.perf_counter()
    cols = {}
    for j, name in enumerate(names):
        chunks = dev_chunks[j]
        if chunks is not None and nrows and \
                sum(int(c.shape[0]) for _, c in chunks) == nrows:
            cols[name] = _DeviceChunks(
                c for _, c in sorted(chunks, key=lambda rc: rc[0]))
        elif flags[:, j].any():
            # numeric cells keep their text form for uniform type guessing
            cols[name] = _decode_text_column(body, offs, j)
        else:
            cols[name] = vals[:, j]
    stats["device_s"] = round(dev_time[0], 4)
    stats["decode_s"] = round(time.perf_counter() - t0, 4)
    stats["native_total_s"] = round(time.perf_counter() - t_all, 4)
    stats["rows"] = nrows
    stats["bytes"] = int(len(view))
    last_parse_stats.clear()
    last_parse_stats.update(stats)
    return names, cols


def parse_csv(path_or_buf, destination_frame: Optional[str] = None,
              header: Optional[bool] = None, sep: Optional[str] = None,
              col_types: Optional[Dict[str, str]] = None,
              col_names: Optional[List[str]] = None,
              on_range=None) -> Frame:
    """Parse a CSV file/buffer into a sharded Frame (ParseDataset.parse).

    Tokenization order: the native C++ fast path (numeric cells never
    become Python objects), then pandas' reader, then the stdlib fallback.

    ``on_range(row_lo, nrows, vals, flags)`` fires per newline-aligned
    byte range as the native tokenizer lands it (completion order, pool
    threads) — the streaming-ingest overlap seam.  Fallback engines parse
    whole-file and never fire it.
    """
    col_types = col_types or {}
    last_parse_stats.clear()             # fallbacks leave no stale stats
    # read streams ONCE up front so the native attempt cannot exhaust a
    # non-seekable input before a fallback runs; paths are mmap'd inside
    # the native pipeline (no full-file copy)
    source = path_or_buf
    raw: Optional[bytes] = None
    if isinstance(path_or_buf, bytes):
        raw = source = path_or_buf
    elif not isinstance(path_or_buf, str):
        raw = path_or_buf.read()
        if isinstance(raw, str):
            raw = raw.encode()
        source = raw
    names = cols = None
    try:
        parsed = _parse_csv_native(source, header, sep, col_names,
                                   col_types=col_types, on_range=on_range)
        if parsed is not None:
            names, cols = parsed
    except Exception:
        names = cols = None
    if names is None:
        pd_src = io.BytesIO(raw) if raw is not None else path_or_buf
        eff_header = header
        if header is None:
            # same first-line guess the native path (and stdlib fallback)
            # use, so parse results don't depend on which engine ran
            if raw is not None:
                first = raw.split(b"\n", 1)[0].decode(errors="replace")
            else:
                with open(path_or_buf, "r", errors="replace") as fh_:
                    first = fh_.readline()
            sepc = sep if sep is not None else ","
            cells = [c.strip().strip('"') for c in first.strip().split(sepc)]
            eff_header = not _guess_numeric(cells)
        use_pandas = _pandas_safe()
        if use_pandas:
            try:
                import pandas as pd
                df = pd.read_csv(
                    pd_src, sep=sep if sep is not None else ",",
                    header=0 if eff_header else None,
                    na_values=sorted(_NA), keep_default_na=True, engine="c",
                    low_memory=False)
                if col_names:
                    df.columns = col_names
                names = [str(c) for c in df.columns]
                cols = {n: df[n].to_numpy() for n in names}
            except ImportError:
                use_pandas = False
        if not use_pandas:
            sd = io.StringIO(raw.decode(errors="replace")) \
                if raw is not None else path_or_buf
            names, cols = _parse_csv_stdlib(sd, header, sep, col_names)
    t0 = time.perf_counter()
    vecs = [_assemble_vec(cols[n], n, col_types.get(n)) for n in names]
    if last_parse_stats:
        last_parse_stats["vec_s"] = round(time.perf_counter() - t0, 4)
        from ..runtime.observability import record
        record("parse", **last_parse_stats)
    key = destination_frame or dkv.make_key(
        os.path.basename(str(path_or_buf)) if isinstance(path_or_buf, str)
        else "frame")
    fr = Frame(names, vecs, key=key)
    if isinstance(path_or_buf, str):
        from . import lineage
        lineage.record_parse(fr, path_or_buf, header=header, sep=sep,
                             col_types=col_types, col_names=col_names)
    return fr


def _assemble_vec(col, name: str, coltype: Optional[str]) -> Vec:
    """Vec from one parsed column: device chunks concatenate in place
    (their transfer already overlapped tokenization); host arrays go
    through the type guesser."""
    if isinstance(col, _DeviceChunks):
        import jax.numpy as jnp
        data = jnp.concatenate(list(col)) if len(col) > 1 else col[0]
        return _device_numeric_vec(data)
    return _column_to_vec(col, name, coltype)


def _parse_csv_stdlib(path_or_buf, header, sep, col_names):
    """Dependency-free fallback tokenizer (CsvParser analog)."""
    if isinstance(path_or_buf, str):
        fh = open(path_or_buf, "r", newline="")
    else:
        fh = path_or_buf
    try:
        sample = fh.read(64 * 1024)
        fh.seek(0)
        try:
            dialect = csv.Sniffer().sniff(sample, delimiters=sep or ",;\t| ")
        except csv.Error:  # e.g. single-column files
            class dialect(csv.excel):
                delimiter = sep or ","
        rows = list(csv.reader(fh, dialect))
    finally:
        if isinstance(path_or_buf, str):
            fh.close()
    if not rows:
        raise ValueError("empty file")
    if header is None:
        header = not _guess_numeric(rows[0])
    if header:
        names, rows = [str(c) for c in rows[0]], rows[1:]
    else:
        names = col_names or [f"C{i+1}" for i in range(len(rows[0]))]
    ncol = len(names)
    cols = {n: np.array([r[i] if i < len(r) else "" for r in rows], dtype=object)
            for i, n in enumerate(names) if i < ncol}
    return names, cols


def _open_decompressed(uri: str) -> io.TextIOBase:
    """Open a (possibly remote, possibly compressed) source as text.

    Compression by extension — gzip/zip/bz2/xz; zip reads the first entry
    (ZipUtil.java behavior).  Remote schemes route through the Persist SPI.
    """
    from .. import persist
    raw = persist.open_read(uri)
    base = uri.lower()
    if base.endswith(".gz"):
        import gzip
        return io.TextIOWrapper(gzip.GzipFile(fileobj=raw), newline="")
    if base.endswith(".zip"):
        import zipfile
        zf = zipfile.ZipFile(raw)
        names = [n for n in zf.namelist() if not n.endswith("/")]
        if not names:
            raise ValueError(f"{uri}: empty zip archive")
        return io.TextIOWrapper(zf.open(names[0]), newline="")
    if base.endswith(".bz2"):
        import bz2
        return io.TextIOWrapper(bz2.BZ2File(raw), newline="")
    if base.endswith(".xz"):
        import lzma
        return io.TextIOWrapper(lzma.LZMAFile(raw), newline="")
    return io.TextIOWrapper(raw, newline="")


def _expand_paths(path) -> List[str]:
    """Expand a path / glob / directory / URI / list into source URIs."""
    from .. import persist
    paths = path if isinstance(path, (list, tuple)) else [path]
    out: List[str] = []
    for p in paths:
        matches = persist.list_uris(p)
        if matches:
            out.extend(matches)
        elif persist.exists(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    return out


def parse_files(paths: Sequence[str],
                destination_frame: Optional[str] = None,
                header: Optional[bool] = None, sep: Optional[str] = None,
                col_types: Optional[Dict[str, str]] = None,
                col_names: Optional[List[str]] = None,
                chunksize: int = 1_000_000) -> Frame:
    """Parse many CSV shards into ONE Frame — MultiFileParseTask analog.

    Local uncompressed shards take the same ranged-parallel mmap pipeline
    as ``parse_csv`` (``_parse_csv_native``): numeric columns arrive as
    on-device chunks whose transfer overlapped tokenization.  Remote or
    compressed shards stream through pandas in ``chunksize``-row chunks.
    Numeric chunks are ``device_put`` immediately and the host copy
    dropped, so host RSS stays bounded (the reference keeps raw chunks in
    the DKV and parses in place — ParseDataset.java:688).
    Text/categorical columns accumulate host-side: their global domain
    must be built before codes exist, mirroring the reference's
    cluster-wide categorical domain merge (ParseDataset.java:501-600).
    """
    import jax.numpy as jnp
    col_types = col_types or {}
    try:
        import pandas as pd
    except ImportError:
        pd = None
    dev_chunks: Dict[str, list] = {}
    host_chunks: Dict[str, list] = {}
    names: Optional[List[str]] = None

    def eat(df_names, df_cols):
        nonlocal names
        if names is None:
            names = list(df_names)
            for n in names:
                dev_chunks[n] = []
                host_chunks[n] = []
        elif list(df_names) != names:
            raise ValueError(
                f"shard schema mismatch: {df_names} vs {names}")
        for n in names:
            raw_col = df_cols[n]
            if isinstance(raw_col, _DeviceChunks):
                # ranged native pipeline already placed these on device
                if host_chunks[n]:     # column went host in an earlier shard
                    host_chunks[n].extend(np.asarray(c) for c in raw_col)
                else:
                    dev_chunks[n].extend(raw_col)
                continue
            arr = np.asarray(raw_col)
            want = col_types.get(n)
            if arr.dtype.kind in "if" and want in (None, T_NUM) \
                    and not host_chunks[n]:
                dev_chunks[n].append(jnp.asarray(arr, jnp.float32))
            else:
                if dev_chunks[n]:      # late type widening: pull back
                    host_chunks[n] = [np.asarray(c) for c in dev_chunks[n]]
                    dev_chunks[n] = []
                host_chunks[n].append(arr)

    def _ranged_ok(uri: str) -> bool:
        return "://" not in uri and not uri.lower().endswith(
            (".gz", ".zip", ".bz2", ".xz"))

    for uri in paths:
        if _ranged_ok(uri):
            # pandas treats header=None as "every shard has a header":
            # mirror that so engine choice can't change the result
            parsed = None
            try:
                parsed = _parse_csv_native(
                    uri, header in (None, True), sep, col_names,
                    col_types=col_types)
            except Exception:
                parsed = None
            if parsed is not None:
                eat(*parsed)
                continue
        fh = _open_decompressed(uri)
        if pd is not None:
            reader = pd.read_csv(
                fh, sep=sep if sep is not None else ",",
                header=0 if header in (None, True) else None,
                na_values=sorted(_NA), keep_default_na=True, engine="c",
                chunksize=chunksize)
            for df in reader:
                if col_names:
                    df.columns = col_names
                eat([str(c) for c in df.columns],
                    {str(c): df[c].to_numpy() for c in df.columns})
        else:
            snames, scols = _parse_csv_stdlib(fh, header, sep, col_names)
            eat(snames, scols)
        fh.close()
    if names is None:
        raise ValueError("no data parsed")
    vecs = []
    for n in names:
        if dev_chunks[n]:
            data = jnp.concatenate(dev_chunks[n]) if len(dev_chunks[n]) > 1 \
                else dev_chunks[n][0]
            vecs.append(_device_numeric_vec(data))
        else:
            col = np.concatenate(host_chunks[n]) if len(host_chunks[n]) > 1 \
                else host_chunks[n][0]
            vecs.append(_column_to_vec(col, n, col_types.get(n)))
    key = destination_frame or dkv.make_key(
        os.path.basename(str(paths[0])) or "frame")
    return Frame(names, vecs, key=key)


def _device_numeric_vec(data) -> Vec:
    """Vec from an already-on-device f32 column (pads + row-shards)."""
    import jax.numpy as jnp
    from ..runtime.cluster import cluster, put_sharded
    cl = cluster()
    n = int(data.shape[0])
    padded = cl.pad_rows(n)
    if padded > n:
        data = jnp.concatenate(
            [data, jnp.full(padded - n, jnp.nan, jnp.float32)])
    return Vec(put_sharded(data, cl.row_sharding), T_NUM, n)


def parse_svmlight(path: str,
                   destination_frame: Optional[str] = None) -> Frame:
    """SVMLight sparse format -> dense Frame (parser/SVMLightParser analog).

    Lines: ``<target> <idx>:<val> ...`` (1-based indices per the format).
    """
    targets, rows, max_idx = [], [], 0
    fh = _open_decompressed(path)
    for line in fh:
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        targets.append(float(parts[0]))
        pairs = []
        for tok in parts[1:]:
            i, _, v = tok.partition(":")
            idx = int(i)
            pairs.append((idx, float(v)))
            max_idx = max(max_idx, idx)
        rows.append(pairs)
    fh.close()
    # index base detection: the format spec is 1-based, but 0-based files
    # are common (sklearn dump_svmlight_file defaults to zero_based=True)
    min_idx = min((i for pairs in rows for i, _ in pairs), default=1)
    base = 0 if min_idx == 0 else 1
    n, d = len(rows), max_idx + 1 - base
    X = np.zeros((n, d), np.float32)
    for r, pairs in enumerate(rows):
        for idx, v in pairs:
            X[r, idx - base] = v
    names = ["target"] + [f"C{j+1}" for j in range(d)]
    vecs = [Vec.from_numpy(np.asarray(targets, np.float64), T_NUM)]
    vecs += [Vec.from_numpy(X[:, j], T_NUM) for j in range(d)]
    return Frame(names, vecs, key=destination_frame or dkv.make_key("svm"))


def parse_arff(path: str, destination_frame: Optional[str] = None) -> Frame:
    """ARFF -> Frame (parser/ARFFParser analog): @attribute-driven types."""
    names, types, domains = [], [], []
    data_lines = []
    in_data = False
    fh = _open_decompressed(path)
    for line in fh:
        s = line.strip()
        if not s or s.startswith("%"):
            continue
        low = s.lower()
        if in_data:
            data_lines.append(s)
        elif low.startswith("@attribute"):
            rest = s.split(None, 1)[1]
            if rest.startswith('"') or rest.startswith("'"):
                q = rest[0]
                name = rest[1:rest.index(q, 1)]
                spec = rest[rest.index(q, 1) + 1:].strip()
            else:
                name, _, spec = rest.partition(" ")
                spec = spec.strip()
            names.append(name)
            if spec.startswith("{"):
                types.append(T_CAT)
                domains.append([v.strip().strip("'\"")
                                for v in spec.strip("{}").split(",")])
            elif spec.lower() in ("numeric", "real", "integer"):
                types.append(T_NUM)
                domains.append(None)
            elif spec.lower().startswith("date"):
                types.append(T_TIME)
                domains.append(None)
            else:
                types.append(T_STR)
                domains.append(None)
        elif low.startswith("@data"):
            in_data = True
    fh.close()
    rows = list(csv.reader(data_lines))
    cols = {}
    for i, n in enumerate(names):
        cols[n] = np.array([r[i].strip() if i < len(r) else ""
                            for r in rows], dtype=object)
    vecs = []
    for n, t, dom in zip(names, types, domains):
        if t == T_CAT:
            lookup = {s: i for i, s in enumerate(dom)}
            codes = np.array([lookup.get(v, -1) for v in cols[n]], np.int32)
            vecs.append(Vec.from_numpy(codes, T_CAT, domain=dom))
        elif t == T_NUM:
            vals = np.array([np.nan if v in _NA else float(v)
                             for v in cols[n]], np.float64)
            vecs.append(Vec.from_numpy(vals, T_NUM))
        else:
            vecs.append(_column_to_vec(cols[n], n, t))
    return Frame(names, vecs, key=destination_frame or dkv.make_key("arff"))


def arrow_table_to_vecs(table):
    """Arrow table -> (names, vecs) under the standard type mapping:
    numerics -> T_NUM, dictionary/string -> categorical/string via the
    standard guesser, timestamps -> T_TIME (ms since epoch).  Shared by
    ``parse_arrow``, the streaming row-group path, and the parquet
    re-materialization branch in ``runtime/remat.py`` so all three land
    bitwise-identical columns."""
    import pyarrow as pa
    names, vecs = [], []
    for col_name in table.column_names:
        col = table.column(col_name)
        pa_type = col.type
        names.append(str(col_name))
        if pa.types.is_timestamp(pa_type) or pa.types.is_date(pa_type):
            ms = col.cast(pa.timestamp("ms")).to_numpy(
                zero_copy_only=False).astype("datetime64[ms]") \
                .astype("int64").astype(np.float64)
            nulls = col.is_null().to_numpy(zero_copy_only=False)
            ms[nulls] = np.nan
            vecs.append(Vec.from_numpy(ms, T_TIME))
        elif pa.types.is_floating(pa_type) or pa.types.is_integer(pa_type) \
                or pa.types.is_boolean(pa_type):
            arr = col.cast(pa.float64()).to_numpy(zero_copy_only=False)
            vecs.append(Vec.from_numpy(arr, T_NUM))
        else:
            arr = np.asarray(col.to_pylist(), dtype=object)
            arr = np.asarray(["" if v is None else str(v) for v in arr],
                             dtype=object)
            vecs.append(_column_to_vec(arr, str(col_name)))
    return names, vecs


def read_parquet_groups(raw, on_group=None):
    """Ranged parquet read: one ``read_row_group`` per group instead of a
    whole-table ``read_table``.  ``on_group(group_no, row_lo, table)``
    fires as each group lands — the columnar streaming seam, mirroring
    the CSV ``on_range`` hook (same ``parse_group`` fault-injection
    point).  Returns the concatenated table, bitwise equal to a
    whole-table read."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(raw)
    if pf.metadata.num_row_groups == 0:
        return pf.read()
    from ..runtime import failure
    parts, row_lo = [], 0
    for gi in range(pf.metadata.num_row_groups):
        tbl = pf.read_row_group(gi)
        failure.maybe_inject("parse_group")
        if on_group is not None:
            on_group(gi, row_lo, tbl)
        row_lo += tbl.num_rows
        parts.append(tbl)
    return pa.concat_tables(parts)


def parse_arrow(path: str, fmt: str,
                destination_frame: Optional[str] = None,
                on_group=None) -> Frame:
    """Columnar formats via pyarrow — the h2o-parsers/{parquet,orc} analog.

    ``fmt``: parquet | orc | feather.  Parquet reads row group by row
    group (``read_parquet_groups``), firing ``on_group`` per landed group
    and stamping a row-group-granularity lineage record so parquet frames
    re-materialize partially after a host loss, exactly like CSV parses.
    """
    from .. import persist
    raw = persist.open_read(path)
    if fmt == "parquet":
        table = read_parquet_groups(raw, on_group=on_group)
    elif fmt == "orc":
        import pyarrow.orc as porc
        table = porc.ORCFile(raw).read()
    elif fmt == "feather":
        import pyarrow.feather as pf
        table = pf.read_table(raw)
    else:
        raise ValueError(f"unknown arrow format {fmt!r}")
    names, vecs = arrow_table_to_vecs(table)
    # register only when a destination was requested: multi-file imports
    # build unregistered shards and register just the rbind result
    fr = Frame(names, vecs, key=destination_frame)
    if fmt == "parquet" and destination_frame:
        from . import lineage
        lineage.record_parse_columnar(fr, path)
    return fr


def import_file(path, destination_frame: Optional[str] = None,
                **kw) -> Frame:
    """h2o.import_file analog — see ``_import_file_impl``.  The returned
    frame carries ``source_uri`` provenance so the recovery journal can
    re-import it after a coordinator restart (Recovery.java:72 contract)."""
    fr = _import_file_impl(path, destination_frame=destination_frame, **kw)
    fr.source_uri = path if isinstance(path, str) else list(path)
    return fr


def _import_file_impl(path, destination_frame: Optional[str] = None,
                      **kw) -> Frame:
    """h2o.import_file analog (h2o-py/h2o/h2o.py import_file -> /3/Parse).

    Accepts a single path, a glob pattern, a directory, a list of paths, or
    a persist URI (``gcs://…``, ``file://…``); gzip/zip/bz2/xz shards
    decompress transparently; ``.svm``/``.svmlight``, ``.arff``,
    ``.parquet``, ``.orc``, ``.feather``, ``.avro``, ``.xlsx`` and legacy
    ``.xls`` route to format parsers.
    """
    paths = _expand_paths(path)
    low = paths[0].lower()
    for ext, fn in ((".svm", parse_svmlight), (".svmlight", parse_svmlight),
                    (".arff", parse_arff)):
        if low.endswith(ext) or low.endswith(ext + ".gz"):
            if len(paths) > 1:
                raise ValueError(f"multi-file {ext} import not supported")
            return fn(paths[0], destination_frame=destination_frame)
    for ext, fmt in ((".parquet", "parquet"), (".pq", "parquet"),
                     (".orc", "orc"), (".feather", "feather")):
        if low.endswith(ext):
            if len(paths) == 1:
                return parse_arrow(
                    paths[0], fmt,
                    destination_frame=destination_frame
                    or dkv.make_key(fmt))
            from ..rapids.ops import rbind
            frames = [parse_arrow(p2, fmt) for p2 in paths]
            out = rbind(*frames)
            out.key = destination_frame or dkv.make_key(fmt)
            dkv.put(out.key, out)
            return out
    fmt_parsers = {}
    from .avro import parse_avro
    from .xls import parse_xls, parse_xlsx
    fmt_parsers[".avro"] = parse_avro
    fmt_parsers[".xlsx"] = parse_xlsx
    fmt_parsers[".xls"] = parse_xls
    for ext, fn in fmt_parsers.items():
        if low.endswith(ext):
            if len(paths) == 1:
                return fn(paths[0], destination_frame=destination_frame)
            from ..rapids.ops import rbind
            out = rbind(*[fn(p2) for p2 in paths])
            out.key = destination_frame or dkv.make_key(ext.strip("."))
            dkv.put(out.key, out)
            return out
    import jax

    def _rangeable(p: str) -> bool:
        """Byte-range-capable source: local files and the cloud persist
        backends with real range reads (GCS/S3/HDFS/file)."""
        if p.lower().endswith((".gz", ".zip", ".bz2", ".xz")):
            return False
        scheme = p.split("://", 1)[0] if "://" in p else ""
        return scheme in ("", "file", "gs", "gcs", "s3", "hdfs")

    if jax.process_count() > 1 and all(_rangeable(p) for p in paths):
        # pod-scale ingest: tokenize on the hosts that own the byte ranges
        # (MultiFileParseTask analog) instead of replicating the full
        # tokenization on every process
        from .dparse import parse_files_distributed
        return parse_files_distributed(
            paths, destination_frame=destination_frame, **kw)
    if len(paths) == 1 and "://" not in paths[0] \
            and not any(paths[0].lower().endswith(e)
                        for e in (".gz", ".zip", ".bz2", ".xz")):
        return parse_csv(paths[0], destination_frame=destination_frame, **kw)
    return parse_files(paths, destination_frame=destination_frame, **kw)


def export_file(frame: Frame, uri: str, header: bool = True) -> str:
    """Write a Frame to any persist URI — h2o.export_file analog.

    Format by extension: ``.parquet``/``.feather`` via pyarrow, else CSV.
    """
    from .. import persist
    low = uri.lower()
    if low.endswith((".parquet", ".pq", ".feather")):
        import pyarrow as pa
        cols = {}
        for n, v in zip(frame.names, frame.vecs):
            col = v.decoded()
            if v.type == T_TIME:
                cols[n] = np.asarray(col, "float64").astype("datetime64[ms]")
            else:
                cols[n] = col
        table = pa.table(cols)
        fh = persist.open_write(uri)
        if low.endswith(".feather"):
            import pyarrow.feather as pf
            pf.write_feather(table, fh)
        else:
            import pyarrow.parquet as pq
            pq.write_table(table, fh)
        fh.close()
        return uri
    cols = [v.decoded() for v in frame.vecs]
    fh = persist.open_write(uri)
    out = io.TextIOWrapper(fh, newline="")
    wr = csv.writer(out)
    if header:
        wr.writerow(frame.names)
    for i in range(frame.nrows):
        wr.writerow(["" if (c[i] is None or (isinstance(c[i], float)
                                             and np.isnan(c[i]))) else c[i]
                     for c in cols])
    out.flush()
    out.close()
    return uri


def upload_string(text: str, **kw) -> Frame:
    return parse_csv(io.StringIO(text), **kw)


def from_pandas(df, destination_frame: Optional[str] = None) -> Frame:
    """Build a Frame from a pandas DataFrame — the h2o.H2OFrame(df) path.

    dtype mapping: numeric/bool -> num (bool as 0/1), datetime64 ->
    time, pandas categorical -> cat preserving the category order,
    object/string -> the parser's type guesser (_column_to_vec), so
    mixed string columns come out num/time/cat/str exactly like a CSV
    import of the same data.
    """
    import pandas as pd
    names, vecs = [], []
    for c in df.columns:
        s = df[c]
        name = str(c)
        if isinstance(s.dtype, pd.CategoricalDtype):
            domain = [str(v) for v in s.cat.categories]
            # pandas already stores int codes with -1 = NA: pass through
            vec = Vec.from_numpy(s.cat.codes.to_numpy(np.int32), T_CAT,
                                 domain=domain)
        elif s.dtype.kind == "b":
            vec = Vec.from_numpy(
                s.to_numpy(dtype=np.float64, na_value=np.nan), T_NUM)
        elif s.dtype.kind in "iuf":
            vec = Vec.from_numpy(s.to_numpy(dtype=np.float64,
                                            na_value=np.nan), T_NUM)
        elif s.dtype.kind == "M":
            vec = _column_to_vec(s.to_numpy(), name)
        else:
            vals = np.asarray(["" if v is None or v is pd.NA else v
                               for v in s.to_numpy()], dtype=object)
            vec = _column_to_vec(vals, name)
        names.append(name)
        vecs.append(vec)
    return Frame(names, vecs,
                 key=destination_frame or dkv.make_key("pandas"))


def H2OFrame(python_obj, destination_frame: Optional[str] = None) -> Frame:
    """h2o.H2OFrame constructor analog: accepts a pandas DataFrame, a
    dict of columns, a list of rows (first row = header if strings),
    or a 2-D numpy array."""
    try:
        import pandas as pd
        if isinstance(python_obj, pd.DataFrame):
            return from_pandas(python_obj, destination_frame)
    except ImportError:
        pass
    if isinstance(python_obj, dict):
        names, vecs = [], []
        for k, v in python_obj.items():
            arr = np.asarray(v)
            if arr.dtype == object:
                arr = np.asarray(["" if x is None else x for x in arr],
                                 dtype=object)
            names.append(str(k))
            vecs.append(_column_to_vec(arr, str(k)))
        return Frame(names, vecs,
                     key=destination_frame or dkv.make_key("pyobj"))
    arr = np.asarray(python_obj, dtype=object)
    one_d = arr.ndim == 1
    if one_d:
        arr = arr[:, None]
    # header heuristic only for 2-D input: a 1-D list is pure data
    if not one_d and arr.shape[0] and             all(isinstance(v, str) for v in arr[0]):
        header, body = [str(v) for v in arr[0]], arr[1:]
    else:
        header, body = [f"C{j + 1}" for j in range(arr.shape[1])], arr
    names, vecs = [], []
    for j, name in enumerate(header):
        vals = np.asarray(["" if v is None else v for v in body[:, j]],
                          dtype=object)
        names.append(name)
        vecs.append(_column_to_vec(vals, name))
    return Frame(names, vecs,
                 key=destination_frame or dkv.make_key("pyobj"))
