"""Distributed parse: files -> typed, sharded Frame.

Reference: ``water/parser/ParseDataset.java:31,60,133,688`` — a two-phase
parse: (1) ``ParseSetup`` samples raw bytes to guess separator/header/column
types; (2) ``MultiFileParseTask`` (an MRTask) tokenizes each raw chunk on its
home node, writes compressed NewChunks, and merges categorical domains
cluster-wide in the reduce (ParseDataset.java:501-600).

TPU-native redesign: tokenization is host CPU work either way, so phase 2 uses
the fastest host path available (pandas' C reader when present, stdlib csv
otherwise) into numpy buffers, then a SINGLE device_put per column lays the
data out row-sharded across the mesh — the "chunk homing" step.  Type
guessing (phase 1) mirrors ParseSetup: numeric > time > categorical > string,
with a cardinality heuristic for cat-vs-str.  Categorical domains are unified
globally by construction (single host pass) — the analog of the reference's
domain-merge reduce.
"""

from __future__ import annotations

import csv
import io
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .frame import Frame
from .vec import Vec, T_CAT, T_NUM, T_STR, T_TIME
from ..runtime import dkv

_NA = {"", "na", "n/a", "nan", "null", "none", "?", "-", "NA", "NaN", "NULL", "None"}

# cat-vs-str heuristic: mostly-unique, high-cardinality text is a string column
_STR_UNIQUE_RATIO = 0.95
_STR_MIN_CARD = 100


def _guess_numeric(sample: Sequence[str]) -> bool:
    seen = False
    for s in sample:
        if s in _NA:
            continue
        seen = True
        try:
            float(s)
        except ValueError:
            return False
    return seen


def _parse_time_column(values: np.ndarray):
    """Try to parse an object column as datetimes -> ms since epoch (f64)."""
    try:
        import pandas as pd
        with np.errstate(all="ignore"):
            dt = pd.to_datetime(pd.Series(values), errors="coerce", format="mixed")
        ok = dt.notna().to_numpy()
        real = np.array([v not in _NA for v in values.astype(str)])
        if real.sum() == 0 or ok[real].mean() < 0.9:
            return None
        # robust to pandas ns/us/ms internal resolution
        ms = dt.to_numpy().astype("datetime64[ms]").astype("int64").astype(np.float64)
        ms[~ok] = np.nan
        return ms
    except Exception:
        return None


def _column_to_vec(values: np.ndarray, name: str,
                   coltype: Optional[str] = None) -> Vec:
    """Type-guess one parsed column and build its Vec (ParseSetup analog)."""
    values = np.asarray(values)
    if values.dtype.kind in "ifb" and coltype in (None, T_NUM):
        return Vec.from_numpy(values.astype(np.float32), T_NUM)
    if values.dtype.kind == "M":  # datetime64 from pandas
        ms = values.astype("datetime64[ms]").astype("int64").astype(np.float64)
        ms[np.isnat(values)] = np.nan
        return Vec.from_numpy(ms, T_TIME)
    svals = values.astype(str)
    na = np.isin(svals, list(_NA))
    if coltype in (None, T_NUM):
        sample = [s for s in svals[~na][:1000]]
        if _guess_numeric(sample):
            out = np.full(len(svals), np.nan, dtype=np.float64)
            ok = ~na
            try:
                out[ok] = svals[ok].astype(np.float64)
                return Vec.from_numpy(out, T_NUM)
            except ValueError:
                pass
    if coltype in (None, T_TIME):
        ms = _parse_time_column(values)
        if ms is not None:
            return Vec.from_numpy(ms, T_TIME)
    nz = svals[~na]
    uniq = np.unique(nz)
    if coltype != T_CAT and (coltype == T_STR or (
            len(uniq) >= _STR_MIN_CARD and
            len(uniq) > _STR_UNIQUE_RATIO * max(len(nz), 1))):
        host = np.array([None if m else s for s, m in zip(svals, na)], dtype=object)
        return Vec(None, T_STR, len(host), host_data=host)
    lookup = {s: i for i, s in enumerate(uniq)}
    codes = np.array([-1 if m else lookup[s] for s, m in zip(svals, na)],
                     dtype=np.int32)
    return Vec.from_numpy(codes, T_CAT, domain=[str(u) for u in uniq])


def parse_csv(path_or_buf, destination_frame: Optional[str] = None,
              header: Optional[bool] = None, sep: Optional[str] = None,
              col_types: Optional[Dict[str, str]] = None,
              col_names: Optional[List[str]] = None) -> Frame:
    """Parse a CSV file/buffer into a sharded Frame (ParseDataset.parse)."""
    col_types = col_types or {}
    try:
        import pandas as pd
        df = pd.read_csv(
            path_or_buf, sep=sep if sep is not None else ",",
            header=0 if header in (None, True) else None,
            na_values=sorted(_NA), keep_default_na=True, engine="c",
            low_memory=False)
        if col_names:
            df.columns = col_names
        names = [str(c) for c in df.columns]
        cols = {n: df[n].to_numpy() for n in names}
    except ImportError:
        names, cols = _parse_csv_stdlib(path_or_buf, header, sep, col_names)
    vecs = [_column_to_vec(cols[n], n, col_types.get(n)) for n in names]
    key = destination_frame or dkv.make_key(
        os.path.basename(str(path_or_buf)) if isinstance(path_or_buf, str)
        else "frame")
    return Frame(names, vecs, key=key)


def _parse_csv_stdlib(path_or_buf, header, sep, col_names):
    """Dependency-free fallback tokenizer (CsvParser analog)."""
    if isinstance(path_or_buf, str):
        fh = open(path_or_buf, "r", newline="")
    else:
        fh = path_or_buf
    try:
        sample = fh.read(64 * 1024)
        fh.seek(0)
        try:
            dialect = csv.Sniffer().sniff(sample, delimiters=sep or ",;\t| ")
        except csv.Error:  # e.g. single-column files
            class dialect(csv.excel):
                delimiter = sep or ","
        rows = list(csv.reader(fh, dialect))
    finally:
        if isinstance(path_or_buf, str):
            fh.close()
    if not rows:
        raise ValueError("empty file")
    if header is None:
        header = not _guess_numeric(rows[0])
    if header:
        names, rows = [str(c) for c in rows[0]], rows[1:]
    else:
        names = col_names or [f"C{i+1}" for i in range(len(rows[0]))]
    ncol = len(names)
    cols = {n: np.array([r[i] if i < len(r) else "" for r in rows], dtype=object)
            for i, n in enumerate(names) if i < ncol}
    return names, cols


def import_file(path: str, destination_frame: Optional[str] = None,
                **kw) -> Frame:
    """h2o.import_file analog (h2o-py/h2o/h2o.py import_file -> /3/Parse)."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return parse_csv(path, destination_frame=destination_frame, **kw)


def upload_string(text: str, **kw) -> Frame:
    return parse_csv(io.StringIO(text), **kw)
