"""Avro Object Container File parser — no external avro library.

Reference: ``h2o-parsers/h2o-avro-parser/src/main/java/water/parser/avro/
AvroParser.java`` (flat-record import: primitive fields + nullable unions
+ enums; nested records/arrays/maps are out of scope there too).

This is a from-scratch decoder of the public Avro 1.x container spec
(magic ``Obj\\x01``, metadata map with ``avro.schema``/``avro.codec``,
sync-marker-delimited blocks of zigzag-varint-encoded datums; null and
deflate codecs).  Columns become Vecs: long/int/float/double -> numeric,
boolean -> 0/1, string/bytes -> cat/str per cardinality heuristics of the
CSV path, enum -> cat with the schema's symbol list as domain.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import List, Optional

import numpy as np

_MAGIC = b"Obj\x01"


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos: self.pos + n]
        if len(b) != n:
            raise ValueError("truncated avro data")
        self.pos += n
        return b

    def long(self) -> int:
        """zigzag varint — the single Avro integer encoding."""
        shift, acc = 0, 0
        while True:
            if self.pos >= len(self.buf):
                raise ValueError("truncated avro data")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]


def _decode_value(r: _Reader, schema):
    """One datum of a (restricted) schema. Supported: primitives, enum,
    [null, X] unions, logical types riding on supported primitives."""
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "enum":
            return schema["symbols"][r.long()]
        if t in ("record", "array", "map", "fixed"):
            raise NotImplementedError(
                f"nested avro type {t!r} is not importable as a column "
                "(reference AvroParser imports flat records too)")
        schema = t
    if isinstance(schema, list):                       # union
        branch = schema[r.long()]
        return _decode_value(r, branch)
    if schema == "null":
        return None
    if schema == "boolean":
        return bool(r.read(1)[0])
    if schema in ("int", "long"):
        return r.long()
    if schema == "float":
        return r.float_()
    if schema == "double":
        return r.double()
    if schema == "string":
        return r.bytes_().decode()
    if schema == "bytes":
        return r.bytes_()
    raise NotImplementedError(f"avro type {schema!r}")


def _column_kind(schema) -> str:
    """'num' | 'bool' | 'text' | ('enum', symbols) for a field schema."""
    if isinstance(schema, list):
        non_null = [s for s in schema if s != "null"]
        if len(non_null) != 1:
            raise NotImplementedError(
                "only [null, X] unions import as columns")
        return _column_kind(non_null[0])
    if isinstance(schema, dict):
        if schema["type"] == "enum":
            return ("enum", list(schema["symbols"]))
        return _column_kind(schema["type"])
    if schema in ("int", "long", "float", "double"):
        return "num"
    if schema == "boolean":
        return "bool"
    if schema in ("string", "bytes"):
        return "text"
    raise NotImplementedError(f"avro type {schema!r}")


def parse_avro(path_or_buf, destination_frame: Optional[str] = None):
    """Avro container file -> Frame (AvroParser.java parseChunk analog)."""
    from ..runtime import dkv
    from .frame import Frame
    from .parse import _column_to_vec
    from .vec import Vec, T_CAT, T_NUM

    if isinstance(path_or_buf, (bytes, bytearray)):
        raw = bytes(path_or_buf)
    else:
        with open(path_or_buf, "rb") as fh:
            raw = fh.read()
    r = _Reader(raw)
    if r.read(4) != _MAGIC:
        raise ValueError("not an avro object container file (bad magic)")
    meta = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:                       # negative count -> byte size follows
            n = -n
            r.long()
        for _ in range(n):
            k = r.bytes_().decode()
            meta[k] = r.bytes_()
    sync = r.read(16)
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise NotImplementedError("top-level avro schema must be a record")
    fields = schema["fields"]
    names = [f["name"] for f in fields]
    kinds = [_column_kind(f["type"]) for f in fields]
    cols: List[list] = [[] for _ in names]

    while r.pos < len(r.buf):
        count = r.long()
        size = r.long()
        block = r.read(size)
        if r.read(16) != sync:
            raise ValueError("avro block sync marker mismatch")
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise NotImplementedError(f"avro codec {codec!r} (null/deflate)")
        br = _Reader(block)
        for _ in range(count):
            for j, f in enumerate(fields):
                cols[j].append(_decode_value(br, f["type"]))

    vecs, out_names = [], []
    for name, kind, vals in zip(names, kinds, cols):
        if kind in ("num", "bool"):
            arr = np.array([np.nan if v is None else float(v)
                            for v in vals], dtype=np.float64)
            vecs.append(Vec.from_numpy(arr, T_NUM))
        elif isinstance(kind, tuple):                  # enum -> cat
            symbols = kind[1]
            lookup = {s: i for i, s in enumerate(symbols)}
            codes = np.array([-1 if v is None else lookup[v]
                              for v in vals], np.int32)
            vecs.append(Vec.from_numpy(codes, T_CAT, domain=symbols))
        else:                                          # text: type-guess
            decoded = np.array(
                ["" if v is None else
                 (v.decode(errors="replace") if isinstance(v, bytes) else v)
                 for v in vals], dtype=object)
            vecs.append(_column_to_vec(decoded, name))
        out_names.append(name)
    key = destination_frame or dkv.make_key("avro")
    fr = Frame(out_names, vecs, key=key)
    dkv.put(key, fr)
    return fr
