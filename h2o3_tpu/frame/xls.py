"""Excel import: .xlsx (OOXML zip) and legacy .xls (CFB + BIFF8) readers.

Reference: ``h2o-core/src/main/java/water/parser/XlsParser.java`` (a
from-scratch BIFF record reader) — this module re-implements both the
legacy BIFF8 path and the modern OOXML path from the public file-format
specs, with no spreadsheet library (none is in this image).

Scope mirrors the reference parser: the FIRST worksheet, first row as the
header when it is all-text, cells of numeric / text / boolean / shared-
string kinds; formulas import their cached value where present.
"""

from __future__ import annotations

import struct
import zipfile
from typing import Dict, List, Optional, Tuple
from xml.etree import ElementTree

import numpy as np


# ------------------------------------------------------------------- xlsx

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
_REL_NS = ("{http://schemas.openxmlformats.org/officeDocument/2006/"
           "relationships}")


def _col_index(ref: str) -> int:
    """'BC12' -> zero-based column 54."""
    acc = 0
    for ch in ref:
        if ch.isdigit():
            break
        acc = acc * 26 + (ord(ch.upper()) - 64)
    return acc - 1


def _read_xlsx_rows(path_or_buf) -> List[List[object]]:
    zf = zipfile.ZipFile(path_or_buf)
    shared: List[str] = []
    if "xl/sharedStrings.xml" in zf.namelist():
        root = ElementTree.fromstring(zf.read("xl/sharedStrings.xml"))
        for si in root.iter(f"{_NS}si"):
            shared.append("".join(t.text or "" for t in si.iter(f"{_NS}t")))
    # first sheet in workbook order (sheet rIds -> worksheet parts)
    wb = ElementTree.fromstring(zf.read("xl/workbook.xml"))
    rels = ElementTree.fromstring(zf.read("xl/_rels/workbook.xml.rels"))
    rel_map = {r.get("Id"): r.get("Target") for r in rels}
    first = next(iter(wb.iter(f"{_NS}sheet")))
    target = rel_map[first.get(f"{_REL_NS}id")].lstrip("/")
    if not target.startswith("xl/"):
        target = "xl/" + target
    sheet = ElementTree.fromstring(zf.read(target))

    rows: List[List[object]] = []
    for row in sheet.iter(f"{_NS}row"):
        out: List[object] = []
        for c in row.iter(f"{_NS}c"):
            ref = c.get("r") or ""
            j = _col_index(ref) if ref else len(out)
            while len(out) <= j:
                out.append(None)
            t = c.get("t", "n")
            v = c.find(f"{_NS}v")
            if t == "inlineStr":
                is_el = c.find(f"{_NS}is")
                out[j] = "".join(tt.text or ""
                                 for tt in is_el.iter(f"{_NS}t")) \
                    if is_el is not None else None
            elif v is None or v.text is None:
                out[j] = None
            elif t == "s":
                out[j] = shared[int(v.text)]
            elif t == "b":
                out[j] = float(int(v.text))
            elif t in ("str", "e"):
                out[j] = v.text
            else:                                      # numeric
                out[j] = float(v.text)
        rows.append(out)
    return rows


# ------------------------------------------------- legacy .xls (CFB + BIFF8)

_CFB_MAGIC = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
_FREE = 0xFFFFFFFF
_ENDCHAIN = 0xFFFFFFFE


def _cfb_stream(raw: bytes, names=("Workbook", "Book")) -> bytes:
    """Extract a named stream from a Compound File Binary container
    (the OLE2 wrapper every legacy .xls lives in)."""
    if raw[:8] != _CFB_MAGIC:
        raise ValueError("not a legacy .xls (missing CFB magic)")
    sect_shift = struct.unpack_from("<H", raw, 30)[0]
    mini_shift = struct.unpack_from("<H", raw, 32)[0]
    ssz, mssz = 1 << sect_shift, 1 << mini_shift
    n_fat = struct.unpack_from("<I", raw, 44)[0]
    dir_start = struct.unpack_from("<I", raw, 48)[0]
    mini_cutoff = struct.unpack_from("<I", raw, 56)[0]
    minifat_start = struct.unpack_from("<I", raw, 60)[0]
    difat_start = struct.unpack_from("<I", raw, 68)[0]

    def sector(i: int) -> bytes:
        off = 512 + i * ssz
        return raw[off: off + ssz]

    # FAT sector list: 109 header DIFAT entries + DIFAT chain
    max_sectors = (len(raw) - 512) // ssz + 2     # cycle guard bound
    difat = list(struct.unpack_from("<109I", raw, 76))
    nxt = difat_start
    guard = 0
    while nxt not in (_FREE, _ENDCHAIN) and guard < max_sectors:
        s = sector(nxt)
        entries = struct.unpack(f"<{ssz // 4}I", s)
        difat.extend(entries[:-1])
        nxt = entries[-1]
        guard += 1
    fat: List[int] = []
    for si in difat[:n_fat]:
        if si in (_FREE, _ENDCHAIN):
            continue
        fat.extend(struct.unpack(f"<{ssz // 4}I", sector(si)))

    def chain(start: int) -> bytes:
        out, cur, guard = [], start, 0
        while cur not in (_FREE, _ENDCHAIN) and guard < len(fat) + 2:
            out.append(sector(cur))
            cur = fat[cur] if cur < len(fat) else _ENDCHAIN
            guard += 1
        return b"".join(out)

    directory = chain(dir_start)
    root_start = None
    target = None
    for off in range(0, len(directory) - 127, 128):
        entry = directory[off: off + 128]
        name_len = struct.unpack_from("<H", entry, 64)[0]
        name = entry[: max(name_len - 2, 0)].decode("utf-16-le",
                                                    errors="replace")
        obj_type = entry[66]
        start = struct.unpack_from("<I", entry, 116)[0]
        size = struct.unpack_from("<Q", entry, 120)[0]
        if obj_type == 5:                              # root: mini stream
            root_start = start
        if name in names and obj_type == 2:
            target = (start, size)
    if target is None:
        raise ValueError("no Workbook stream in .xls container")
    start, size = target
    if size >= mini_cutoff:
        return chain(start)[:size]
    # small stream: walk the mini FAT within the root's mini stream
    mini_stream = chain(root_start) if root_start is not None else b""
    minifat: List[int] = []
    cur = minifat_start
    guard = 0
    while cur not in (_FREE, _ENDCHAIN) and guard < max_sectors:
        minifat.extend(struct.unpack(f"<{ssz // 4}I", sector(cur)))
        cur = fat[cur] if cur < len(fat) else _ENDCHAIN
        guard += 1
    out, cur, guard = [], start, 0
    while cur not in (_FREE, _ENDCHAIN) and guard < len(minifat) + 2:
        out.append(mini_stream[cur * mssz: (cur + 1) * mssz])
        cur = minifat[cur]
        guard += 1
    return b"".join(out)[:size]


def _rk_value(rk: int) -> float:
    """BIFF RK number: packed 30-bit float-or-int with a /100 flag."""
    div100 = rk & 1
    is_int = rk & 2
    if is_int:
        v = float(rk >> 2 if rk >> 2 < (1 << 29) else (rk >> 2) - (1 << 30))
    else:
        v = struct.unpack("<d", b"\x00\x00\x00\x00"
                          + struct.pack("<I", rk & 0xFFFFFFFC))[0]
    return v / 100.0 if div100 else v


def _read_biff_rows(stream: bytes) -> List[List[object]]:
    """Walk BIFF8 records of the first worksheet substream."""
    cells: Dict[Tuple[int, int], object] = {}
    sst: List[str] = []
    pos = 0
    in_sheet_substream = 0          # 0 = globals, 1 = first sheet, 2 = done

    def _sst_strings(chunks: List[bytes]):
        """Parse the Shared String Table across its CONTINUE records.

        Real-world SSTs exceed one 8224-byte record; character data may
        straddle a record boundary, where the continuation re-emits a
        fresh option-flags byte (so a string can switch between
        compressed and utf-16 mid-stream) — [MS-XLS] 2.5.293."""
        ci, p = 0, 0

        def _avail() -> int:
            return len(chunks[ci]) - p if ci < len(chunks) else 0

        def _read(n: int) -> bytes:
            """Raw read crossing boundaries (headers/rich data only —
            no option byte is re-emitted inside these)."""
            nonlocal ci, p
            out = bytearray()
            while n > 0 and ci < len(chunks):
                if _avail() == 0:
                    ci += 1
                    p = 0
                    continue
                take = min(n, _avail())
                out += chunks[ci][p: p + take]
                p += take
                n -= take
            return bytes(out)

        header = _read(8)
        if len(header) < 8:
            return
        cnt = struct.unpack_from("<I", header, 4)[0]
        for _ in range(cnt):
            head = _read(3)
            if len(head) < 3:
                break
            ln, flags = struct.unpack("<HB", head)
            nrich = struct.unpack("<H", _read(2))[0] if flags & 0x08 else 0
            next_ = struct.unpack("<I", _read(4))[0] if flags & 0x04 else 0
            wide = flags & 0x01
            parts = []
            remaining = ln
            while remaining > 0 and ci < len(chunks):
                if _avail() == 0:
                    ci += 1
                    p = 0
                    if ci < len(chunks) and len(chunks[ci]):
                        wide = chunks[ci][p] & 0x01    # boundary flag byte
                        p += 1
                    continue
                unit = 2 if wide else 1
                nbytes = min(_avail(), remaining * unit)
                if wide:
                    nbytes -= nbytes % 2
                if nbytes == 0:                        # split utf-16 pair
                    ci += 1
                    p = 0
                    continue
                seg = chunks[ci][p: p + nbytes]
                p += nbytes
                parts.append(seg.decode(
                    "utf-16-le" if wide else "latin-1", errors="replace"))
                remaining -= nbytes // unit
            _read(4 * nrich + next_)                   # rich runs / ext data
            sst.append("".join(parts))

    while pos + 4 <= len(stream):
        opcode, ln = struct.unpack_from("<HH", stream, pos)
        payload = stream[pos + 4: pos + 4 + ln]
        pos += 4 + ln
        if opcode == 0x0809:                           # BOF
            if in_sheet_substream == 0 and \
                    struct.unpack_from("<H", payload, 2)[0] == 0x0010:
                in_sheet_substream = 1                 # first sheet BOF
            elif in_sheet_substream >= 1 and \
                    struct.unpack_from("<H", payload, 2)[0] == 0x0010:
                in_sheet_substream = 2                 # later sheet: stop
        elif opcode == 0x000A:                         # EOF
            if in_sheet_substream == 1:
                break
        elif opcode == 0x00FC:                         # SST (globals)
            chunks = [payload]
            while pos + 4 <= len(stream):              # gather CONTINUEs
                op2, ln2 = struct.unpack_from("<HH", stream, pos)
                if op2 != 0x003C:
                    break
                chunks.append(stream[pos + 4: pos + 4 + ln2])
                pos += 4 + ln2
            _sst_strings(chunks)
        elif in_sheet_substream != 1:
            continue
        elif opcode == 0x0203:                         # NUMBER
            rw, col = struct.unpack_from("<HH", payload, 0)
            cells[rw, col] = struct.unpack_from("<d", payload, 6)[0]
        elif opcode == 0x027E:                         # RK
            rw, col = struct.unpack_from("<HH", payload, 0)
            cells[rw, col] = _rk_value(
                struct.unpack_from("<I", payload, 6)[0])
        elif opcode == 0x00BD:                         # MULRK
            rw, first_col = struct.unpack_from("<HH", payload, 0)
            n = (ln - 6) // 6
            for i in range(n):
                rk = struct.unpack_from("<I", payload, 4 + 6 * i + 2)[0]
                cells[rw, first_col + i] = _rk_value(rk)
        elif opcode == 0x00FD:                         # LABELSST
            rw, col = struct.unpack_from("<HH", payload, 0)
            idx = struct.unpack_from("<I", payload, 6)[0]
            cells[rw, col] = sst[idx] if idx < len(sst) else None
        elif opcode == 0x0204:                         # LABEL (pre-SST)
            rw, col = struct.unpack_from("<HH", payload, 0)
            sl = struct.unpack_from("<H", payload, 6)[0]
            cells[rw, col] = payload[8: 8 + sl].decode("latin-1")
        elif opcode == 0x0205:                         # BOOLERR
            rw, col = struct.unpack_from("<HH", payload, 0)
            val, is_err = payload[6], payload[7]
            cells[rw, col] = None if is_err else float(val)
        elif opcode == 0x0006:                         # FORMULA: cached num
            rw, col = struct.unpack_from("<HH", payload, 0)
            if payload[12:14] != b"\xff\xff":
                cells[rw, col] = struct.unpack_from("<d", payload, 6)[0]

    if not cells:
        return []
    max_r = max(k[0] for k in cells)
    max_c = max(k[1] for k in cells)
    return [[cells.get((r, c)) for c in range(max_c + 1)]
            for r in range(max_r + 1)]


# ------------------------------------------------------------- Frame glue

def _rows_to_frame(rows: List[List[object]],
                   destination_frame: Optional[str], kind: str):
    from ..runtime import dkv
    from .frame import Frame
    from .parse import _column_to_vec

    rows = [r for r in rows if any(v is not None and v != "" for v in r)]
    if not rows:
        raise ValueError("empty spreadsheet")
    width = max(len(r) for r in rows)
    rows = [r + [None] * (width - len(r)) for r in rows]
    header_row = rows[0]
    all_text = all(isinstance(v, str) or v is None for v in header_row) \
        and any(isinstance(v, str) for v in header_row)
    if all_text:
        names = [str(v) if v not in (None, "") else f"C{j + 1}"
                 for j, v in enumerate(header_row)]
        body = rows[1:]
    else:
        names = [f"C{j + 1}" for j in range(width)]
        body = rows
    vecs = []
    for j, name in enumerate(names):
        col = [r[j] for r in body]
        if all(isinstance(v, (int, float)) or v is None for v in col):
            arr = np.array([np.nan if v is None else float(v)
                            for v in col], np.float64)
            from .vec import Vec, T_NUM
            vecs.append(Vec.from_numpy(arr, T_NUM))
        else:
            svals = np.array(["" if v is None else str(v) for v in col],
                             dtype=object)
            vecs.append(_column_to_vec(svals, name))
    key = destination_frame or dkv.make_key(kind)
    fr = Frame(names, vecs, key=key)
    dkv.put(key, fr)
    return fr


def parse_xlsx(path_or_buf, destination_frame: Optional[str] = None):
    """.xlsx (OOXML) -> Frame."""
    return _rows_to_frame(_read_xlsx_rows(path_or_buf),
                          destination_frame, "xlsx")


def parse_xls(path_or_buf, destination_frame: Optional[str] = None):
    """Legacy .xls (CFB/BIFF8) -> Frame (XlsParser.java analog)."""
    if isinstance(path_or_buf, (bytes, bytearray)):
        raw = bytes(path_or_buf)
    else:
        with open(path_or_buf, "rb") as fh:
            raw = fh.read()
    return _rows_to_frame(_read_biff_rows(_cfb_stream(raw)),
                          destination_frame, "xls")
