"""Frame: a named columnar table of Vecs, row-sharded over the mesh.

Reference: ``water/fvec/Frame.java:65`` — a Frame is an ordered set of column
names + Vec keys, lockable for R/W coherence, living in the DKV.  Columns are
chunked identically (VectorGroup, Vec.java:1528) so row i of every column is
on the same node.

TPU-native redesign: every Vec payload is a ``jax.Array`` sharded with the
same NamedSharding over the mesh "rows" axis, which gives the VectorGroup
row-alignment property by construction.  There is no lock protocol — Frames
are functionally immutable (mutation returns a new Frame), which is what XLA
wants anyway.  ``matrix()`` materializes a [rows, features] design block for
the algorithms (the hot path feeding the MXU) and caches it on the Frame the
way the reference caches rollups.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.cluster import cluster
from ..runtime import dkv
from .vec import Vec, T_CAT, T_NUM, T_STR, T_TIME


class Frame:
    def __init__(self, names: Sequence[str], vecs: Sequence[Vec],
                 key: Optional[str] = None):
        if len(names) != len(vecs):
            raise ValueError("names/vecs length mismatch")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {list(names)}")
        nrows = {v.nrows for v in vecs}
        if len(nrows) > 1:
            raise ValueError(f"vecs disagree on nrows: {nrows}")
        self.names: List[str] = list(names)
        self.vecs: List[Vec] = list(vecs)
        self.nrows: int = vecs[0].nrows if vecs else 0
        self.key = key
        self._matrix_cache: Dict[tuple, jax.Array] = {}
        self._atime = time.monotonic()       # LRU clock for the Cleaner
        self._lineage: Optional[dict] = None  # frame/lineage.py provenance
        if key is not None:
            dkv.put(key, self)

    # ------------------------------------------------------------- accessors
    @property
    def ncols(self) -> int:
        return len(self.vecs)

    @property
    def shape(self):
        return (self.nrows, self.ncols)

    @property
    def padded_rows(self) -> int:
        return self.vecs[0].padded_len if self.vecs else 0

    def vec(self, name: str) -> Vec:
        self._atime = time.monotonic()
        try:
            return self.vecs[self.names.index(name)]
        except ValueError:
            raise KeyError(f"no column {name!r} in frame (have {self.names})")

    def __getitem__(self, cols) -> "Frame":
        if isinstance(cols, str):
            cols = [cols]
        from . import lineage
        return lineage.derive(Frame(cols, [self.vec(c) for c in cols]),
                              self, {"op": "cols", "cols": list(cols)})

    def types(self) -> Dict[str, str]:
        return {n: v.type for n, v in zip(self.names, self.vecs)}

    def valid_mask(self) -> jax.Array:
        return self.vecs[0].valid_mask()

    # ------------------------------------------------------------ construct
    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray], key: Optional[str] = None,
                   types: Optional[Dict[str, str]] = None,
                   domains: Optional[Dict[str, Sequence[str]]] = None) -> "Frame":
        """Build a Frame from host columns (tests' TestFrameBuilder analog)."""
        types = types or {}
        domains = domains or {}
        names, vecs = [], []
        for name, arr in arrays.items():
            arr = np.asarray(arr)
            vtype = types.get(name)
            domain = domains.get(name)
            if vtype is None:
                if arr.dtype == object or arr.dtype.kind in "US":
                    labels, codes = np.unique(arr.astype(str), return_inverse=True)
                    vtype, domain, arr = T_CAT, [str(l) for l in labels], codes
                else:
                    vtype = T_NUM
            names.append(name)
            vecs.append(Vec.from_numpy(arr, vtype, domain=domain))
        return Frame(names, vecs, key=key)

    # --------------------------------------------------------------- munging
    def cbind(self, other: "Frame") -> "Frame":
        if other.nrows != self.nrows:
            raise ValueError("cbind: row counts differ")
        return Frame(self.names + other.names, self.vecs + other.vecs)

    def rename(self, mapping: Dict[str, str]) -> "Frame":
        from . import lineage
        return lineage.derive(
            Frame([mapping.get(n, n) for n in self.names], self.vecs),
            self, {"op": "rename", "mapping": dict(mapping)})

    def drop(self, cols: Sequence[str]) -> "Frame":
        cols = set([cols] if isinstance(cols, str) else cols)
        keep = [(n, v) for n, v in zip(self.names, self.vecs) if n not in cols]
        from . import lineage
        return lineage.derive(
            Frame([n for n, _ in keep], [v for _, v in keep]),
            self, {"op": "drop", "cols": sorted(cols)})

    def with_vec(self, name: str, vec: Vec) -> "Frame":
        if name in self.names:
            vecs = list(self.vecs)
            vecs[self.names.index(name)] = vec
            return Frame(self.names, vecs)
        return Frame(self.names + [name], self.vecs + [vec])

    def rows(self, index: np.ndarray) -> "Frame":
        """Row subset by integer index (host-driven gather, re-sharded)."""
        index = np.asarray(index)
        out = []
        for v in self.vecs:
            if v.data is None:
                out.append(Vec.from_numpy(v.host_data[: v.nrows][index], v.type))
            else:
                col = np.asarray(v.data)[: v.nrows][index]
                out.append(Vec.from_numpy(col, v.type, domain=v.domain,
                                          time_base=v.time_base))
        from . import lineage
        return lineage.derive_rows(Frame(self.names, out), self, index)

    def filter(self, mask: np.ndarray) -> "Frame":
        mask = np.asarray(mask, dtype=bool)
        return self.rows(np.nonzero(mask[: self.nrows])[0])

    def split_frame(self, ratios: Sequence[float], seed: int = 0) -> List["Frame"]:
        """Random row split — analog of h2o.split_frame (random uniform)."""
        rng = np.random.default_rng(seed)
        u = rng.random(self.nrows)
        bounds = np.cumsum(list(ratios))
        if len(bounds) == 0 or bounds[-1] < 1.0 - 1e-9:
            bounds = np.append(bounds, 1.0)
        bounds[-1] = np.inf  # last piece takes everything remaining
        pieces, lo = [], 0.0
        from . import lineage
        for i, hi in enumerate(bounds):
            p = self.filter((u >= lo) & (u < hi))
            # a (ratios, seed, piece) triple replays smaller than the
            # row index the filter recorded — override it
            lineage.derive(p, self, {"op": "split",
                                     "ratios": [float(r) for r in ratios],
                                     "seed": int(seed), "piece": i})
            pieces.append(p)
            lo = hi
        return pieces

    # ---------------------------------------------------------- device views
    def matrix(self, cols: Optional[Sequence[str]] = None,
               dtype=jnp.float32) -> jax.Array:
        """[padded_rows, len(cols)] design block; cats as raw codes (-1 NA).

        The MXU feed: column Vec payloads stacked into one row-sharded 2-D
        array.  Cached per column-set (the reference caches the per-algo
        DataInfo adaptation similarly, hex/DataInfo.java).
        """
        self._atime = time.monotonic()
        cols = list(cols) if cols is not None else list(self.names)
        ck = (tuple(cols), str(dtype))
        hit = self._matrix_cache.get(ck)
        if hit is not None:
            return hit
        cl = cluster()
        parts = []
        for c in cols:
            v = self.vec(c)
            if v.data is None:
                raise TypeError(f"column {c!r} of type {v.type} is host-only")
            parts.append(v.data.astype(dtype))
        mat = jnp.stack(parts, axis=1)
        from ..runtime.cluster import put_sharded
        mat = put_sharded(mat, cl.matrix_sharding)
        self._matrix_cache[ck] = mat
        return mat

    # ---------------------------------------------------------------- export
    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({n: v.decoded() for n, v in zip(self.names, self.vecs)})

    # ------------------------------------------------- munging sugar
    # h2o-py's H2OFrame carries the munging verbs as methods; the device
    # implementations live in rapids/ops.py and these delegate.
    def sort(self, by, ascending=True) -> "Frame":
        from ..rapids import ops
        return ops.sort(self, by, ascending=ascending)

    def merge(self, other: "Frame", by, how: str = "inner") -> "Frame":
        from ..rapids import ops
        return ops.merge(self, other, by, how=how)

    def group_by(self, by, aggs) -> "Frame":
        from ..rapids import ops
        return ops.group_by(self, by, aggs)

    def impute(self, column: str, method: str = "mean",
               combine_method: str = "interpolate") -> "Frame":
        from ..rapids import ops
        return ops.impute(self, column, method=method,
                          combine_method=combine_method)

    def scale(self, center: bool = True, scale: bool = True) -> "Frame":
        from ..rapids import ops
        return ops.scale(self, center=center, scale_=scale)

    def cor(self, cols=None, use: str = "complete.obs"):
        from ..rapids import ops
        return ops.cor(self, cols, use=use)

    def var(self, cols=None, use: str = "complete.obs"):
        from ..rapids import ops
        return ops.var(self, cols, use=use)

    def spill(self) -> int:
        """Evict all device payloads to host RAM (Cleaner analog)."""
        freed = sum(int(m.nbytes) for m in self._matrix_cache.values())
        self._matrix_cache.clear()
        return freed + sum(v.spill() for v in self.vecs)

    def to_numpy(self) -> np.ndarray:
        return np.stack([np.asarray(v.to_numpy(), dtype=np.float64)
                         for v in self.vecs], axis=1)

    def head(self, n: int = 10):
        return self.to_pandas().head(n)

    def describe(self) -> "Dict[str, dict]":
        """h2o-py H2OFrame.describe() alias for summary()."""
        return self.summary()

    def warm_rollups(self) -> None:
        """Batch-compute rollups for every device column that lacks them —
        ONE fused program + ONE fetch (RollupStats' lazy-compute contract,
        but frame-wide: per-column eager rollups cost a dispatch round trip
        each, measured ~0.4 s/column on a tunnelled TPU)."""
        from .vec import RollupStats, _batch_rollup_kernel
        # membership test must NOT touch v.data: the getter transparently
        # restores spilled payloads, and restoring ALL columns up-front
        # would defeat the spill mechanism (blocks restore lazily below)
        todo = [v for v in self.vecs
                if v._rollups is None
                and (v._device is not None or v._spill is not None)
                and not (v.type == T_TIME and v.host_data is not None)]
        if len(todo) < 2:
            return
        import jax
        # block the stack: a single [C, padded] copy of a wide frame near
        # HBM capacity would defeat the Vec spill mechanism it exists for
        blk = max(2, 268_435_456 // (4 * max(todo[0].padded_len, 1)))
        for lo in range(0, len(todo), blk):
            chunk = todo[lo: lo + blk]
            X = jnp.stack([v.numeric_data() for v in chunk], axis=0)
            cnt, mean, var, vmin, vmax, nzero = (
                np.asarray(a) for a in jax.device_get(
                    _batch_rollup_kernel(X, chunk[0].nrows)))
            for i, v in enumerate(chunk):
                n = int(cnt[i])
                v._rollups = RollupStats(
                    nrows=v.nrows, nmissing=v.nrows - n,
                    mean=float(mean[i]) if n else float("nan"),
                    sigma=(float(np.sqrt(max(float(var[i]), 0.0)))
                           if n > 1 else float("nan")),
                    vmin=float(vmin[i]) if n else float("nan"),
                    vmax=float(vmax[i]) if n else float("nan"),
                    nzero=int(nzero[i]))

    def summary(self) -> Dict[str, dict]:
        self.warm_rollups()
        out = {}
        for name, v in zip(self.names, self.vecs):
            if v.data is None:
                out[name] = {"type": v.type, "missing": v.rollups().nmissing}
            else:
                r = v.rollups()
                out[name] = {"type": v.type, "min": r.vmin, "max": r.vmax,
                             "mean": r.mean, "sigma": r.sigma,
                             "missing": r.nmissing, "zeros": r.nzero,
                             "cardinality": v.cardinality}
        return out

    def __repr__(self):
        return f"<Frame {self.key or ''} {self.nrows}x{self.ncols} {self.names[:8]}>"
