"""SQL table import — ``h2o.import_sql_table`` / JDBC analog.

Reference: ``h2o-core/src/main/java/water/jdbc/SQLManager.java`` — ranged
SELECTs fan out over the cluster via JDBC.  Python-side the natural
transport is DB-API 2.0: sqlite is built in; anything else works by
passing an already-open DB-API connection (psycopg2, mysql-connector,
…) — the import itself only uses cursor/execute/fetchmany.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from .frame import Frame
from .parse import _column_to_vec
from ..runtime import dkv


def _connect(connection_url: str):
    if connection_url.startswith(("sqlite://", "jdbc:sqlite:")):
        import sqlite3
        path = connection_url.split("sqlite:", 1)[1]
        if path.startswith("//"):
            path = path[2:]              # sqlite://<path> (absolute or rel)
        if path in ("", ":memory:"):
            path = ":memory:"
        return sqlite3.connect(path)
    raise NotImplementedError(
        f"no built-in driver for {connection_url!r}: sqlite:// URLs work "
        "out of the box; for other databases pass an open DB-API "
        "connection object instead of a URL")


def import_sql_select(connection_or_url, select_query: str,
                      destination_frame: Optional[str] = None,
                      fetch_size: int = 100_000) -> Frame:
    """Run a SELECT and build a Frame — import_sql_select analog."""
    owns = isinstance(connection_or_url, str)
    conn = _connect(connection_or_url) if owns else connection_or_url
    try:
        cur = conn.cursor()
        try:
            cur.execute(select_query)
            names = [d[0] for d in cur.description]
            chunks: List[list] = [[] for _ in names]
            while True:
                rows = cur.fetchmany(fetch_size)
                if not rows:
                    break
                for row in rows:
                    for j, v in enumerate(row):
                        chunks[j].append(v)
        finally:
            cur.close()
    finally:
        if owns:
            conn.close()
    vecs = []
    for name, vals in zip(names, chunks):
        # numeric columns stay numeric; everything else goes through the
        # canonical parser type-guesser (_column_to_vec) unchanged
        if all(v is None or isinstance(v, (int, float)) for v in vals):
            arr = np.asarray([np.nan if v is None else float(v)
                              for v in vals], np.float64)
        else:
            arr = np.asarray(["" if v is None else str(v) for v in vals],
                             dtype=object)
        vecs.append(_column_to_vec(arr, name))
    return Frame(names, vecs,
                 key=destination_frame or dkv.make_key("sql"))


def import_sql_table(connection_or_url, table: str,
                     columns: Optional[Iterable[str]] = None,
                     destination_frame: Optional[str] = None) -> Frame:
    """Import a whole table — h2o.import_sql_table analog."""
    def _ident_ok(name: str) -> bool:
        return bool(name) and name.replace("_", "").replace(".", "") \
            .isalnum()
    if columns:
        for c in columns:
            if not _ident_ok(c):
                raise ValueError(f"suspicious column name {c!r}")
    collist = ", ".join(columns) if columns else "*"
    if not _ident_ok(table):
        raise ValueError(f"suspicious table name {table!r}")
    return import_sql_select(connection_or_url,
                             f"SELECT {collist} FROM {table}",  # noqa: S608
                             destination_frame=destination_frame)
