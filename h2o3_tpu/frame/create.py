"""Frame synthesis + munging utilities behind small REST handlers.

Reference handlers: ``water/api/CreateFrameHandler.java`` (h2o.create_frame
random frames), ``MissingInserterHandler.java`` (NA injection),
``InteractionHandler.java`` (categorical interaction columns,
``hex/Interaction.java``), ``TabulateHandler.java`` (``hex/Tabulate.java``
2-column co-occurrence + response means), ``DCTTransformerHandler.java``
(``hex/DCTTransformer.java``).

TPU-native notes: the DCT is expressed as a dense cosine-basis matmul
(MXU-friendly; the reference loops per element), and tabulation is a
one-hot × one-hot cross product — the same trick the histogram kernel
uses.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .frame import Frame
from .vec import Vec, T_CAT, T_NUM
from ..runtime import dkv


def create_frame(rows: int = 10_000, cols: int = 10,
                 randomize: bool = True, value: float = 0.0,
                 real_range: float = 100.0,
                 categorical_fraction: float = 0.2, factors: int = 100,
                 integer_fraction: float = 0.2, integer_range: int = 100,
                 binary_fraction: float = 0.1, binary_ones_fraction: float = 0.02,
                 time_fraction: float = 0.0, string_fraction: float = 0.0,
                 missing_fraction: float = 0.01,
                 has_response: bool = False, response_factors: int = 2,
                 positive_response: bool = False, seed: Optional[int] = None,
                 destination_frame: Optional[str] = None) -> Frame:
    """h2o.create_frame analog (CreateFrameHandler/CreateFrame.java)."""
    fracs = (categorical_fraction + integer_fraction + binary_fraction
             + time_fraction + string_fraction)
    if fracs > 1.0 + 1e-9:
        raise ValueError("column-type fractions sum past 1.0")
    rng = np.random.default_rng(seed)
    counts = {
        "cat": int(round(cols * categorical_fraction)),
        "int": int(round(cols * integer_fraction)),
        "bin": int(round(cols * binary_fraction)),
        "time": int(round(cols * time_fraction)),
        "str": int(round(cols * string_fraction)),
    }
    counts["real"] = cols - sum(counts.values())
    if counts["real"] < 0:
        raise ValueError("column-type fractions produce negative real count")
    names: List[str] = []
    vecs: List[Vec] = []

    def _with_missing(arr: np.ndarray) -> np.ndarray:
        if missing_fraction > 0:
            mask = rng.random(rows) < missing_fraction
            arr = arr.astype(np.float64)
            arr[mask] = np.nan
        return arr

    j = 0
    for _ in range(counts["real"]):
        vals = (rng.uniform(-real_range, real_range, rows) if randomize
                else np.full(rows, value))
        vecs.append(Vec.from_numpy(_with_missing(vals), T_NUM))
        names.append(f"C{(j := j + 1)}")
    for _ in range(counts["int"]):
        vals = rng.integers(-integer_range, integer_range + 1,
                            rows).astype(np.float64)
        vecs.append(Vec.from_numpy(_with_missing(vals), T_NUM))
        names.append(f"C{(j := j + 1)}")
    for _ in range(counts["bin"]):
        vals = (rng.random(rows) < binary_ones_fraction).astype(np.float64)
        vecs.append(Vec.from_numpy(_with_missing(vals), T_NUM))
        names.append(f"C{(j := j + 1)}")
    for _ in range(counts["time"]):
        base = 1_500_000_000_000.0
        vals = base + rng.uniform(0, 3.15e10, rows)
        from .vec import T_TIME
        vecs.append(Vec.from_numpy(_with_missing(vals), T_TIME))
        names.append(f"C{(j := j + 1)}")
    for _ in range(counts["cat"]):
        codes = rng.integers(0, max(factors, 1), rows).astype(np.int32)
        if missing_fraction > 0:
            codes = np.where(rng.random(rows) < missing_fraction,
                             -1, codes).astype(np.int32)
        dom = [f"c{i}.l{k}" for i, k in
               zip([j] * factors, range(factors))]
        vecs.append(Vec.from_numpy(codes, T_CAT, domain=dom))
        names.append(f"C{(j := j + 1)}")
    for _ in range(counts["str"]):
        host = np.array([f"s{rng.integers(0, 1 << 30):x}"
                         for _ in range(rows)], dtype=object)
        from .vec import T_STR
        vecs.append(Vec(None, T_STR, rows, host_data=host))
        names.append(f"C{(j := j + 1)}")
    if has_response:
        if response_factors > 1:
            codes = rng.integers(0, response_factors, rows).astype(np.int32)
            dom = [f"level{k}" for k in range(response_factors)]
            vecs.insert(0, Vec.from_numpy(codes, T_CAT, domain=dom))
        else:
            vals = rng.uniform(0 if positive_response else -real_range,
                               real_range, rows)
            vecs.insert(0, Vec.from_numpy(vals, T_NUM))
        names.insert(0, "response")
    key = destination_frame or dkv.make_key("createframe")
    return Frame(names, vecs, key=key)


def insert_missing_values(frame: Frame, fraction: float = 0.1,
                          seed: Optional[int] = None) -> Frame:
    """In-place NA injection — MissingInserterHandler analog."""
    rng = np.random.default_rng(seed)
    new_vecs = []
    for vec in frame.vecs:
        if vec.data is None:                   # string vecs: host path
            host = vec.host_data.copy()
            host[rng.random(frame.nrows) < fraction] = None
            new_vecs.append(Vec(None, vec.type, vec.nrows, host_data=host))
            continue
        vals = vec.to_numpy().copy()
        mask = rng.random(len(vals)) < fraction
        if vec.type == T_CAT:
            vals = np.where(mask, -1, vals).astype(np.int32)
            new_vecs.append(Vec.from_numpy(vals, T_CAT, domain=vec.domain))
        else:
            vals = vals.astype(np.float64)
            vals[mask] = np.nan
            new_vecs.append(Vec.from_numpy(vals, vec.type))
    out = Frame(frame.names, new_vecs, key=None)
    out.key = frame.key
    if frame.key:
        dkv.put(frame.key, out)
    return out


def interaction(frame: Frame, factor_columns: Sequence[str],
                pairwise: bool = False, max_factors: int = 100,
                min_occurrence: int = 1,
                destination_frame: Optional[str] = None) -> Frame:
    """Categorical interaction features — hex/Interaction.java analog.

    Combines the named factor columns into one interaction column (or all
    pairwise combinations), keeping the ``max_factors`` most frequent
    combined levels (rest pooled into ``other``).
    """
    cols = list(factor_columns)
    if len(cols) < 2:
        raise ValueError("interaction needs >= 2 factor columns")
    for c in cols:
        if frame.vec(c).type != T_CAT:
            raise ValueError(f"interaction column {c!r} is not categorical")
    groups = ([(a, b) for i, a in enumerate(cols) for b in cols[i + 1:]]
              if pairwise else [tuple(cols)])
    names: List[str] = []
    vecs: List[Vec] = []
    for group in groups:
        gvecs = [frame.vec(c) for c in group]
        codes = [np.asarray(v.to_numpy()).astype(np.int64) for v in gvecs]
        doms = [v.domain or [] for v in gvecs]
        combo = np.zeros(frame.nrows, np.int64)
        valid = np.ones(frame.nrows, bool)
        for c, d in zip(codes, doms):
            combo = combo * max(len(d), 1) + np.clip(c, 0, None)
            valid &= c >= 0
        labels = {}
        for idx in np.flatnonzero(valid):
            labels.setdefault(int(combo[idx]), 0)
            labels[int(combo[idx])] += 1
        kept = [k for k, n in sorted(labels.items(),
                                     key=lambda kv: -kv[1])
                if n >= min_occurrence][:max_factors]
        kept_set = {k: i for i, k in enumerate(kept)}

        def decode(k: int) -> str:
            parts = []
            for d in reversed(doms):
                parts.append(str(d[k % max(len(d), 1)]))
                k //= max(len(d), 1)
            return "_".join(reversed(parts))

        domain = [decode(k) for k in kept]
        other = len(domain)
        has_other = len(labels) > len(kept)
        if has_other:
            domain = domain + ["other"]
        out_codes = np.full(frame.nrows, -1, np.int32)
        for idx in np.flatnonzero(valid):
            out_codes[idx] = kept_set.get(int(combo[idx]), other)
        vecs.append(Vec.from_numpy(out_codes, T_CAT, domain=domain))
        names.append("_".join(group))
    key = destination_frame or dkv.make_key("interaction")
    return Frame(names, vecs, key=key)


def tabulate(frame: Frame, predictor: str, response: str,
             weights_column: Optional[str] = None,
             nbins_predictor: int = 20, nbins_response: int = 10) -> dict:
    """2-column co-occurrence counts + per-level response means —
    hex/Tabulate.java.  Numerics are equal-width binned; the cross table
    is a one-hot x one-hot product (device-friendly form)."""
    def _binned(name: str, nbins: int):
        vec = frame.vec(name)
        vals = np.asarray(vec.to_numpy(), np.float64)
        if vec.type == T_CAT:
            labels = list(vec.domain or [])
            return np.clip(vals, -1, len(labels) - 1).astype(int), labels
        finite = vals[np.isfinite(vals)]
        lo, hi = (float(finite.min()), float(finite.max())) if finite.size \
            else (0.0, 1.0)
        width = (hi - lo) / nbins or 1.0
        safe = np.where(np.isfinite(vals), vals, lo)
        codes = np.where(np.isfinite(vals),
                         np.clip(((safe - lo) / width).astype(int), 0,
                                 nbins - 1), -1)
        labels = [f"[{lo + i * width:.4g}, {lo + (i + 1) * width:.4g})"
                  for i in range(nbins)]
        return codes, labels

    pc, plabels = _binned(predictor, nbins_predictor)
    rc, rlabels = _binned(response, nbins_response)
    w = (np.asarray(frame.vec(weights_column).to_numpy(), np.float64)
         if weights_column else np.ones(frame.nrows))
    P, R = len(plabels), len(rlabels)
    counts = np.zeros((P, R))
    ok = (pc >= 0) & (rc >= 0)
    np.add.at(counts, (pc[ok], rc[ok]), w[ok])
    rvec = frame.vec(response)
    rvals = np.asarray(rvec.to_numpy(), np.float64)
    sums = np.zeros(P)
    wsum = np.zeros(P)
    np.add.at(sums, pc[ok], (rvals * w)[ok])
    np.add.at(wsum, pc[ok], w[ok])
    with np.errstate(invalid="ignore"):
        means = np.where(wsum > 0, sums / wsum, np.nan)
    return {
        "predictor": predictor, "response": response,
        "predictor_levels": plabels, "response_levels": rlabels,
        "count_table": counts.tolist(),
        "response_table": [[lvl, float(m) if np.isfinite(m) else None,
                            float(ws)]
                           for lvl, m, ws in zip(plabels, means, wsum)],
    }


def dct_transform(frame: Frame, dimensions: Sequence[int],
                  inverse: bool = False,
                  destination_frame: Optional[str] = None) -> Frame:
    """Orthonormal DCT-II along each spatial dimension of row-major
    [height, width, depth] columns — hex/DCTTransformer.java.

    TPU-native: the transform is a dense cosine-basis matmul per axis
    (kron-structured), executed as one einsum on device.
    """
    import jax.numpy as jnp

    dims = [int(d) for d in dimensions]
    while len(dims) < 3:
        dims.append(1)
    h, w, d = dims[:3]
    if h * w * d != frame.ncols:
        raise ValueError(f"dimensions {h}x{w}x{d} != ncols {frame.ncols}")

    def basis(n: int) -> np.ndarray:
        k = np.arange(n)[:, None]
        i = np.arange(n)[None, :]
        B = np.cos(np.pi * (2 * i + 1) * k / (2 * n)) * np.sqrt(2.0 / n)
        B[0] /= np.sqrt(2.0)
        return B

    X = np.stack([np.asarray(v.to_numpy(), np.float64)
                  for v in frame.vecs], axis=1)
    N = X.shape[0]
    T = X.reshape(N, h, w, d)
    Bh, Bw, Bd = basis(h), basis(w), basis(d)
    if inverse:
        Bh, Bw, Bd = Bh.T, Bw.T, Bd.T
    out = jnp.einsum("nhwd,Hh,Ww,Dd->nHWD", jnp.asarray(T),
                     jnp.asarray(Bh), jnp.asarray(Bw), jnp.asarray(Bd))
    out = np.asarray(out).reshape(N, h * w * d)
    vecs = [Vec.from_numpy(out[:, jcol], T_NUM)
            for jcol in range(out.shape[1])]
    names = [f"DCT_{i}" for i in range(out.shape[1])]
    key = destination_frame or dkv.make_key("dct")
    return Frame(names, vecs, key=key)
