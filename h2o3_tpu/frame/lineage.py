"""Shard lineage — provenance records that make frame recovery partial.

Reference contract (PAPER.md L4/DKV; runtime/recovery.py:9): data is
never durable — after any host loss the whole frame is re-imported from
source.  This module replaces that cliff with lineage: every shard of a
parsed frame is a deterministic function of a byte range of its source
plus a replayable op chain, so losing a host costs re-deriving *its*
shards, not the dataset (the DrJAX pure-sharded-function view of the
map-reduce plane, applied to ingest).

Three record kinds live under WAL-durable ``!lineage/<frame>`` DKV keys
(plain dicts, so they rehydrate across a coordinator restart):

- ``parse``      — source path, effective parse config, and one shard
  per mesh host: the newline-aligned byte range whose lines ARE that
  host's row block, a sha1 of those source bytes, and (for frames under
  ``lineage_hash_below_mb``) a sha1 of the shard's canonical column
  values for bitwise verification after re-materialization.
- ``derived``    — the root (parse/checkpoint) frame key plus a compact
  list of replayable op descriptors (column select/drop/rename, bounded
  row gathers, split_frame pieces, rapids sort/impute/scale) instead of
  copied provenance.  Chains deeper than ``lineage_max_chain`` force a
  checkpoint-materialization at registration time.
- ``checkpoint`` — a pickled canonical-column snapshot under the
  recovery dir; rebuilding is a load, not a replay.

Hot-frame replicas: frames at or under ``replicate_below_mb`` keep one
replica of every shard's canonical columns under ``!replica/<frame>/<i>``
with a DCN-neighbor placement recorded in the lineage record, so their
recovery is a copy verified by content hash, not a recompute.

``runtime/remat.py`` is the consumer: given the set of lost host/shard
ids it walks these records back to bytes (replica copy → ranged
re-parse + op replay → caller falls back to full re-import).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.config import config
from .vec import T_CAT, T_STR, T_TIME, T_UUID, Vec

LINEAGE_PREFIX = "!lineage/"
REPLICA_PREFIX = "!replica/"

# ops replayed by runtime/remat.py — anything else breaks the chain
REPLAYABLE_OPS = ("cols", "drop", "rename", "rows", "split",
                  "sort", "impute", "scale")


def enabled() -> bool:
    return config().lineage_enabled


def lineage_key(frame_key: str) -> str:
    return LINEAGE_PREFIX + frame_key


def replica_key(frame_key: str, shard: int) -> str:
    return f"{REPLICA_PREFIX}{frame_key}/{shard}"


def get_record(frame_key: str) -> Optional[dict]:
    from ..runtime import dkv
    rec = dkv.get(lineage_key(frame_key))
    return rec if isinstance(rec, dict) else None


def drop_record(frame_key: str) -> None:
    """Remove a frame's lineage + replica records (frame deletion)."""
    from ..runtime import dkv
    try:
        dkv.remove(lineage_key(frame_key))
        for k in dkv.keys(f"{REPLICA_PREFIX}{frame_key}/"):
            dkv.remove(k)
    except Exception:                    # noqa: BLE001 — best-effort
        pass


# ----------------------------------------------------------- canonical values

def canonical_cols(frame) -> List[np.ndarray]:
    """Engine-independent host form of every column (Vec.canonical_host):
    num→f32, cat→i32 codes, time→f64 ms, str/uuid→object."""
    return [v.canonical_host() for v in frame.vecs]


def _canonical_nbytes(cols: Sequence[np.ndarray], types: Sequence[str]) -> int:
    total = 0
    for arr, t in zip(cols, types):
        if t in (T_STR, T_UUID):
            total += sum(len(str(v)) if v is not None else 1 for v in arr)
        else:
            total += int(arr.nbytes)
    return total


def hash_cols(cols: Sequence[np.ndarray], types: Sequence[str],
              lo: int, hi: int) -> str:
    """sha1 over the canonical bytes of rows [lo, hi) of every column —
    the bitwise-equality check for re-materialized/replicated shards."""
    h = hashlib.sha1()
    for arr, t in zip(cols, types):
        part = arr[lo:hi]
        if t in (T_STR, T_UUID):
            h.update("\x1f".join("\x00" if v is None else str(v)
                                 for v in part).encode())
        else:
            h.update(np.ascontiguousarray(part).tobytes())
        h.update(b"|")
    return h.hexdigest()


def schema_of(frame) -> dict:
    return {
        "names": list(frame.names),
        "types": [v.type for v in frame.vecs],
        "domains": {n: [str(x) for x in v.domain]
                    for n, v in zip(frame.names, frame.vecs)
                    if v.type == T_CAT and v.domain is not None},
        "time_base": {n: float(v.time_base)
                      for n, v in zip(frame.names, frame.vecs)
                      if v.type == T_TIME},
    }


def shard_row_bounds(nrows: int, n_shards: int,
                     padded: Optional[int] = None) -> List[Tuple[int, int]]:
    """Per-host logical row blocks, mirroring device placement: the mesh
    is hosts-major, hosts own contiguous blocks of the padded buffer, and
    padding rows live at the tail (so only trailing hosts clip)."""
    if padded is None:
        from ..runtime.cluster import cluster
        padded = cluster().pad_rows(nrows)
    per = max(padded // max(n_shards, 1), 1)
    return [(min(i * per, nrows), min((i + 1) * per, nrows))
            for i in range(n_shards)]


# ------------------------------------------------------------- parse stamping

_NL = 10
_CR = 13


def _row_byte_starts(view: np.ndarray, has_header: bool
                     ) -> Optional[np.ndarray]:
    """Byte offset of every non-blank data line (the parser engines all
    drop blank lines); None when the file has no body."""
    nl = np.flatnonzero(view == _NL)
    body = 0
    if has_header:
        if not len(nl):
            return None                  # header-only file
        body = int(nl[0]) + 1
    starts = np.concatenate(
        [np.array([body], np.int64), nl[nl >= body].astype(np.int64) + 1])
    starts = starts[starts < len(view)]
    if not len(starts):
        return None
    ch = view[starts]
    nxt = np.full(len(starts), _NL, np.uint8)
    ok = starts + 1 < len(view)
    nxt[ok] = view[starts[ok] + 1]
    blank = (ch == _NL) | ((ch == _CR) & (nxt == _NL))
    return starts[~blank]


def compute_parse_shards(path: str, has_header: bool, nrows: int,
                         n_shards: int) -> Optional[List[dict]]:
    """Newline-aligned byte ranges whose lines ARE the per-host row
    blocks, each stamped with a sha1 of its source bytes.  None when the
    file's line structure cannot account for every parsed row (quoted
    embedded newlines, parser-dropped lines, …) — lineage then refuses
    to claim ranged re-parse is safe and recovery falls back."""
    size = os.path.getsize(path)
    if size > config().lineage_max_mb * 1e6:
        return None
    with open(path, "rb") as f:
        view = np.frombuffer(f.read(), np.uint8)
    row_starts = _row_byte_starts(view, has_header)
    if row_starts is None or len(row_starts) != nrows:
        return None
    bounds = shard_row_bounds(nrows, n_shards)
    shards = []
    for i, (lo, hi) in enumerate(bounds):
        if hi <= lo:
            shards.append({"shard": i, "row_lo": int(lo), "rows": 0,
                           "lo": 0, "hi": 0,
                           "src_sha1": hashlib.sha1(b"").hexdigest()})
            continue
        b_lo = int(row_starts[lo])
        b_hi = int(row_starts[hi]) if hi < nrows else len(view)
        shards.append({
            "shard": i, "row_lo": int(lo), "rows": int(hi - lo),
            "lo": b_lo, "hi": b_hi,
            "src_sha1": hashlib.sha1(
                np.ascontiguousarray(view[b_lo:b_hi]).tobytes()).hexdigest(),
        })
    return shards


def record_parse(frame, path: str, header: Optional[bool] = None,
                 sep: Optional[str] = None,
                 col_types: Optional[Dict[str, str]] = None,
                 col_names: Optional[Sequence[str]] = None) -> Optional[dict]:
    """Stamp a just-parsed frame with ranged provenance and publish the
    WAL-durable ``!lineage/<frame>`` record.  Never raises; a source that
    can't be safely range-split simply leaves no record (recovery then
    uses the journaled source URI, the pre-lineage contract)."""
    if not enabled() or getattr(frame, "key", None) is None:
        return None
    try:
        if not isinstance(path, str) or "://" in path \
                or path.lower().endswith((".gz", ".zip", ".bz2", ".xz")) \
                or not os.path.isfile(path):
            return None
        from .parse import _guess_numeric
        sepc = sep if sep is not None else ","
        if header is None:
            with open(path, "rb") as f:
                first = f.readline().decode(errors="replace").rstrip("\r\n")
            cells = [c.strip().strip('"') for c in first.split(sepc)]
            has_header = not _guess_numeric(cells)
        else:
            has_header = bool(header)
        from ..runtime.cluster import cluster
        n_shards = cluster().n_hosts
        shards = compute_parse_shards(path, has_header, frame.nrows,
                                      n_shards)
        if shards is None:
            return None
        rec = {
            "kind": "parse",
            "source": os.path.abspath(path),
            "parse": {"header": has_header, "sep": sep,
                      "col_types": dict(col_types or {}),
                      "col_names": list(col_names) if col_names else None},
            "n_shards": n_shards,
            "shards": shards,
        }
        frame._lineage = rec
        return publish(frame)
    except Exception as e:               # noqa: BLE001 — stamping is optional
        from ..runtime.observability import log
        log.debug("lineage: parse stamp of %r skipped: %r", path, e)
        frame._lineage = None
        return None


# ------------------------------------------------- columnar (row-group) parse

def compute_columnar_shards(path: str, nrows: int,
                            n_shards: int) -> Optional[List[dict]]:
    """Row-group-granularity provenance for a parquet source: each
    per-host row block carries the span of row groups covering it
    (``group_lo``..``group_hi``) plus the contiguous byte range of those
    groups' column chunks, sha1'd — the columnar analog of the CSV
    newline-aligned ranges.  None when the row-group metadata cannot
    account for every parsed row."""
    import pyarrow.parquet as pq
    size = os.path.getsize(path)
    if size > config().lineage_max_mb * 1e6:
        return None
    md = pq.ParquetFile(path).metadata
    if md.num_row_groups == 0:
        return None
    g_rows = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
    if sum(g_rows) != nrows:
        return None
    g_starts = np.concatenate(
        [np.array([0], np.int64), np.cumsum(g_rows).astype(np.int64)])
    spans = []                           # per-group [byte_lo, byte_hi)
    for gi in range(md.num_row_groups):
        rg = md.row_group(gi)
        b_lo, b_hi = None, None
        for ci in range(rg.num_columns):
            cc = rg.column(ci)
            start = cc.dictionary_page_offset \
                if cc.dictionary_page_offset is not None \
                else cc.data_page_offset
            end = start + cc.total_compressed_size
            b_lo = start if b_lo is None else min(b_lo, start)
            b_hi = end if b_hi is None else max(b_hi, end)
        spans.append((int(b_lo), int(b_hi)))
    with open(path, "rb") as f:
        view = np.frombuffer(f.read(), np.uint8)
    bounds = shard_row_bounds(nrows, n_shards)
    shards = []
    for i, (lo, hi) in enumerate(bounds):
        if hi <= lo:
            shards.append({"shard": i, "row_lo": int(lo), "rows": 0,
                           "group_lo": 0, "group_hi": 0, "lo": 0, "hi": 0,
                           "src_sha1": hashlib.sha1(b"").hexdigest()})
            continue
        g_lo = int(np.searchsorted(g_starts, lo, side="right") - 1)
        g_hi = int(np.searchsorted(g_starts, hi - 1, side="right"))
        b_lo = min(spans[g][0] for g in range(g_lo, g_hi))
        b_hi = max(spans[g][1] for g in range(g_lo, g_hi))
        shards.append({
            "shard": i, "row_lo": int(lo), "rows": int(hi - lo),
            "group_lo": g_lo, "group_hi": g_hi,
            "group_row_lo": int(g_starts[g_lo]),
            "lo": b_lo, "hi": b_hi,
            "src_sha1": hashlib.sha1(
                np.ascontiguousarray(view[b_lo:b_hi]).tobytes()).hexdigest(),
        })
    return shards


def record_parse_columnar(frame, path: str,
                          fmt: str = "parquet") -> Optional[dict]:
    """Stamp a parquet-parsed frame with row-group provenance and publish
    the ``!lineage/<frame>`` record — the columnar peer of
    :func:`record_parse`.  Never raises; sources that can't be safely
    group-split leave no record."""
    if not enabled() or getattr(frame, "key", None) is None:
        return None
    try:
        if fmt != "parquet" or not isinstance(path, str) or "://" in path \
                or not os.path.isfile(path):
            return None
        from ..runtime.cluster import cluster
        n_shards = cluster().n_hosts
        shards = compute_columnar_shards(path, frame.nrows, n_shards)
        if shards is None:
            return None
        rec = {
            "kind": "parse",
            "source": os.path.abspath(path),
            "parse": {"format": "parquet"},
            "n_shards": n_shards,
            "shards": shards,
        }
        frame._lineage = rec
        return publish(frame)
    except Exception as e:               # noqa: BLE001 — stamping is optional
        from ..runtime.observability import log
        log.debug("lineage: columnar stamp of %r skipped: %r", path, e)
        frame._lineage = None
        return None


# --------------------------------------------------- streaming (partial) recs

def stream_record_start(frame_key: str, source: str, parse: dict,
                        total_bytes: int) -> Optional[dict]:
    """Open a partial streaming-parse record: ``complete=False`` plus an
    (initially empty) landed-range list.  A host dying mid-stream leaves
    this record behind, and :meth:`ingest.stream.StreamingFrame.resume`
    re-parses ONLY the ranges missing from it."""
    if not enabled():
        return None
    from ..runtime import dkv
    rec = {"kind": "parse", "streaming": True, "complete": False,
           "source": os.path.abspath(source), "parse": dict(parse),
           "total_bytes": int(total_bytes), "ranges": []}
    dkv.put(lineage_key(frame_key), rec)
    return rec


def stream_record_range(frame_key: str, rng: dict) -> None:
    """Append one landed range ({lo, hi, row_lo, rows, src_sha1}) to the
    partial streaming record.  Never raises."""
    try:
        from ..runtime import dkv
        rec = get_record(frame_key)
        if not isinstance(rec, dict) or not rec.get("streaming"):
            return
        rec.setdefault("ranges", []).append(dict(rng))
        dkv.put(lineage_key(frame_key), rec)
    except Exception:                    # noqa: BLE001 — stamping is optional
        pass


# ------------------------------------------------------------- derived chains

def _pack_index(index) -> Optional[bytes]:
    index = np.asarray(index, np.int64)
    if index.size > config().lineage_max_index:
        return None
    return zlib.compress(index.tobytes(), 1)


def unpack_index(blob: bytes) -> np.ndarray:
    return np.frombuffer(zlib.decompress(blob), np.int64)


def derive(out, base, op: Optional[dict]):
    """Attach a derived-lineage record to ``out``: the root frame key of
    ``base``'s chain plus ``base``'s ops with ``op`` appended.  ``op=None``
    (or a base with no lineage) breaks the chain.  Registered outputs
    publish immediately; anonymous intermediates stay in-memory until
    :func:`register` gives them a key.  Never raises."""
    try:
        if op is None or not enabled():
            out._lineage = None
            return out
        rec = getattr(base, "_lineage", None)
        if not isinstance(rec, dict):
            out._lineage = None
            return out
        kind = rec.get("kind")
        if kind in ("parse", "checkpoint"):
            root = getattr(base, "key", None) or rec.get("frame")
            ops: List[dict] = [op]
        elif kind == "derived":
            root = rec.get("root")
            ops = list(rec.get("ops") or []) + [op]
        else:
            root = None
            ops = []
        if not root:
            out._lineage = None
            return out
        out._lineage = {"kind": "derived", "root": root, "ops": ops}
        if getattr(out, "key", None):
            publish(out)
    except Exception:                    # noqa: BLE001 — lineage is optional
        out._lineage = None
    return out


def derive_rows(out, base, index):
    """Row-gather op; indexes past ``lineage_max_index`` break the chain
    (an unbounded index would bloat the WAL past any replay savings)."""
    blob = None
    try:
        blob = _pack_index(index)
    except Exception:                    # noqa: BLE001
        blob = None
    return derive(out, base, None if blob is None
                  else {"op": "rows", "index": blob})


def register(frame, key: str):
    """Give a derived frame a DKV identity and persist its lineage — the
    step that makes an anonymous split/munge output recoverable (and
    journal-able as a training frame) after a restart."""
    from ..runtime import dkv
    frame.key = key
    dkv.put(key, frame)
    publish(frame)
    return frame


# ------------------------------------------------------------- checkpointing

def _safe_name(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


def _checkpoint_uri(key: str) -> Optional[str]:
    from ..runtime import recovery
    base = recovery.recovery_dir()
    if not base:
        return None
    return f"{base.rstrip('/')}/lineage_ckpt_{_safe_name(key)}.pkl"


def write_checkpoint(frame, key: str) -> Optional[dict]:
    """Materialize a frame's canonical columns under the recovery dir and
    return a checkpoint-kind record (None without a recovery dir)."""
    uri = _checkpoint_uri(key)
    if uri is None:
        return None
    from .. import persist
    schema = schema_of(frame)
    cols = canonical_cols(frame)
    with persist.open_write(uri) as f:
        pickle.dump({"schema": schema, "nrows": int(frame.nrows),
                     "cols": cols}, f)
    return {"kind": "checkpoint", "uri": uri}


def load_checkpoint(rec: dict) -> Tuple[dict, int, List[np.ndarray]]:
    from .. import persist
    with persist.open_read(rec["uri"]) as f:
        blob = pickle.load(f)            # our own recovery-dir artifact
    return blob["schema"], int(blob["nrows"]), list(blob["cols"])


# ----------------------------------------------------------------- publishing

def publish(frame, key: Optional[str] = None) -> Optional[dict]:
    """Persist ``frame``'s in-memory lineage as the WAL-durable
    ``!lineage/<key>`` record: attach schema + per-shard value hashes
    (for frames under ``lineage_hash_below_mb``), checkpoint-materialize
    over-deep derived chains, and cut hot-frame replicas for frames
    under ``replicate_below_mb``.  Never raises."""
    key = key or getattr(frame, "key", None)
    rec = getattr(frame, "_lineage", None)
    if key is None or not isinstance(rec, dict) or not enabled():
        return None
    try:
        from ..runtime import dkv
        from ..runtime.observability import log, set_gauge
        cfg = config()
        rec = dict(rec)
        if rec.get("kind") == "derived" \
                and len(rec.get("ops") or []) > cfg.lineage_max_chain:
            ck = None
            try:
                ck = write_checkpoint(frame, key)
            except Exception as e:       # noqa: BLE001
                log.warning("lineage: checkpoint of %r failed (%r); "
                            "keeping the deep op chain", key, e)
            if ck is not None:
                rec = ck
        rec["frame"] = key
        rec["nrows"] = int(frame.nrows)
        rec["schema"] = schema_of(frame)
        types = rec["schema"]["types"]
        n_shards = rec.get("n_shards")
        if n_shards is None:
            from ..runtime.cluster import cluster
            rec["n_shards"] = n_shards = cluster().n_hosts
        bounds = shard_row_bounds(frame.nrows, n_shards)
        if "shards" not in rec:
            rec["shards"] = [{"shard": i, "row_lo": int(lo),
                              "rows": int(hi - lo)}
                             for i, (lo, hi) in enumerate(bounds)]
        cols = None
        size_mb = None
        if cfg.lineage_hash_below_mb > 0 or cfg.replicate_below_mb > 0:
            cols = canonical_cols(frame)
            size_mb = _canonical_nbytes(cols, types) / 1e6
        if cols is not None and size_mb <= cfg.lineage_hash_below_mb:
            for s in rec["shards"]:
                lo = s["row_lo"]
                s["val_sha1"] = hash_cols(cols, types, lo, lo + s["rows"])
        if cols is not None and cfg.replicate_below_mb > 0 \
                and size_mb <= cfg.replicate_below_mb and n_shards > 1:
            rec["replicas"] = {}
            for s in rec["shards"]:
                i, lo = s["shard"], s["row_lo"]
                hi = lo + s["rows"]
                neighbor = (i + 1) % n_shards    # DCN-neighbor placement
                sha = s.get("val_sha1") or hash_cols(cols, types, lo, hi)
                dkv.put(replica_key(key, i),
                        {"cols": [np.ascontiguousarray(c[lo:hi])
                                  if c.dtype != object else c[lo:hi]
                                  for c in cols],
                         "sha1": sha, "host": neighbor,
                         "row_lo": lo, "rows": hi - lo})
                rec["replicas"][str(i)] = {"host": neighbor, "sha1": sha}
        dkv.put(lineage_key(key), rec)
        frame._lineage = rec
        try:
            set_gauge("lineage_records",
                      float(len(dkv.keys(LINEAGE_PREFIX))))
        except Exception:                # noqa: BLE001
            pass
        return rec
    except Exception as e:               # noqa: BLE001 — lineage is optional
        from ..runtime.observability import log
        log.debug("lineage: publish of %r skipped: %r", key, e)
        return None
