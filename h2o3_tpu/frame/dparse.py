"""Multi-host distributed parse: each process tokenizes its own byte ranges.

Reference: ``water/parser/ParseDataset.java:688`` — ``MultiFileParseTask``
parses each raw chunk on the node that owns it and writes chunks in place;
categorical domains are merged cluster-wide in the reduce
(ParseDataset.java:501-600).

TPU-native redesign: the input (one or many CSV files) is treated as one
concatenated byte stream split into per-process byte spans at line
boundaries (the classic text-split contract: a reader owns every line that
*starts* inside its span).  Each process tokenizes only its spans with the
same native/pandas ladder the single-host parser uses, so at pod scale
ingest bandwidth grows with host count instead of serializing through one
VM's CPU and NIC.  Global reconciliation then rides the DCN control plane
(DKV):

1. *Setup reduce* — per-column type evidence (numeric/time parseability,
   capped unique sets, row counts, raw-token availability) is published and
   merged deterministically on every process: the ParseSetup + domain-merge
   analog.  When a column mixes numeric-typed spans with text spans, an
   extra round republishes raw-token uniques so the merged categorical
   domain uses source tokens ("3", "007"), never float round-trips ("3.0").
2. *Shard exchange* — each process converts its rows to the agreed dtype
   and ships only the boundary slices other processes' device shards need
   (row offsets rarely align with the even device sharding); host-resident
   columns (strings, exact time payloads) are allgathered.  Device columns
   are assembled with ``jax.make_array_from_callback``, which touches only
   this process's addressable shards.

Correctness guard: byte-span splitting cannot see RFC-4180 quoted fields
that contain newlines.  Every span tokenize reports a *suspect* flag
(unbalanced quotes, tokenizer errors, unconsumed native bytes); if any
process raises it, all processes abandon the split and fall back to the
replicated single-host parse (``parse_files``), which handles quoting.
"""

from __future__ import annotations

import hashlib
import io
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .frame import Frame
from .vec import Vec, T_CAT, T_NUM, T_STR, T_TIME
from .parse import (_NA, _guess_numeric, _parse_time_column, _STR_MIN_CARD,
                    _STR_UNIQUE_RATIO, _decode_text_column)
from ..runtime import dkv

_UNIQ_CAP = 10_000

# Telemetry from the most recent distributed parse on this process
# (test hook: proves tokenization stayed local to the byte assignment).
last_stats: Dict[str, float] = {}

_seq = 0


# --------------------------------------------------------------------- split

def _byte_assignments(paths: Sequence[str], sizes: Sequence[int],
                      nproc: int) -> List[List[Tuple[str, int, int]]]:
    """Even byte spans over the concatenated file stream, one per process.

    Returns, for each process, a list of (path, lo, hi) file pieces.  Line
    alignment happens at read time (``_read_span``).
    """
    total = sum(sizes)
    cuts = [i * total // nproc for i in range(nproc + 1)]
    assign: List[List[Tuple[str, int, int]]] = [[] for _ in range(nproc)]
    base = 0
    for p, size in zip(paths, sizes):
        for i in range(nproc):
            lo, hi = max(cuts[i] - base, 0), min(cuts[i + 1] - base, size)
            if lo < hi:
                assign[i].append((p, lo, hi))
        base += size
    return assign


def _read_span(path: str, lo: int, hi: int, skip_header: bool):
    """The lines of ``path`` whose first byte lies in [lo, hi).

    A reader owns every line that STARTS in its span: if ``lo > 0`` it skips
    the line already in progress, and it reads past ``hi`` to finish the
    last line it owns.  ``skip_header`` drops the file's header row (only
    meaningful for the span containing byte 0).

    Local files return a zero-copy uint8 view over an mmap (the ranged
    pipeline's no-copy contract); persist URIs return bytes from range
    reads.
    """
    if "://" in path:
        return _read_span_persist(path, lo, hi, skip_header)
    import mmap as _mmap
    with open(path, "rb") as f:
        try:
            mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        except ValueError:                # empty file
            return b""
    size = len(mm)
    start = lo
    if lo > 0:
        if mm[lo - 1:lo] != b"\n":
            nl = mm.find(b"\n", lo)       # line in progress belongs upstream
            if nl < 0:
                return b""
            start = nl + 1
    elif skip_header:
        nl = mm.find(b"\n", 0)
        if nl < 0:
            return b""
        start = nl + 1
    if start >= hi:
        return b""
    end = min(hi, size)
    if end < size and mm[end - 1:end] != b"\n":
        nl = mm.find(b"\n", end)          # finish the last owned line
        end = size if nl < 0 else nl + 1
    return np.frombuffer(mm, np.uint8)[start:end]


_TAIL_CHUNK = 1 << 20


def _read_span_persist(uri: str, lo: int, hi: int,
                       skip_header: bool) -> bytes:
    """Line-aligned span read over the persist SPI (GCS/S3/HDFS range
    reads — PersistGcs/PersistS3 load byte ranges the same way)."""
    from .. import persist
    be, path = persist.split_uri(uri)
    total = be.size(path)
    hi = min(hi, total)
    buf = be.read_range(path, lo, hi - lo)
    if lo > 0 and be.read_range(path, lo - 1, 1) != b"\n":
        nl = buf.find(b"\n")
        if nl < 0:
            return b""            # the whole span is an upstream line
        buf = buf[nl + 1:]
    elif skip_header:
        while b"\n" not in buf and lo + len(buf) < total:
            buf += be.read_range(path, lo + len(buf), _TAIL_CHUNK)
        nl = buf.find(b"\n")
        if nl < 0:
            return b""
        buf = buf[nl + 1:]
    # finish the last owned line past hi
    pos = hi
    while buf and not buf.endswith(b"\n") and pos < total:
        ext = be.read_range(path, pos, min(_TAIL_CHUNK, total - pos))
        nl = ext.find(b"\n")
        if nl >= 0:
            buf += ext[: nl + 1]
            break
        buf += ext
        pos += len(ext)
    return buf


# ------------------------------------------------------------------ tokenize

class _Span:
    """One tokenized byte span: column arrays + enough context to re-extract
    raw tokens (native offsets, or the bytes for a pandas re-read)."""

    __slots__ = ("data", "cols", "offs", "nrows")

    def __init__(self, data, cols: Dict[str, np.ndarray],
                 offs: Optional[np.ndarray], nrows: int):
        self.data = data                  # bytes or zero-copy uint8 view
        self.cols = cols
        self.offs = offs
        self.nrows = nrows


def _span_bytes(data) -> bytes:
    """Materialize a span as bytes (pandas/stdlib fallbacks only)."""
    return data if isinstance(data, bytes) else bytes(memoryview(data))


def _tokenize(data, sepc: str,
              names: List[str]) -> Tuple[Optional[_Span], bool]:
    """Tokenize a headerless CSV byte span (bytes or uint8 view).
    Returns (span, suspect).

    ``suspect`` signals the byte-split cannot be trusted (quoted newlines /
    tokenizer failure) — the caller falls back to a replicated parse.
    """
    if isinstance(data, bytes):
        odd_quotes = data.count(b'"') % 2 == 1
    else:
        odd_quotes = int(np.count_nonzero(data == 0x22)) % 2 == 1
    if odd_quotes:
        return None, True             # unbalanced quotes: split mid-field
    try:
        from .. import native
        out = native.parse_view(native._as_view(data), sepc,
                                ncols=len(names))
    except Exception:
        out = None
    if out is not None:
        vals, flags, offs, consumed = out
        if consumed != len(data):
            return None, True         # unterminated quote etc.
        if vals.shape[1] == len(names):
            cols = {}
            for j, nm in enumerate(names):
                if flags[:, j].any():
                    cols[nm] = _decode_text_column(data, offs, j)
                else:
                    cols[nm] = vals[:, j]
            return _Span(data, cols, offs, len(vals)), False
    try:
        import pandas as pd
        try:
            df = pd.read_csv(io.BytesIO(_span_bytes(data)), sep=sepc,
                             header=None, names=names,
                             na_values=sorted(_NA),
                             keep_default_na=True, engine="c",
                             low_memory=False)
        except Exception:
            return None, True         # ragged rows / parser error: suspect
        if len(df.columns) != len(names):
            return None, True
        cols = {n: df[n].to_numpy() for n in names}
        return _Span(data, cols, None, len(df)), False
    except ImportError:
        import csv
        rows = list(csv.reader(io.StringIO(
            _span_bytes(data).decode(errors="replace")), delimiter=sepc))
        if rows and any(len(r) != len(names) for r in rows):
            return None, True
        cols = {n: np.array([r[i] for r in rows], dtype=object)
                for i, n in enumerate(names)}
        return _Span(data, cols, None, len(rows)), False


def _raw_column(span: _Span, names: List[str], name: str,
                sepc: str) -> np.ndarray:
    """Re-extract one column of a span as raw source tokens (object array).

    Needed when another span/process saw text in this column: numeric cells
    must map back to their source spelling ("3", "007"), not a float
    round-trip ("3.0")."""
    j = names.index(name)
    if span.offs is not None:
        return _decode_text_column(span.data, span.offs, j)
    try:
        import pandas as pd
        df = pd.read_csv(io.BytesIO(_span_bytes(span.data)), sep=sepc,
                         header=None, names=names, usecols=[name],
                         dtype=str, na_filter=False, engine="c")
        return df[name].to_numpy(dtype=object)
    except ImportError:
        import csv
        rows = list(csv.reader(io.StringIO(
            _span_bytes(span.data).decode(errors="replace")),
            delimiter=sepc))
        return np.array([r[j] for r in rows], dtype=object)


def _local_column(spans: List[_Span], names: List[str], name: str,
                  sepc: str, force_raw: bool) -> np.ndarray:
    """This process's rows for one column, intra-process consistent.

    If any span holds text tokens for the column (or ``force_raw``), every
    span contributes raw source tokens; otherwise the column is pure
    float64."""
    pieces = [s.cols[name] for s in spans]
    numeric = all(np.asarray(p).dtype.kind in "ifb" for p in pieces)
    if numeric and not force_raw:
        return np.concatenate(
            [np.asarray(p, np.float64) for p in pieces]) if pieces \
            else np.empty(0, np.float64)
    out = []
    for s, p in zip(spans, pieces):
        p = np.asarray(p)
        if p.dtype.kind in "ifb":
            out.append(_raw_column(s, names, name, sepc))
        else:
            out.append(p.astype(object))
    return np.concatenate(out) if out else np.empty(0, dtype=object)


# ------------------------------------------------------------ type evidence

def _evidence(arr: np.ndarray, want: Optional[str]):
    """Per-process type evidence for one column (ParseSetup analog).

    Returns (evidence dict, cached time-parse result or None).  ``obj``
    records whether this process holds raw tokens (object dtype).  Numeric-
    dtype arrays skip unique collection unless the caller forces T_CAT
    (their float-string uniques are only ever used for forced-cat domains);
    ``n_uniq`` is the exact LOCAL cardinality, so the global merge can
    estimate cardinality beyond the per-process ``_UNIQ_CAP`` shipping cap.
    """
    arr = np.asarray(arr)
    if arr.dtype.kind in "ifb":
        vals = arr.astype(np.float64)
        ok = np.isfinite(vals)
        uniq, n_uniq, over = [], 0, False
        if want == T_CAT:
            su = np.unique(vals[ok])
            n_uniq = len(su)
            over = n_uniq > _UNIQ_CAP
            uniq = [str(v) for v in su[:_UNIQ_CAP]]
        return {"numeric": True, "time": False, "obj": False,
                "nonna": int(ok.sum()), "uniq": uniq, "n_uniq": n_uniq,
                "over_cap": over, "ms_min": None}, None
    svals = arr.astype(str)
    na = np.isin(svals, list(_NA))
    nz = svals[~na]
    numeric = False
    if _guess_numeric(nz[:1000].tolist()):
        try:
            nz.astype(np.float64)
            numeric = True
        except ValueError:
            numeric = False
    ms = None if numeric else _parse_time_column(arr)
    ms_min = None
    if ms is not None and np.isfinite(ms).any():
        ms_min = float(np.nanmin(ms))
    su = np.unique(nz)
    return {"numeric": numeric, "time": ms is not None, "obj": True,
            "nonna": int(len(nz)), "uniq": su[:_UNIQ_CAP].tolist(),
            "n_uniq": int(len(su)), "over_cap": bool(len(su) > _UNIQ_CAP),
            "ms_min": ms_min}, ms


def _resolve_type(evs: List[dict], want: Optional[str]):
    """Deterministically merge per-process evidence into (type, needs_raw).

    ``needs_raw`` marks cat/str columns where at least one process holds
    raw text tokens — numeric-dtype processes must then re-extract raw
    tokens so domains/values agree with the source bytes.  Cardinality for
    the cat-vs-str heuristic uses the sum of exact local counts (an upper
    bound — duplicates across processes overcount, which only matters for
    contrived heavy-overlap near-unique columns)."""
    active = [e for e in evs if e["nonna"] > 0]
    if not active:
        return (want if want in (T_CAT, T_STR, T_TIME) else T_NUM), False
    if want in (None, T_NUM) and all(e["numeric"] for e in active):
        return T_NUM, False
    if want in (None, T_TIME) and all(e["time"] for e in active):
        return T_TIME, False
    needs_raw = any(e["obj"] for e in active)
    card_est = sum(e["n_uniq"] for e in evs)
    total_nonna = sum(e["nonna"] for e in evs)
    if want != T_CAT and (want == T_STR or (
            card_est >= _STR_MIN_CARD
            and card_est > _STR_UNIQUE_RATIO * total_nonna)):
        return T_STR, needs_raw
    return T_CAT, needs_raw


def _convert(arr: np.ndarray, vtype: str, domain, ms_cache):
    """Convert raw local tokens to the globally agreed dtype.

    By this point ``arr`` is either pure float64 (no process saw text) or
    raw source tokens — matching what the single-host ``_column_to_vec``
    would have seen for the whole column."""
    arr = np.asarray(arr)
    if vtype == T_NUM:
        if arr.dtype.kind in "ifb":
            return arr.astype(np.float32)
        svals = arr.astype(str)
        na = np.isin(svals, list(_NA))
        out = np.full(len(arr), np.nan, np.float64)
        if (~na).any():
            out[~na] = svals[~na].astype(np.float64)
        return out.astype(np.float32)
    if vtype == T_TIME:
        ms = ms_cache if ms_cache is not None else _parse_time_column(arr)
        if ms is None:
            ms = np.full(len(arr), np.nan, np.float64)
        return ms                                   # float64 ms, NaN missing
    svals = arr.astype(str)
    na = np.isin(svals, list(_NA))
    if vtype == T_CAT:
        from .vec import encode_domain
        return encode_domain(svals, domain, na_mask=na)
    out = svals.astype(object)
    out[na] = None
    return out                                      # T_STR


# -------------------------------------------------------- global assembly

def _barrier(tag: str) -> None:
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def _merge_ranges(ranges: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    out: List[Tuple[int, int]] = []
    for lo, hi in sorted(ranges):
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _needed_ranges(padded: int) -> Dict[int, List[Tuple[int, int]]]:
    """Global-row ranges each process's addressable devices cover."""
    import jax
    from ..runtime.cluster import cluster
    shard = cluster().row_sharding
    need: Dict[int, List[Tuple[int, int]]] = {
        p: [] for p in range(jax.process_count())}
    for d, idx in shard.devices_indices_map((padded,)).items():
        sl = idx[0]
        need[d.process_index].append(
            (sl.start or 0, padded if sl.stop is None else sl.stop))
    return {p: _merge_ranges(r) for p, r in need.items()}


def _publish_xfers(job: str, col: str, local: np.ndarray, own_lo: int,
                   need: Dict[int, List[Tuple[int, int]]],
                   me: int) -> List[str]:
    """Ship the boundary slices other processes' shards need."""
    keys = []
    own_hi = own_lo + len(local)
    for p, ranges in need.items():
        if p == me:
            continue
        for lo, hi in ranges:
            a, b = max(lo, own_lo), min(hi, own_hi)
            if a < b:
                k = f"{job}/x/{col}/{me}/{p}/{a}"
                dkv.put(k, local[a - own_lo:b - own_lo])
                keys.append(k)
    return keys


def _assemble_device(job: str, col: str, local: np.ndarray, offsets,
                     counts, padded: int, my_ranges, fill, dtype):
    """Build the global row-sharded array from local + fetched pieces."""
    import jax
    from ..runtime.cluster import cluster
    me = jax.process_index()
    buf = np.full(padded, fill, dtype=dtype)
    own_lo = int(offsets[me])
    buf[own_lo:own_lo + len(local)] = local
    for p in range(jax.process_count()):
        if p == me:
            continue
        p_lo, p_hi = int(offsets[p]), int(offsets[p]) + int(counts[p])
        for lo, hi in my_ranges:
            a, b = max(lo, p_lo), min(hi, p_hi)
            if a < b:
                buf[a:b] = dkv.get(f"{job}/x/{col}/{p}/{me}/{a}")
    return jax.make_array_from_callback(
        (padded,), cluster().row_sharding, lambda idx: buf[idx])


# ---------------------------------------------------------------- entrypoint

def parse_files_distributed(paths: Sequence[str],
                            destination_frame: Optional[str] = None,
                            header: Optional[bool] = None,
                            sep: Optional[str] = None,
                            col_types: Optional[Dict[str, str]] = None,
                            col_names: Optional[List[str]] = None,
                            chunksize: int = 1_000_000) -> Frame:
    """Parse CSV files with per-process byte-range ownership -> one Frame.

    Works single-process too (degenerates to a local parse with no control-
    plane traffic) — ``import_file`` routes here whenever the cluster spans
    multiple processes and the input is plain local CSV.  ``chunksize`` is
    accepted for ``parse_files`` signature compatibility; span tokenization
    is already bounded by the byte assignment, and the quoted-newline
    fallback forwards it.
    """
    global _seq, last_stats
    import jax
    from ..runtime.cluster import cluster
    cl = cluster()
    nproc, me = jax.process_count(), jax.process_index()
    col_types = dict(col_types or {})
    sepc = sep if sep is not None else ","
    paths = list(paths)
    from .. import persist

    def _size(p):
        if "://" in p:
            be, rest = persist.split_uri(p)
            return be.size(rest)
        return os.path.getsize(p)

    sizes = [_size(p) for p in paths]

    # ParseSetup analog: deterministic header/name guess from file 0's head
    # (every process reads the same few bytes — no communication needed).
    if "://" in paths[0]:
        be0, rest0 = persist.split_uri(paths[0])
        head = be0.read_range(rest0, 0, min(64 * 1024, sizes[0]))
        first = head.split(b"\n", 1)[0].decode(errors="replace") \
            .rstrip("\r\n")
    else:
        with open(paths[0], "rb") as f:
            first = f.readline().decode(errors="replace").rstrip("\r\n")
    import csv as _csv
    try:
        head_cells = [c.strip() for c in
                      next(_csv.reader([first], delimiter=sepc))]
    except (StopIteration, _csv.Error):
        head_cells = [c.strip().strip('"') for c in first.split(sepc)]
    has_header = (not _guess_numeric(head_cells)) if header is None \
        else bool(header)
    if col_names:
        names = list(col_names)
    elif has_header:
        names = head_cells
    else:
        names = [f"C{i + 1}" for i in range(len(head_cells))]

    # ---- local tokenize over this process's byte spans only
    assign = _byte_assignments(paths, sizes, nproc)
    spans: List[_Span] = []
    bytes_tokenized = 0
    suspect = False
    for path, lo, hi in assign[me]:
        data = _read_span(path, lo, hi, skip_header=has_header and lo == 0)
        bytes_tokenized += len(data)
        if len(data) == 0:
            continue
        span, bad = _tokenize(data, sepc, names)
        if bad:
            suspect = True
            break
        spans.append(span)
    n_local = sum(s.nrows for s in spans)
    last_stats = {"bytes_tokenized": bytes_tokenized,
                  "total_bytes": sum(sizes), "rows_local": n_local,
                  "nproc": nproc, "suspect": suspect}

    _seq += 1
    digest = hashlib.md5("|".join(paths).encode()).hexdigest()[:12]
    job = f"dparse/{_seq}/{digest}"
    published: List[str] = []

    # ---- round 1: setup reduce (type evidence + row counts + suspects)
    ev_payload, ms_cache, raw_cols = {}, {}, {}
    if not suspect:
        for n in names:
            raw_cols[n] = _local_column(spans, names, n, sepc,
                                        force_raw=False)
            ev, ms = _evidence(raw_cols[n], col_types.get(n))
            ev_payload[n] = ev
            ms_cache[n] = ms
    meta_key = f"{job}/meta/{me}"
    dkv.put(meta_key, {"n": n_local, "ev": ev_payload, "suspect": suspect})
    published.append(meta_key)
    _barrier(job + ":ev")
    metas = [dkv.get(f"{job}/meta/{p}") for p in range(nproc)]
    if any(m["suspect"] for m in metas):
        # quoted newlines (or tokenizer failure) somewhere: the byte split
        # is unsafe — replicated single-host parse handles quoting.
        _barrier(job + ":abort")
        for k in published:
            dkv.remove(k)
        from .parse import parse_files
        return parse_files(paths, destination_frame=destination_frame,
                           header=header, sep=sep, col_types=col_types,
                           col_names=col_names, chunksize=chunksize)
    counts = [m["n"] for m in metas]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    total = int(offsets[-1])
    if total == 0:
        raise ValueError("no data parsed from " + ", ".join(paths))
    padded = cl.pad_rows(total)
    need = _needed_ranges(padded)

    resolved: Dict[str, list] = {}
    for n in names:
        evs = [m["ev"][n] for m in metas]
        vtype, needs_raw = _resolve_type(evs, col_types.get(n))
        my_ev = ev_payload[n]
        if needs_raw and not my_ev["obj"]:
            # another process saw text; my float tokens must become raw
            raw_cols[n] = _local_column(spans, names, n, sepc,
                                        force_raw=True)
        resolved[n] = [vtype, needs_raw, None]

    # ---- round 1.5: a cat column needs a supplemental FULL-unique round
    # from process p when (a) p held float tokens but another process saw
    # text (domain must use source spellings, not float round-trips), or
    # (b) p's uniques overflowed the _UNIQ_CAP shipping cap (a capped
    # domain would silently map dropped levels to NA).
    def _republishes(p: int, n: str) -> bool:
        vtype, needs_raw, _ = resolved[n]
        if vtype != T_CAT:
            return False
        e = metas[p]["ev"][n]
        if e["nonna"] == 0:
            return False
        return (needs_raw and not e["obj"]) or e["over_cap"]

    if any(_republishes(p, n) for p in range(nproc) for n in names):
        supp = {}
        for n in names:
            if _republishes(me, n):
                svals = raw_cols[n].astype(str)
                nz = svals[~np.isin(svals, list(_NA))]
                supp[n] = np.unique(nz).tolist()
        k = f"{job}/supp/{me}"
        dkv.put(k, supp)
        published.append(k)
        _barrier(job + ":supp")
        supps = [dkv.get(f"{job}/supp/{p}") for p in range(nproc)]
    else:
        supps = [{} for _ in range(nproc)]

    for n in names:
        vtype, needs_raw, _ = resolved[n]
        if vtype == T_CAT:
            dom: set = set()
            for p, m in enumerate(metas):
                if _republishes(p, n):
                    dom.update(supps[p].get(n, ()))
                else:
                    dom.update(m["ev"][n]["uniq"])
            resolved[n][2] = sorted(dom)
        elif vtype == T_TIME:
            mins = [m["ev"][n]["ms_min"] for m in metas
                    if m["ev"][n]["ms_min"] is not None]
            resolved[n][2] = float(min(mins)) if mins else 0.0

    # ---- round 2: convert locally, ship boundary slices / host columns
    converted = {}
    time_bases = {}
    for n in names:
        vtype, _, aux = resolved[n]
        domain = aux if vtype == T_CAT else None
        time_bases[n] = aux if vtype == T_TIME else 0.0
        local = _convert(raw_cols[n], vtype, domain, ms_cache[n])
        if vtype in (T_STR, T_TIME):
            k = f"{job}/h/{n}/{me}"        # host payload: allgather
            dkv.put(k, local)
            published.append(k)
        if vtype == T_TIME:
            ms_cache[n] = local            # exact f64 ms for host_data
            local = ((local - time_bases[n]) / 1000.0).astype(np.float32)
        converted[n] = local
        if vtype != T_STR:
            published += _publish_xfers(job, n, local, int(offsets[me]),
                                        need, me)
    _barrier(job + ":xfer")

    vecs = []
    for n in names:
        vtype, _, aux = resolved[n]
        local = converted[n]
        if vtype == T_STR:
            host = np.concatenate(
                [np.asarray(dkv.get(f"{job}/h/{n}/{p}"), dtype=object)
                 if p != me else local for p in range(nproc)]) \
                if nproc > 1 else local
            vecs.append(Vec(None, T_STR, total, host_data=host))
            continue
        fill = -1 if vtype == T_CAT else np.nan
        dtype = np.int32 if vtype == T_CAT else np.float32
        data = _assemble_device(job, n, local, offsets, counts, padded,
                                need[me], fill, dtype)
        host_data = None
        if vtype == T_TIME:
            host_data = np.concatenate(
                [np.asarray(dkv.get(f"{job}/h/{n}/{p}"), dtype=np.float64)
                 if p != me else ms_cache[n] for p in range(nproc)]) \
                if nproc > 1 else ms_cache[n]
        vecs.append(Vec(data, vtype, total,
                        domain=aux if vtype == T_CAT else None,
                        host_data=host_data,
                        time_base=time_bases[n] or 0.0))

    # every process has read everything it needs; reclaim control-plane keys
    _barrier(job + ":done")
    for k in published:
        dkv.remove(k)

    key = destination_frame or dkv.make_key(
        os.path.basename(paths[0]) or "frame")
    return Frame(names, vecs, key=key)
