"""AutoML: planned modeling steps + leaderboard + stacked ensembles.

Reference: ``h2o-automl`` — ``ai/h2o/automl/AutoML.java:49`` runs a plan of
``ModelingStep``s from per-algo providers
(modeling/{GLM,GBM,DRF,DeepLearning,StackedEnsemble,XGBoost}StepsProvider),
with time/model budgets (WorkAllocations), ranking in
``hex/leaderboard/Leaderboard.java:34``, and two final stacked ensembles
(BestOfFamily, AllModels).

TPU-native redesign: the plan is plain host control flow over this package's
builders; every step trains with common nfolds +
keep_cross_validation_predictions so the final SEs stack for free.  Budgets
are wall-clock/model-count checks between steps (model-parallel scheduling
across mesh slices is the natural extension, SURVEY.md §7 step 8).
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..models.base import Model
from ..models.grid import default_sort_metric, model_metric


class Leaderboard:
    """Ranked model container — hex/leaderboard/Leaderboard.java:34 analog."""

    def __init__(self, models: List[Model], sort_metric: Optional[str] = None):
        self.models = list(models)
        if models:
            default, lower = default_sort_metric(models[0])
            self.sort_metric = sort_metric or default
            from ..models.scorekeeper import METRIC_MAXIMIZE
            self.lower_is_better = lower if sort_metric is None else \
                not METRIC_MAXIMIZE.get(self.sort_metric, False)
        else:
            self.sort_metric, self.lower_is_better = "rmse", True

    def sorted_models(self) -> List[Model]:
        def keyfn(m):
            v = model_metric(m, self.sort_metric)
            if v is None:
                return np.inf if self.lower_is_better else -np.inf
            return v
        return sorted(self.models, key=keyfn,
                      reverse=not self.lower_is_better)

    @property
    def leader(self) -> Model:
        return self.sorted_models()[0]

    def as_table(self) -> List[dict]:
        rows = []
        for m in self.sorted_models():
            row = {"model_id": m.key, "algo": m.algo,
                   self.sort_metric: model_metric(m, self.sort_metric)}
            for extra in ("auc", "logloss", "rmse", "mae"):
                if extra != self.sort_metric:
                    v = model_metric(m, extra)
                    if v is not None:
                        row[extra] = v
            rows.append(row)
        return rows

    def __repr__(self):
        lines = [f"Leaderboard (by {self.sort_metric}):"]
        for r in self.as_table():
            lines.append(f"  {r['model_id']:<28} "
                         f"{r[self.sort_metric]}")
        return "\n".join(lines)


@dataclasses.dataclass
class AutoMLParameters:
    response_column: str = ""
    max_models: int = 10
    max_runtime_secs: float = 0.0            # 0 = no time budget
    nfolds: int = 5
    seed: int = -1
    include_algos: Optional[Sequence[str]] = None
    exclude_algos: Sequence[str] = ()
    sort_metric: Optional[str] = None
    weights_column: Optional[str] = None
    keep_cross_validation_predictions: bool = True
    preprocessing: Sequence[str] = ()        # ("target_encoding",)
    auto_recovery_dir: Optional[str] = None  # resume point (Recovery.java:55)
    exploitation_ratio: float = 0.25         # grid share of the time budget
    # concurrent modeling steps (ModelingStepsExecutor parallelism):
    # 0 = auto (bounded pool), 1 = sequential, n = exactly n
    parallelism: int = 0


# --------------------------------------------------------- steps providers
class StepsProvider:
    """Per-algo modeling steps with work weights — the
    ai/h2o/automl/ModelingStep.java:42 + WorkAllocations contract.

    ``defaults()`` returns the fixed-parameter models; ``grids(rng)``
    returns randomized exploitation steps drawn within the grid space.
    Weights drive proportional time allocation.
    """

    algo = ""

    def defaults(self) -> List[dict]:
        return []

    def grids(self, rng) -> List[dict]:
        return []


class GLMSteps(StepsProvider):
    algo = "glm"

    def defaults(self):
        return [{"id": "GLM_1", "weight": 10,
                 "params": {"lambda_search": True}}]


class GBMSteps(StepsProvider):
    algo = "gbm"

    def defaults(self):
        return [
            {"id": "GBM_1", "weight": 10,
             "params": {"ntrees": 50, "max_depth": 6, "sample_rate": 0.8,
                        "col_sample_rate": 0.8}},
            {"id": "GBM_2", "weight": 10,
             "params": {"ntrees": 50, "max_depth": 7, "sample_rate": 0.9,
                        "col_sample_rate": 0.9}},
            {"id": "GBM_3", "weight": 10,
             "params": {"ntrees": 50, "max_depth": 8}},
        ]

    def grids(self, rng):
        out = []
        for i in range(3):
            out.append({"id": f"GBM_grid_{i+1}", "weight": 6, "params": {
                "ntrees": 50,
                "max_depth": int(rng.integers(3, 10)),
                "learn_rate": float(rng.choice([0.05, 0.1, 0.2])),
                "sample_rate": float(rng.choice([0.6, 0.8, 1.0])),
                "col_sample_rate": float(rng.choice([0.6, 0.8, 1.0])),
                "min_rows": float(rng.choice([1.0, 5.0, 10.0]))}})
        return out


class DRFSteps(StepsProvider):
    algo = "drf"

    def defaults(self):
        return [
            {"id": "DRF_1", "weight": 10, "params": {"ntrees": 50}},
            {"id": "XRT_1", "weight": 10,
             "params": {"ntrees": 50, "sample_rate": 0.632}},
        ]


class XGBoostSteps(StepsProvider):
    algo = "xgboost"

    def defaults(self):
        return [
            {"id": "XGBoost_1", "weight": 10,
             "params": {"ntrees": 50, "max_depth": 6}},
            {"id": "XGBoost_2", "weight": 10,
             "params": {"ntrees": 50, "max_depth": 8, "sample_rate": 0.8}},
        ]

    def grids(self, rng):
        out = []
        for i in range(2):
            out.append({"id": f"XGBoost_grid_{i+1}", "weight": 6, "params": {
                "ntrees": 50,
                "max_depth": int(rng.integers(4, 11)),
                "learn_rate": float(rng.choice([0.05, 0.1, 0.3])),
                "reg_lambda": float(rng.choice([0.1, 1.0, 10.0])),
                "min_child_weight": float(rng.choice([0.0, 1.0, 5.0]))}})
        return out


class DeepLearningSteps(StepsProvider):
    algo = "deeplearning"

    def defaults(self):
        return [{"id": "DeepLearning_1", "weight": 8,
                 "params": {"hidden": [64, 64], "epochs": 10}}]


PROVIDERS = (GLMSteps(), GBMSteps(), DRFSteps(), XGBoostSteps(),
             DeepLearningSteps())


class AutoML:
    """AutoML driver — H2OAutoML analog: planned steps from per-algo
    providers, WorkAllocations-style time budgeting, optional target-encoding
    preprocessing, recovery-dir resumability, leaderboard + SEs."""

    def __init__(self, params: Optional[AutoMLParameters] = None, **kw):
        self.params = params or AutoMLParameters(**kw)
        self.models: List[Model] = []
        self.leaderboard: Optional[Leaderboard] = None
        self.events: List[dict] = []
        self._completed_steps: List[str] = []

    # ------------------------------------------------------------ the plan
    def _plan(self) -> List[dict]:
        """Ordered steps from the providers: defaults first, then grids."""
        p = self.params
        rng = np.random.default_rng(p.seed if p.seed not in (-1, None)
                                    else 0)
        include = set(a.lower() for a in p.include_algos) \
            if p.include_algos else None
        exclude = set(a.lower() for a in p.exclude_algos)

        def allowed(algo):
            return (include is None or algo in include) \
                and algo not in exclude
        out = []
        for prov in PROVIDERS:
            if allowed(prov.algo):
                for s in prov.defaults():
                    out.append({**s, "algo": prov.algo, "group": "default"})
        for prov in PROVIDERS:
            if allowed(prov.algo):
                for s in prov.grids(rng):
                    out.append({**s, "algo": prov.algo, "group": "grid"})
        return out

    def _builder(self, algo: str, params: dict):
        from ..models import GLM, GBM, DRF, XGBoost, DeepLearning
        p = self.params
        common = dict(response_column=p.response_column,
                      weights_column=p.weights_column,
                      nfolds=p.nfolds, seed=p.seed,
                      keep_cross_validation_predictions=
                      p.keep_cross_validation_predictions)
        cls = {"glm": GLM, "gbm": GBM, "drf": DRF, "xgboost": XGBoost,
               "deeplearning": DeepLearning}[algo]
        return cls(**{**common, **params})

    # ------------------------------------------------------- preprocessing
    def _maybe_target_encode(self, frame: Frame,
                             valid: Optional[Frame]):
        """TE preprocessing step (AutoML's preprocessing=["target_encoding"]):
        kfold-encode high-cardinality categoricals, append *_te columns."""
        p = self.params
        if "target_encoding" not in tuple(p.preprocessing):
            return frame, valid
        from ..models.targetencoder import TargetEncoder
        from ..frame.vec import T_CAT
        high_card = [n for n, v in zip(frame.names, frame.vecs)
                     if v.type == T_CAT and n != p.response_column
                     and (v.cardinality or 0) > 10]
        if not high_card:
            return frame, valid
        from ..frame.vec import Vec
        rng = np.random.default_rng(p.seed if p.seed not in (-1, None)
                                    else 0)
        folds = rng.integers(0, max(p.nfolds, 2), frame.nrows)
        fold_vec = Vec.from_numpy(folds.astype(np.float64))
        fr_te = frame.with_vec("_te_fold", fold_vec)
        te = TargetEncoder(response_column=p.response_column,
                           data_leakage_handling="k_fold",
                           fold_column="_te_fold", seed=p.seed).train(
            fr_te[high_card + [p.response_column, "_te_fold"]])
        enc = te.transform(fr_te, as_training=True)
        out_t = frame
        for n in enc.names:
            if n.endswith("_te"):
                out_t = out_t.with_vec(n, enc.vec(n))
        out_v = valid
        if valid is not None:
            encv = te.transform(valid)
            for n in encv.names:
                if n.endswith("_te"):
                    out_v = out_v.with_vec(n, encv.vec(n))
        self.events.append({"step": "TE_preprocessing",
                            "columns": high_card})
        return out_t, out_v

    # --------------------------------------------------------- recovery
    def _recovery_state_path(self):
        import os
        return os.path.join(self.params.auto_recovery_dir, "automl_state.json")

    def _load_recovery(self):
        """Resume from auto_recovery_dir (hex/faulttolerance/Recovery:55)."""
        import json
        import os
        from ..models.base import Model as _Model
        path = self._recovery_state_path()
        if not os.path.exists(path):
            return
        state = json.load(open(path))
        for step_id, model_file in state.get("models", []):
            try:
                m = _Model.load(model_file)
                self.models.append(m)
                self._completed_steps.append(step_id)
            except Exception as e:                      # noqa: BLE001
                self.events.append({"step": step_id, "resume_error": repr(e)})
        if self._completed_steps:
            self.events.append({"resumed_steps": list(self._completed_steps)})

    def _save_recovery(self, step_id: str, model: Model):
        import json
        import os
        d = self.params.auto_recovery_dir
        os.makedirs(d, exist_ok=True)
        model_file = os.path.join(d, f"{step_id}.model")
        model.save(model_file)
        path = self._recovery_state_path()
        state = {"models": []}
        if os.path.exists(path):
            state = json.load(open(path))
        # keyed by step id: a retrain after a failed resume-load must
        # replace the stale entry, not duplicate it
        state["models"] = [e for e in state["models"] if e[0] != step_id]
        state["models"].append([step_id, model_file])
        json.dump(state, open(path, "w"))

    # --------------------------------------------------------------- train
    def train(self, frame: Frame, valid: Optional[Frame] = None) -> Model:
        p = self.params
        if not p.response_column:
            raise ValueError("automl requires response_column")
        t0 = time.time()
        if p.auto_recovery_dir:
            self._load_recovery()
        frame, valid = self._maybe_target_encode(frame, valid)

        plan = [s for s in self._plan()
                if s["id"] not in self._completed_steps]
        total_weight = sum(s["weight"] for s in plan) or 1

        def budget_left(n_planned: int = 0) -> bool:
            if p.max_models and len(self.models) + n_planned > p.max_models:
                return False
            if p.max_runtime_secs and time.time() - t0 > p.max_runtime_secs:
                return False
            return True

        spent_weight = 0
        # Steps execute in WAVES of up to `parallelism` concurrent builds
        # (ModelingStepsExecutor with a bounded pool); budgets and
        # WorkAllocations fair-share checks run between waves.
        from ..models.parallel import effective_parallelism, map_builds
        par = effective_parallelism(p.parallelism, len(plan))

        def run_step(step):
            try:
                from ..runtime import failure
                failure.maybe_inject("automl_member")
                b = self._builder(step["algo"], step["params"])
                m = b.train(frame, valid)
                return step, m, None
            except Exception as e:                      # noqa: BLE001
                return step, None, e

        i = 0
        while i < len(plan):
            if not budget_left(1):
                break
            wave = []
            while i < len(plan) and len(wave) < par \
                    and budget_left(len(wave) + 1):
                step = plan[i]
                # WorkAllocations: skip a step whose proportional time
                # share is already exhausted (keeps late grid steps from
                # starving SEs)
                if p.max_runtime_secs:
                    elapsed = time.time() - t0
                    fair_share = p.max_runtime_secs * (
                        spent_weight / total_weight)
                    if step["group"] == "grid" and elapsed > max(
                            fair_share, p.max_runtime_secs
                            * (1 - p.exploitation_ratio)):
                        self.events.append({"step": step["id"],
                                            "skipped": "work_allocation"})
                        spent_weight += step["weight"]
                        i += 1
                        continue
                spent_weight += step["weight"]
                wave.append(step)
                i += 1
            if not wave:
                continue
            for step, m, err in map_builds(
                    [lambda s=s: run_step(s) for s in wave],
                    min(par, len(wave))):
                if err is not None:
                    self.events.append({"step": step["id"],
                                        "error": repr(err),
                                        "t": time.time() - t0})
                    continue
                m.output["automl_step"] = step["id"]
                self.models.append(m)
                self._completed_steps.append(step["id"])
                self.events.append({"step": step["id"], "model": m.key,
                                    "t": time.time() - t0})
                if p.auto_recovery_dir:
                    self._save_recovery(step["id"], m)

        if not self.models:
            raise RuntimeError(
                f"automl: every modeling step failed; events: {self.events}")

        # stacked ensembles (BestOfFamily + AllModels), CV stacking
        se_excluded = any(a.lower().replace("_", "") == "stackedensemble"
                          for a in p.exclude_algos)
        if len(self.models) >= 2 and p.nfolds and p.nfolds > 1 \
                and not se_excluded:
            lb = Leaderboard(self.models, p.sort_metric)
            ranked = lb.sorted_models()
            best_of_family: List[Model] = []
            seen = set()
            for m in ranked:
                if m.algo not in seen:
                    seen.add(m.algo)
                    best_of_family.append(m)
            from ..models.ensemble import StackedEnsemble
            for name, base in (("SE_BestOfFamily", best_of_family),
                               ("SE_AllModels", ranked)):
                if len(base) < 2:
                    continue
                try:
                    se = StackedEnsemble(
                        response_column=p.response_column,
                        base_models=[m.key for m in base],
                        seed=p.seed).train(frame, valid)
                    se.output["automl_step"] = name
                    self.models.append(se)
                    self.events.append({"step": name, "model": se.key,
                                        "t": time.time() - t0})
                except Exception as e:                  # noqa: BLE001
                    self.events.append({"step": name, "error": repr(e),
                                        "t": time.time() - t0})

        self.leaderboard = Leaderboard(self.models, p.sort_metric)
        return self.leaderboard.leader

    def explain(self, frame, top_n: int = 5) -> dict:
        """h2o.explain(aml, frame) analog over the leaderboard models."""
        from ..explain import explain_models
        if self.leaderboard is None:
            raise RuntimeError("train() the AutoML run first")
        return explain_models(self.leaderboard.sorted_models(), frame,
                              top_n=top_n)

    @property
    def leader(self) -> Model:
        if self.leaderboard is None:
            raise RuntimeError("automl: train() has not been run")
        return self.leaderboard.leader
