"""AutoML: planned modeling steps + leaderboard + stacked ensembles.

Reference: ``h2o-automl`` — ``ai/h2o/automl/AutoML.java:49`` runs a plan of
``ModelingStep``s from per-algo providers
(modeling/{GLM,GBM,DRF,DeepLearning,StackedEnsemble,XGBoost}StepsProvider),
with time/model budgets (WorkAllocations), ranking in
``hex/leaderboard/Leaderboard.java:34``, and two final stacked ensembles
(BestOfFamily, AllModels).

TPU-native redesign: the plan is plain host control flow over this package's
builders; every step trains with common nfolds +
keep_cross_validation_predictions so the final SEs stack for free.  Budgets
are wall-clock/model-count checks between steps (model-parallel scheduling
across mesh slices is the natural extension, SURVEY.md §7 step 8).
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..models.base import Model
from ..models.grid import default_sort_metric, model_metric


class Leaderboard:
    """Ranked model container — hex/leaderboard/Leaderboard.java:34 analog."""

    def __init__(self, models: List[Model], sort_metric: Optional[str] = None):
        self.models = list(models)
        if models:
            default, lower = default_sort_metric(models[0])
            self.sort_metric = sort_metric or default
            from ..models.scorekeeper import METRIC_MAXIMIZE
            self.lower_is_better = lower if sort_metric is None else \
                not METRIC_MAXIMIZE.get(self.sort_metric, False)
        else:
            self.sort_metric, self.lower_is_better = "rmse", True

    def sorted_models(self) -> List[Model]:
        def keyfn(m):
            v = model_metric(m, self.sort_metric)
            if v is None:
                return np.inf if self.lower_is_better else -np.inf
            return v
        return sorted(self.models, key=keyfn,
                      reverse=not self.lower_is_better)

    @property
    def leader(self) -> Model:
        return self.sorted_models()[0]

    def as_table(self) -> List[dict]:
        rows = []
        for m in self.sorted_models():
            row = {"model_id": m.key, "algo": m.algo,
                   self.sort_metric: model_metric(m, self.sort_metric)}
            for extra in ("auc", "logloss", "rmse", "mae"):
                if extra != self.sort_metric:
                    v = model_metric(m, extra)
                    if v is not None:
                        row[extra] = v
            rows.append(row)
        return rows

    def __repr__(self):
        lines = [f"Leaderboard (by {self.sort_metric}):"]
        for r in self.as_table():
            lines.append(f"  {r['model_id']:<28} "
                         f"{r[self.sort_metric]}")
        return "\n".join(lines)


@dataclasses.dataclass
class AutoMLParameters:
    response_column: str = ""
    max_models: int = 10
    max_runtime_secs: float = 0.0            # 0 = no time budget
    nfolds: int = 5
    seed: int = -1
    include_algos: Optional[Sequence[str]] = None
    exclude_algos: Sequence[str] = ()
    sort_metric: Optional[str] = None
    weights_column: Optional[str] = None
    keep_cross_validation_predictions: bool = True


class AutoML:
    """AutoML driver — H2OAutoML analog (plan of steps + leaderboard + SEs)."""

    def __init__(self, params: Optional[AutoMLParameters] = None, **kw):
        self.params = params or AutoMLParameters(**kw)
        self.models: List[Model] = []
        self.leaderboard: Optional[Leaderboard] = None
        self.events: List[dict] = []

    # ------------------------------------------------------------ the plan
    def _plan(self) -> List[dict]:
        """Ordered steps — the {algo}StepsProvider defaults, trimmed."""
        p = self.params
        steps = [
            {"algo": "glm", "id": "GLM_1", "params": {"lambda_search": True}},
            {"algo": "gbm", "id": "GBM_1",
             "params": {"ntrees": 50, "max_depth": 6, "sample_rate": 0.8,
                        "col_sample_rate": 0.8}},
            {"algo": "gbm", "id": "GBM_2",
             "params": {"ntrees": 50, "max_depth": 7, "sample_rate": 0.9,
                        "col_sample_rate": 0.9}},
            {"algo": "gbm", "id": "GBM_3",
             "params": {"ntrees": 50, "max_depth": 8}},
            {"algo": "drf", "id": "DRF_1", "params": {"ntrees": 50}},
            {"algo": "drf", "id": "XRT_1",
             "params": {"ntrees": 50, "sample_rate": 0.632}},
            {"algo": "xgboost", "id": "XGBoost_1",
             "params": {"ntrees": 50, "max_depth": 6}},
            {"algo": "xgboost", "id": "XGBoost_2",
             "params": {"ntrees": 50, "max_depth": 8, "sample_rate": 0.8}},
            {"algo": "deeplearning", "id": "DeepLearning_1",
             "params": {"hidden": [64, 64], "epochs": 10}},
        ]
        include = set(a.lower() for a in p.include_algos) \
            if p.include_algos else None
        exclude = set(a.lower() for a in p.exclude_algos)
        out = []
        for s in steps:
            if include is not None and s["algo"] not in include:
                continue
            if s["algo"] in exclude:
                continue
            out.append(s)
        return out

    def _builder(self, algo: str, params: dict):
        from ..models import GLM, GBM, DRF, XGBoost, DeepLearning
        p = self.params
        common = dict(response_column=p.response_column,
                      weights_column=p.weights_column,
                      nfolds=p.nfolds, seed=p.seed,
                      keep_cross_validation_predictions=
                      p.keep_cross_validation_predictions)
        cls = {"glm": GLM, "gbm": GBM, "drf": DRF, "xgboost": XGBoost,
               "deeplearning": DeepLearning}[algo]
        return cls(**{**common, **params})

    # --------------------------------------------------------------- train
    def train(self, frame: Frame, valid: Optional[Frame] = None) -> Model:
        p = self.params
        if not p.response_column:
            raise ValueError("automl requires response_column")
        t0 = time.time()

        def budget_left(n_planned: int = 0) -> bool:
            if p.max_models and len(self.models) + n_planned > p.max_models:
                return False
            if p.max_runtime_secs and time.time() - t0 > p.max_runtime_secs:
                return False
            return True

        for step in self._plan():
            if not budget_left(1):
                break
            try:
                b = self._builder(step["algo"], step["params"])
                m = b.train(frame, valid)
                m.output["automl_step"] = step["id"]
                self.models.append(m)
                self.events.append({"step": step["id"], "model": m.key,
                                    "t": time.time() - t0})
            except Exception as e:                      # noqa: BLE001
                self.events.append({"step": step["id"], "error": repr(e),
                                    "t": time.time() - t0})

        if not self.models:
            raise RuntimeError(
                f"automl: every modeling step failed; events: {self.events}")

        # stacked ensembles (BestOfFamily + AllModels), CV stacking
        se_excluded = any(a.lower().replace("_", "") == "stackedensemble"
                          for a in p.exclude_algos)
        if len(self.models) >= 2 and p.nfolds and p.nfolds > 1 \
                and not se_excluded:
            lb = Leaderboard(self.models, p.sort_metric)
            ranked = lb.sorted_models()
            best_of_family: List[Model] = []
            seen = set()
            for m in ranked:
                if m.algo not in seen:
                    seen.add(m.algo)
                    best_of_family.append(m)
            from ..models.ensemble import StackedEnsemble
            for name, base in (("SE_BestOfFamily", best_of_family),
                               ("SE_AllModels", ranked)):
                if len(base) < 2:
                    continue
                try:
                    se = StackedEnsemble(
                        response_column=p.response_column,
                        base_models=[m.key for m in base],
                        seed=p.seed).train(frame, valid)
                    se.output["automl_step"] = name
                    self.models.append(se)
                    self.events.append({"step": name, "model": se.key,
                                        "t": time.time() - t0})
                except Exception as e:                  # noqa: BLE001
                    self.events.append({"step": name, "error": repr(e),
                                        "t": time.time() - t0})

        self.leaderboard = Leaderboard(self.models, p.sort_metric)
        return self.leaderboard.leader

    @property
    def leader(self) -> Model:
        if self.leaderboard is None:
            raise RuntimeError("automl: train() has not been run")
        return self.leaderboard.leader
