"""Continuous micro-batching + the published-model registry.

Requests land in an admission queue; a ticker thread drains up to
``serve_max_batch`` rows per tick into the fixed device-shaped
``[max_batch, F]`` buffer (ONE compiled signature — short batches pad,
so the AOT executable from warm-up serves every launch), runs the
packed scoring program once, and demuxes slices of the result back to
the waiting callers.  Knobs ride ``H2O3_TPU_SERVE_*`` (runtime/config):
tick interval, batch capacity, queue depth.

Prometheus series (runtime/observability registry, already exposed at
``GET /metrics``): ``serve_batch_size`` (rows per launch),
``serve_queue_depth`` (rows waiting at drain),
``serve_latency_seconds{phase=queue|device|total}``, and
``serve_rejected_total{reason=queue_full|deadline}`` — the latter when
admission overflows or a request exceeds its per-request deadline
(``H2O3_TPU_SERVE_DEADLINE_MS``; shed with HTTP 503, also during
SIGTERM drain).

``publish(key, model)`` packs a trained model, starts its batcher, and
warms the executable so the first real request never pays a compile;
the REST layer calls ``ensure_published`` lazily on first traffic.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..runtime import observability as obs
from ..runtime.config import config

_BATCH_BUCKETS = (1., 2., 4., 8., 16., 32., 64., 128., 256., 512., 1024.)


class DeadlineExceeded(RuntimeError):
    """A request waited in the serving queue past its per-request
    deadline (``H2O3_TPU_SERVE_DEADLINE_MS``) and was shed — the REST
    layer maps this to HTTP 503 so clients retry elsewhere instead of
    hanging behind a backed-up device."""


class _Pending:
    __slots__ = ("X", "out", "error", "event", "t_enqueue", "t_launch")

    def __init__(self, X: np.ndarray):
        self.X = X
        self.out: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.t_enqueue = time.perf_counter()
        self.t_launch = 0.0


class MicroBatcher:
    """Continuous micro-batcher in front of one ``PackedScorer``."""

    def __init__(self, scorer, max_batch: Optional[int] = None,
                 tick_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None):
        cfg = config()
        self.scorer = scorer
        self.max_batch = int(max_batch or cfg.serve_max_batch)
        self.tick_s = float(tick_ms if tick_ms is not None
                            else cfg.serve_tick_ms) / 1000.0
        self.queue_depth = int(queue_depth or cfg.serve_queue_depth)
        # per-request queue deadline (0 = none): expired requests are
        # shed at drain time and during close(), never dispatched
        self.deadline_s = float(deadline_ms if deadline_ms is not None
                                else cfg.serve_deadline_ms) / 1000.0
        self._queue: "collections.deque[_Pending]" = collections.deque()
        self._queued_rows = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # ---------------------------------------------------------- callers
    def submit(self, X: np.ndarray,
               score_mode: Optional[str] = None) -> np.ndarray:
        """Score a raw f32 design matrix; blocks until the demuxed
        result is ready.  Requests wider than the device buffer score
        in max_batch-row chunks through the same queue."""
        X = np.ascontiguousarray(X, dtype=np.float32)
        if score_mode not in (None, "", "packed"):
            # parity modes bypass the shared buffer: they are a
            # debugging surface, not the hot path
            return self.scorer.score(X, score_mode=score_mode)
        chunks = [X[i:i + self.max_batch]
                  for i in range(0, X.shape[0], self.max_batch)] or [X]
        pending = []
        with self._cv:
            if self._closed:
                raise RuntimeError("serving batcher is shut down")
            if self._queued_rows + X.shape[0] > self.queue_depth:
                obs.inc("serve_rejected_total", reason="queue_full")
                raise RuntimeError(
                    f"serving queue full ({self._queued_rows} rows "
                    f"waiting, depth {self.queue_depth})")
            for c in chunks:
                p = _Pending(c)
                self._queue.append(p)
                pending.append(p)
            self._queued_rows += X.shape[0]
            self._cv.notify()
        outs = []
        for p in pending:
            p.event.wait()
            if p.error is not None:
                raise p.error
            outs.append(p.out)
            obs.observe("serve_latency_seconds",
                        time.perf_counter() - p.t_enqueue, phase="total")
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def warmup(self) -> float:
        """Compile + launch the full-buffer signature; returns seconds."""
        t0 = time.perf_counter()
        dummy = np.zeros((self.max_batch, self.scorer.nfeatures),
                         dtype=np.float32)
        self.scorer.score(dummy)
        return time.perf_counter() - t0

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=2.0)
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
        # SIGTERM drain: requests already past their deadline shed as
        # 503s, the rest error as a shutdown — nothing hangs
        now = time.perf_counter()
        for p in leftovers:
            if self.deadline_s > 0 and now - p.t_enqueue > self.deadline_s:
                self._expire(p, now)
            else:
                p.error = RuntimeError("serving batcher shut down")
                p.event.set()

    # ----------------------------------------------------------- ticker
    def _expire(self, p: "_Pending", now: float) -> None:
        obs.inc("serve_rejected_total", reason="deadline")
        p.error = DeadlineExceeded(
            f"request waited {(now - p.t_enqueue) * 1e3:.0f}ms in the "
            f"serving queue, past its {self.deadline_s * 1e3:.0f}ms "
            f"deadline")
        p.event.set()

    def _drain_locked(self):
        batch, rows = [], 0
        now = time.perf_counter()
        while self._queue and rows + self._queue[0].X.shape[0] \
                <= self.max_batch:
            p = self._queue.popleft()
            self._queued_rows -= p.X.shape[0]
            if self.deadline_s > 0 \
                    and now - p.t_enqueue > self.deadline_s:
                self._expire(p, now)     # shed, don't dispatch
                continue
            rows += p.X.shape[0]
            batch.append(p)
        return batch, rows

    def _run(self):
        cfg_F = self.scorer.nfeatures
        buf = np.zeros((self.max_batch, cfg_F), dtype=np.float32)
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                # continuous batching: the tick window lets co-arriving
                # requests coalesce into one launch
                deadline = self._queue[0].t_enqueue + self.tick_s
            delay = deadline - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            with self._cv:
                batch, rows = self._drain_locked()
                obs.set_gauge("serve_queue_depth", self._queued_rows)
            if not batch:
                continue
            if obs.enabled():
                obs.histogram("serve_batch_size",
                              buckets=_BATCH_BUCKETS).observe(rows)
            t_launch = time.perf_counter()
            for p in batch:
                p.t_launch = t_launch
                obs.observe("serve_latency_seconds",
                            t_launch - p.t_enqueue, phase="queue")
            buf[:] = 0.0
            off = 0
            for p in batch:
                buf[off:off + p.X.shape[0]] = p.X
                off += p.X.shape[0]
            try:
                out = self.scorer.score(buf)
            except Exception as e:       # noqa: BLE001 — demux the error
                for p in batch:
                    p.error = e
                    p.event.set()
                continue
            obs.observe("serve_latency_seconds",
                        time.perf_counter() - t_launch, phase="device")
            off = 0
            for p in batch:
                p.out = out[off:off + p.X.shape[0]]
                off += p.X.shape[0]
                p.event.set()


# ----------------------------------------------------------- publishing

class ServingEntry:
    """One published model: packed scorer + its micro-batcher."""

    def __init__(self, key: str, scorer, batcher: MicroBatcher,
                 warmup_s: float):
        self.key = key
        self.scorer = scorer
        self.batcher = batcher
        self.warmup_s = warmup_s

    def predict_rows(self, rows, score_mode: Optional[str] = None) -> dict:
        X = self.scorer.featurize(rows)
        probs = self.batcher.submit(X, score_mode=score_mode)
        return self.scorer.decode(np.asarray(probs))


_registry: Dict[str, ServingEntry] = {}
_registry_lock = threading.Lock()

# journaled publishes: a WAL-durable `!serve/<model>` DKV record + model
# artifact under the recovery dir, so the serving plane survives a
# coordinator restart (republish_journaled() in deploy/serve.py's
# relaunch path) — the in-memory _registry alone did not
SERVE_PREFIX = "!serve/"


def _journal_uri(key: str) -> Optional[str]:
    from ..runtime import recovery
    base = recovery.recovery_dir()
    if not base:
        return None
    import re
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", key)
    return f"{base.rstrip('/')}/serve_{safe}.model"


def _journal_publish(key: str, model, warm: bool) -> None:
    """Best-effort: persist the model artifact + a `!serve/` pointer."""
    uri = _journal_uri(key)
    if uri is None or model is None:
        return
    try:
        from ..runtime import dkv
        model.save(uri)
        dkv.put(SERVE_PREFIX + key,
                {"uri": uri, "warm": bool(warm), "ts": time.time()})
    except Exception as e:               # noqa: BLE001 — serving still up
        obs.log.warning("serving: journal of publish %r failed: %r", key, e)


def publish(key: str, model=None, warm: bool = True,
            journal: bool = True) -> ServingEntry:
    """Pack + batch + warm one model for realtime scoring (idempotent).

    ``model=None`` resolves the key from the DKV — the REST layer's
    model-publish hook.  With a recovery dir configured the publish is
    journaled (``journal=False`` only on the re-publish path itself).
    """
    with _registry_lock:
        ent = _registry.get(key)
    if ent is not None:
        return ent
    if model is None:
        from ..runtime import dkv
        model = dkv.get(key)
        if model is None:
            raise KeyError(f"no model {key!r}")
    from ..export import mojo
    from .kernel import PackedScorer
    meta, arrays = mojo._extract(model)
    from ..export.scoring import ScoringModel
    scorer = PackedScorer(ScoringModel(meta, arrays))
    batcher = MicroBatcher(scorer)
    warmup_s = batcher.warmup() if warm else 0.0
    ent = ServingEntry(key, scorer, batcher, warmup_s)
    with _registry_lock:
        ent = _registry.setdefault(key, ent)
    if ent.batcher is not batcher:       # lost the publish race
        batcher.close()
    obs.set_gauge("serve_published_models", len(_registry))
    if journal:
        _journal_publish(key, model, warm)
    return ent


def ensure_published(key: str) -> ServingEntry:
    with _registry_lock:
        ent = _registry.get(key)
    return ent if ent is not None else publish(key)


def unpublish(key: str) -> bool:
    with _registry_lock:
        ent = _registry.pop(key, None)
    try:                                 # retract the journaled publish
        from .. import persist
        from ..runtime import dkv
        if dkv.get(SERVE_PREFIX + key) is not None:
            dkv.remove(SERVE_PREFIX + key)
        uri = _journal_uri(key)
        if uri:
            persist.delete(uri)
    except Exception:                    # noqa: BLE001 — best-effort
        pass
    if ent is None:
        return False
    ent.batcher.close()
    obs.set_gauge("serve_published_models", len(_registry))
    return True


def republish_journaled() -> List[str]:
    """Re-publish every journaled serving model not already live — the
    coordinator-restart path (deploy/serve.py relaunch): models are
    reloaded from their saved artifacts when the DKV lost them."""
    from ..runtime import dkv
    out: List[str] = []
    for k in dkv.keys(SERVE_PREFIX):
        key = k[len(SERVE_PREFIX):]
        with _registry_lock:
            if key in _registry:
                continue
        rec = dkv.get(k)
        if not isinstance(rec, dict):
            continue
        try:
            model = dkv.get(key)
            if model is None and rec.get("uri"):
                from ..models.base import Model
                model = Model.load(rec["uri"])   # re-registers under key
            publish(key, model, warm=bool(rec.get("warm", True)),
                    journal=False)
            out.append(key)
        except Exception as e:           # noqa: BLE001 — keep going
            obs.log.warning("serving: re-publish of journaled %r "
                            "failed: %r", key, e)
    if out:
        obs.record("serve_republish", models=out)
    return out


def shutdown_all():
    """Drain-and-stop every published batcher (process shutdown)."""
    with _registry_lock:
        entries = list(_registry.values())
        _registry.clear()
    for ent in entries:
        ent.batcher.close()
