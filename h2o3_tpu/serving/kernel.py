"""Fused packed-ensemble traversal — the online scoring program.

One jitted program scores a ``[B, F]`` request batch against the whole
bitpacked ensemble (serving/pack.py layout): a ``depth``-step
``fori_loop`` advances every (row, tree) node pointer through the int32
word plane — no per-tree dispatch, no host loop — then class-reduces
and applies the link, all inside one executable.  The program registers
in the PR 10 compile ledger (``xprof.register_program("serve_score")``)
so serving executables are AOT-compiled once per batch signature, warm
at first request, and their flops/bytes are already Prometheus series.

Implementations mirror the hist.py convention:

* ``impl="xla"`` — gather-based twin, the off-TPU oracle (CPU/GPU).
* ``impl="pallas"`` — batch-tiled Mosaic kernel, node planes in VMEM;
  real-chip validation is a carry-over acceptance gate like the other
  TPU kernels (``pallas_interpret`` pins interpret mode for CI).
* ``impl="auto"`` — pallas on TPU, xla elsewhere.

``PackedScorer.score(..., score_mode=...)`` mirrors the
``hist_mode``/``split_mode`` knob convention: ``"packed"`` runs the
device program, ``"ref"`` the numpy ``ScoringModel`` walk, ``"check"``
runs both and raises on divergence.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..runtime import xprof
from ..runtime.config import config
from . import pack as packmod

_SCORE_MODES = ("packed", "ref", "check")


# ------------------------------------------------------------ traversal

def _step(nodes_i32, nodes_f32, X, node):
    """One depth step: advance every [B, R] node pointer (leaves self-loop)."""
    w = jnp.take(nodes_i32, node)
    leaf = (w >> packmod.LEAF_BIT) & 1
    feat = w & packmod.FEAT_MASK
    nal = (w >> packmod.NA_LEFT_BIT) & 1
    delta = (w >> packmod.DELTA_SHIFT) & packmod.DELTA_MASK
    thr = jnp.take(nodes_f32, node)
    x = jnp.take_along_axis(X, feat, axis=1)
    right = jnp.where(jnp.isnan(x), nal == 0, x >= thr).astype(jnp.int32)
    return node + jnp.where(leaf == 1, 0, delta + right)


def _traverse_xla(nodes_i32, nodes_f32, roots, X, depth: int):
    """[B, F] batch -> [B, R] leaf values, R = K*T trees."""
    B = X.shape[0]
    node = jnp.broadcast_to(roots[None, :], (B, roots.shape[0]))
    node = lax.fori_loop(
        0, depth, lambda _, n: _step(nodes_i32, nodes_f32, X, n), node)
    return jnp.take(nodes_f32, node)


def _make_pallas_traverse(depth: int, R: int, F: int, tile_b: int,
                          interpret: bool = False):
    """Batch-tiled kernel: node planes + roots resident in VMEM, one
    program instance per ``tile_b`` rows of the request batch."""
    from jax.experimental import pallas as pl

    def kernel(i32_ref, f32_ref, roots_ref, x_ref, out_ref):
        nodes_i32 = i32_ref[:]
        nodes_f32 = f32_ref[:]
        X = x_ref[:]
        node = jnp.broadcast_to(roots_ref[:][None, :], (tile_b, R))
        node = lax.fori_loop(
            0, depth, lambda _, n: _step(nodes_i32, nodes_f32, X, n), node)
        out_ref[:] = jnp.take(nodes_f32, node)

    def call(nodes_i32, nodes_f32, roots, X):
        B = X.shape[0]
        grid = (B // tile_b,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(nodes_i32.shape, lambda i: (0,)),
                pl.BlockSpec(nodes_f32.shape, lambda i: (0,)),
                pl.BlockSpec(roots.shape, lambda i: (0,)),
                pl.BlockSpec((tile_b, F), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((tile_b, R), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((B, R), jnp.float32),
            interpret=interpret,
        )(nodes_i32, nodes_f32, roots, X)

    return call


def _traverse_impl(impl: str, depth: int, R: int, F: int, B: int):
    """Resolve the traversal implementation for one batch signature."""
    if impl in ("", "auto"):
        from ..runtime import autotune
        impl = autotune.resolve_serve_impl(depth=depth, R=R, F=F, B=B)
    if impl == "xla":
        return functools.partial(_traverse_xla, depth=depth)
    if impl in ("pallas", "pallas_interpret"):
        interpret = impl == "pallas_interpret" or \
            jax.default_backend() != "tpu"
        tile_b = B if B <= 128 else 128
        while B % tile_b:
            tile_b //= 2
        return _make_pallas_traverse(depth, R, F, max(tile_b, 1),
                                     interpret=interpret)
    raise ValueError(f"unknown serve impl {impl!r} "
                     "(xla | pallas | pallas_interpret | auto)")


# ---------------------------------------------------------- the program

def _postprocess(sums, init, family: str, n_class: int, avg: bool,
                 ntrees: int, binomial: bool, link: str, c_norm: float,
                 xp=jnp):
    """[B, K] per-class leaf sums -> probability/score matrix.

    Mirrors ``ScoringModel._score_tree`` / ``_score_isolation`` exactly;
    ``xp`` swaps numpy in for the ref/check paths so both sides share
    one formula.
    """
    if family == "isolation":
        mean_len = sums[:, 0] / max(ntrees, 1)
        return xp.exp2(-mean_len / max(c_norm, 1e-9))[:, None]
    if n_class > 1:
        scores = sums + init[None, :]
        if avg:
            p = xp.clip(scores / max(ntrees, 1), 0, 1)
            return p / xp.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        e = xp.exp(scores - scores.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)
    s = sums[:, 0] + init[0]
    if avg:
        s = s / max(ntrees, 1)
    if binomial:
        p1 = xp.clip(s if avg else 1 / (1 + xp.exp(-s)), 0.0, 1.0)
        return xp.stack([1 - p1, p1], axis=1)
    return (xp.exp(s) if link == "log" else s)[:, None]


class PackedScorer:
    """Device-resident packed ensemble + one AOT-compiled scoring program.

    Built from a numpy ``ScoringModel`` (mojo ``_extract`` output) — the
    scoring model stays attached as featurizer and as the "ref"/"check"
    oracle.  ``score(X)`` maps a raw f32 design batch to the probability
    matrix ``ScoringModel._score`` would produce; ``predict_rows`` adds
    row featurization and label decode for the REST realtime route.
    """

    def __init__(self, scoring_model, impl: Optional[str] = None):
        meta = scoring_model.meta
        if meta.get("family") not in ("tree", "isolation"):
            raise ValueError("packed serving supports tree/isolation "
                             f"ensembles, not {meta.get('family')!r}")
        self.ref = scoring_model
        self.meta = meta
        spec = meta["datainfo"]
        self.nfeatures = len(spec["specs"])
        self.packed = packmod.pack_ensemble(meta, scoring_model.arrays,
                                            self.nfeatures)
        self.impl = (impl if impl is not None
                     else config().serve_impl) or "auto"
        self.family = meta["family"]
        self.n_class = self.packed.n_class
        self.ntrees = self.packed.ntrees
        self.depth = self.packed.depth
        self.avg = bool(meta.get("tree_average", False))
        self.binomial = bool(spec.get("response_domain")) \
            and self.n_class == 1 and self.family == "tree"
        self.link = meta.get("link", "identity")
        self.c_norm = float(meta.get("c_norm", 1.0))
        init = meta.get("init_score", 0.0)
        self._init = np.atleast_1d(np.asarray(init, np.float32))
        # device residency: planes uploaded once, reused every launch
        self._d_i32 = jax.device_put(self.packed.nodes_i32)
        self._d_f32 = jax.device_put(self.packed.nodes_f32)
        self._d_roots = jax.device_put(self.packed.roots)
        self._d_init = jax.device_put(self._init)
        self._programs = {}

    # ------------------------------------------------------------ device
    def _program(self, B: int):
        """One ledger-registered executable per (batch, impl) signature."""
        key = (B, self.impl)
        prog = self._programs.get(key)
        if prog is None:
            R = int(self.packed.roots.shape[0])
            traverse = _traverse_impl(self.impl, self.depth, R,
                                      self.nfeatures, B)
            K, T = self.n_class, self.ntrees

            def score(nodes_i32, nodes_f32, roots, init, X):
                leaves = traverse(nodes_i32, nodes_f32, roots, X)
                sums = leaves.reshape(X.shape[0], K, T).sum(axis=2)
                return _postprocess(sums, init, self.family, K, self.avg,
                                    T, self.binomial, self.link,
                                    self.c_norm)

            prog = xprof.register_program("serve_score", jax.jit(score),
                                          orig=score)
            self._programs[key] = prog
        return prog

    # ----------------------------------------------------------- scoring
    def _packed_scores(self, X: np.ndarray) -> np.ndarray:
        prog = self._program(X.shape[0])
        out = prog(self._d_i32, self._d_f32, self._d_roots, self._d_init,
                   jnp.asarray(X, jnp.float32))
        return np.asarray(out)

    def _ref_scores(self, X: np.ndarray) -> np.ndarray:
        leaves = packmod.traverse(self.packed.nodes_i32,
                                  self.packed.nodes_f32,
                                  self.packed.roots, X, self.depth)
        sums = leaves.reshape(X.shape[0], self.n_class, self.ntrees) \
            .sum(axis=2)
        return _postprocess(sums, self._init, self.family, self.n_class,
                            self.avg, self.ntrees, self.binomial,
                            self.link, self.c_norm, xp=np)

    def score(self, X: np.ndarray,
              score_mode: Optional[str] = None) -> np.ndarray:
        """Raw f32 design batch ``[B, F]`` -> probability/score matrix."""
        mode = (score_mode if score_mode is not None
                else config().serve_score_mode) or "packed"
        if mode not in _SCORE_MODES:
            raise ValueError(f"score_mode {mode!r} not in {_SCORE_MODES}")
        X = np.ascontiguousarray(X, dtype=np.float32)
        if mode == "ref":
            return self._ref_scores(X)
        out = self._packed_scores(X)
        if mode == "check":
            ref = self._ref_scores(X)
            if not np.allclose(out, ref, rtol=1e-4, atol=1e-5,
                               equal_nan=True):
                diff = float(np.nanmax(np.abs(out - ref)))
                raise AssertionError(
                    f"score_mode='check' diverged: packed vs ref "
                    f"max|diff|={diff:.3e}")
        return out

    # --------------------------------------------------------- row plane
    def featurize(self, rows) -> np.ndarray:
        """List of row dicts -> raw f32 design matrix (cat codes, NaN)."""
        cols = {}
        for s in self.meta["datainfo"]["specs"]:
            name = s["name"]
            vals = [r.get(name) for r in rows]
            cols[name] = np.asarray(
                ["" if v is None else v for v in vals]
                if any(isinstance(v, str) for v in vals)
                else [np.nan if v is None else v for v in vals])
        return self.ref._design_raw(cols, len(rows))

    def decode(self, probs: np.ndarray) -> dict:
        """Probability matrix -> the ScoringModel.predict output shape."""
        domain = self.meta["datainfo"].get("response_domain")
        if domain and self.family == "tree":
            labels = np.asarray(domain, dtype=object)[
                np.argmax(probs, axis=1)]
            if probs.shape[1] == 2:
                thr = self.meta.get("default_threshold", 0.5)
                labels = np.asarray(domain, dtype=object)[
                    (probs[:, 1] >= thr).astype(int)]
            return {"predict": labels, "probabilities": probs}
        return {"predict": probs[:, 0]}

    def predict_rows(self, rows,
                     score_mode: Optional[str] = None) -> dict:
        return self.decode(self.score(self.featurize(rows),
                                      score_mode=score_mode))
