"""Online scoring plane: bitpacked ensembles, fused traversal, batching.

Layout:

* ``pack``    — numpy-only bitpacked node-array packer (imported by
  ``export/scoring.py``; keep it jax-free).
* ``kernel``  — the fused device traversal (XLA twin + Pallas variant)
  behind ``PackedScorer`` with ``score_mode="packed"|"ref"|"check"``.
* ``batcher`` — continuous micro-batching + the published-model
  registry behind ``POST /3/Predictions/realtime/{model}``.

Imports are lazy so ``pack`` stays importable without pulling jax.
"""

from __future__ import annotations

_LAZY = {
    "PackedScorer": ("kernel", "PackedScorer"),
    "MicroBatcher": ("batcher", "MicroBatcher"),
    "publish": ("batcher", "publish"),
    "ensure_published": ("batcher", "ensure_published"),
    "unpublish": ("batcher", "unpublish"),
    "shutdown_all": ("batcher", "shutdown_all"),
}


def __getattr__(name: str):
    import importlib
    if name == "pack":
        return importlib.import_module(".pack", __name__)
    if name in _LAZY:
        mod_name, attr = _LAZY[name]
        mod = importlib.import_module(f".{mod_name}", __name__)
        return getattr(mod, attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["pack", *_LAZY]
