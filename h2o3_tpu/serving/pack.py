"""Bitpacked flattened ensemble layout — numpy + stdlib ONLY.

Reference layout: the GPU tree-boosting paper (arXiv:1706.08359) flattens
an ensemble into a contiguous node array so traversal is one loop over
depth steps with no per-tree dispatch.  Here every node of every tree is
packed into two parallel planes:

* ``nodes_i32[N]`` — one int32 word per node::

      bits  0..9   feature id          (design-matrix column, F < 1024)
      bit   10     NA-goes-left        (missing value routed left)
      bit   11     leaf flag           (word is a terminal node)
      bits  12..31 left-child delta    (child_index - node_index, >= 0)

* ``nodes_f32[N]`` — split threshold (internal) or leaf value (leaf).

Trees are concatenated (BFS order per tree, levels contiguous) with the
root index of tree ``t`` in ``roots[t]``; a multinomial ensemble
concatenates its K per-class groups so ``roots`` has ``K*T`` entries.
Leaves are packed as self-loops (delta unused behind the leaf mask), so
a fixed ``depth``-step descent is branch-free: rows that reach a leaf
early simply re-read it.

This module is imported by ``export/scoring.py`` (the deployment
contract's numpy-only half) — it must never import jax; the jax twin
lives in ``serving/kernel.py`` and shares these constants.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import numpy as np

FEAT_MASK = 0x3FF            # bits 0..9
NA_LEFT_BIT = 10
LEAF_BIT = 11
DELTA_SHIFT = 12
DELTA_MASK = 0xFFFFF         # 20 bits
MAX_FEATURES = FEAT_MASK + 1


@dataclasses.dataclass(frozen=True)
class PackedEnsemble:
    """Device-shaped ensemble: two node planes + per-tree root offsets."""
    nodes_i32: np.ndarray    # [N] int32 packed words
    nodes_f32: np.ndarray    # [N] float32 threshold-or-leaf-value
    roots: np.ndarray        # [K*T] int32 tree start indices
    n_class: int             # K (class-tree groups; 1 for binomial/reg)
    ntrees: int              # T per group
    depth: int               # max depth (traversal step count)
    nfeatures: int           # design-matrix width F

    @property
    def n_nodes(self) -> int:
        return int(self.nodes_i32.shape[0])

    def nbytes(self) -> int:
        return int(self.nodes_i32.nbytes + self.nodes_f32.nbytes
                   + self.roots.nbytes)


def pack_group(arrays: Dict[str, np.ndarray], depth: int, prefix: str = "",
               base: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack one class group of heap-layout trees into node planes.

    ``arrays`` holds the mojo export layout: ``{prefix}feat_d`` /
    ``thr_d`` / ``na_left_d`` / ``valid_d`` as ``[T, 2^d]`` plus
    ``{prefix}values`` as ``[T, 2^depth]``.  A heap slot exists iff its
    parent chain is valid; an existing slot is internal iff ``valid``,
    else it is a leaf whose value sits at ``values[i << (depth - d)]``
    (the all-left heap descendant — exactly where the level-walk
    scorer lands).  Returns ``(nodes_i32, nodes_f32, roots)`` with node
    indices offset by ``base`` (for multi-group concatenation).
    """
    values = np.asarray(arrays[f"{prefix}values"], dtype=np.float32)
    T = values.shape[0]
    exist = [np.ones((T, 1), dtype=bool)]
    valid = []
    for d in range(depth):
        v = np.asarray(arrays[f"{prefix}valid_{d}"], dtype=bool)
        internal = exist[d] & v
        nxt = np.zeros((T, 2 ** (d + 1)), dtype=bool)
        nxt[:, 0::2] = internal
        nxt[:, 1::2] = internal
        valid.append(v)
        exist.append(nxt)

    counts = np.stack([e.sum(axis=1) for e in exist])        # [depth+1, T]
    level_off = np.zeros_like(counts)
    if depth:
        level_off[1:] = np.cumsum(counts[:-1], axis=0)
    tree_size = counts.sum(axis=0).astype(np.int64)          # [T]
    tree_base = np.zeros(T, dtype=np.int64)
    tree_base[1:] = np.cumsum(tree_size)[:-1]
    tree_base += base

    # absolute node index per existing heap slot, level by level
    idx = []
    for d in range(depth + 1):
        rank = np.cumsum(exist[d], axis=1) - 1
        idx.append(tree_base[:, None] + level_off[d][:, None] + rank)

    total = int(tree_size.sum())
    i32 = np.zeros(total, dtype=np.int32)
    f32 = np.zeros(total, dtype=np.float32)
    leaf_word = np.int32(1 << LEAF_BIT)
    for d in range(depth + 1):
        e, ix = exist[d], idx[d]
        if d < depth:
            internal = e & valid[d]
            leaf = e & ~valid[d]
            if internal.any():
                feat = np.asarray(arrays[f"{prefix}feat_{d}"],
                                  dtype=np.int64)
                thr = np.asarray(arrays[f"{prefix}thr_{d}"],
                                 dtype=np.float32)
                nal = np.asarray(arrays[f"{prefix}na_left_{d}"], dtype=bool)
                if (feat[internal] < 0).any() or \
                        (feat[internal] >= MAX_FEATURES).any():
                    raise ValueError(
                        f"packed layout holds feature ids < {MAX_FEATURES}")
                delta = idx[d + 1][:, 0::2] - ix
                if (delta[internal] > DELTA_MASK).any():
                    raise ValueError("left-child delta overflows 20 bits "
                                     f"(depth {depth} tree too large)")
                word = (feat & FEAT_MASK) \
                    | (nal.astype(np.int64) << NA_LEFT_BIT) \
                    | (delta << DELTA_SHIFT)
                sel = ix[internal] - base
                i32[sel] = (word[internal] & 0xFFFFFFFF).astype(
                    np.uint32).view(np.int32)
                f32[sel] = thr[internal]
        else:
            leaf = e
        if leaf.any():
            # leaf value = where the heap level-walk bottoms out
            col = np.arange(e.shape[1], dtype=np.int64) << (depth - d)
            lv = values[:, col]                              # [T, 2^d]
            sel = ix[leaf] - base
            i32[sel] = leaf_word
            f32[sel] = lv[leaf]
    return i32, f32, tree_base.astype(np.int32)


def pack_ensemble(meta: dict, arrays: Dict[str, np.ndarray],
                  nfeatures: int) -> PackedEnsemble:
    """Pack a tree/isolation export (mojo ``_extract`` output) whole.

    Multinomial groups (``k{k}_`` prefixes) concatenate k-major so the
    scored ``[B, K*T]`` leaf matrix reshapes to ``[B, K, T]``.
    """
    if nfeatures >= MAX_FEATURES:
        raise ValueError(f"packed layout supports < {MAX_FEATURES} "
                         f"features, got {nfeatures}")
    K = int(meta.get("nclass_trees", 1) or 1)
    depth = int(meta["depth"])
    prefixes = [f"k{k}_" for k in range(K)] if K > 1 else [""]
    i32s, f32s, roots = [], [], []
    base = 0
    for p in prefixes:
        gi, gf, gr = pack_group(arrays, depth, prefix=p, base=base)
        i32s.append(gi)
        f32s.append(gf)
        roots.append(gr)
        base += gi.shape[0]
    return PackedEnsemble(
        nodes_i32=np.concatenate(i32s), nodes_f32=np.concatenate(f32s),
        roots=np.concatenate(roots), n_class=K,
        ntrees=int(meta["ntrees"]), depth=depth, nfeatures=nfeatures)


def traverse(nodes_i32: np.ndarray, nodes_f32: np.ndarray,
             roots: np.ndarray, X: np.ndarray, depth: int) -> np.ndarray:
    """Iterative packed descent — the numpy "ref" oracle.

    ``X`` is the raw f32 design matrix (cat codes, NaN missing).
    Returns the ``[n, R]`` leaf-value matrix (R = len(roots)).  Early
    exit: node-sparse deep trees (PR 7) bottom out levels before
    ``depth``, so once every (row, tree) sits on a leaf the remaining
    steps are identity self-loops and the walk stops.
    """
    n = X.shape[0]
    node = np.broadcast_to(roots.astype(np.int64)[None, :],
                           (n, roots.shape[0])).copy()
    for _ in range(depth):
        w = nodes_i32[node]
        leaf = (w >> LEAF_BIT) & 1
        if leaf.all():
            break
        feat = (w & FEAT_MASK).astype(np.int64)
        nal = (w >> NA_LEFT_BIT) & 1
        delta = ((w >> DELTA_SHIFT) & DELTA_MASK).astype(np.int64)
        thr = nodes_f32[node]
        x = np.take_along_axis(X, feat, axis=1)
        right = np.where(np.isnan(x), nal == 0, x >= thr)
        node += np.where(leaf == 1, 0, delta + right.astype(np.int64))
    return nodes_f32[node]
