"""Grid search: Cartesian + RandomDiscrete hyperparameter walkers.

Reference: ``hex/grid/GridSearch.java`` + ``HyperSpaceWalker.java:213-216``
(Cartesian and RandomDiscrete walkers with max_models / max_runtime_secs
budgets and early stopping over the model sequence) + ``hex/grid/Grid.java``
(the model container, sorted metric table, resumable).

TPU-native redesign: each grid entry is an independent compiled training
program; the walker is plain host control flow.  (Coarse model-parallel
scheduling across mesh slices is the multi-slice AutoML pattern from
SURVEY.md §7 — entries are embarrassingly parallel.)
"""

from __future__ import annotations

import json

import itertools
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from .base import Model, ModelBuilder
from .scorekeeper import stop_early


def default_sort_metric(model: Model) -> (str, bool):
    """(metric, lower_is_better) by model category (Leaderboard defaults)."""
    di = model.datainfo
    if di.is_classifier and di.nclasses == 2:
        return "auc", False
    if di.is_classifier:
        return "logloss", True
    return "rmse", True


def model_metric(model: Model, metric: str,
                 prefer: str = "cv") -> Optional[float]:
    """Pull a metric off CV metrics when present, else training metrics."""
    for m in ((model.cross_validation_metrics, model.validation_metrics,
               model.training_metrics) if prefer == "cv" else
              (model.validation_metrics, model.cross_validation_metrics,
               model.training_metrics)):
        if m is None:
            continue
        v = getattr(m, metric, None)
        if v is None and isinstance(m, dict):
            v = m.get(metric)
        if v is not None:
            return float(v)
    return None


class Grid:
    """Trained-grid container — hex/grid/Grid.java analog."""

    def __init__(self, key: str, models: List[Model],
                 hyper_names: Sequence[str], entries: List[dict],
                 sort_metric: str, decreasing: bool):
        self.key = key
        self.models = models
        self.hyper_names = list(hyper_names)
        self.entries = entries
        self.sort_metric = sort_metric
        self.decreasing = decreasing
        dkv.put(key, self)

    def _order(self) -> List[int]:
        vals = [model_metric(m, self.sort_metric) for m in self.models]
        keyed = [(v if v is not None else np.inf * (1 if not self.decreasing
                                                    else -1), i)
                 for i, v in enumerate(vals)]
        return [i for _, i in sorted(keyed, reverse=self.decreasing)]

    @property
    def best_model(self) -> Model:
        return self.models[self._order()[0]]

    def sorted_metric_table(self) -> List[dict]:
        rows = []
        for i in self._order():
            rows.append({**self.entries[i],
                         "model_id": self.models[i].key,
                         self.sort_metric: model_metric(
                             self.models[i], self.sort_metric)})
        return rows

    def save(self, path: str) -> str:
        """Persist the grid (h2o.save_grid analog): one file per model
        plus a manifest, under any persist URI prefix."""
        from .. import persist
        for i, m in enumerate(self.models):
            m.save(f"{path}/model_{i}.bin")
        with persist.open_write(f"{path}/grid.json") as f:
            f.write(json.dumps(
                {"key": self.key, "n_models": len(self.models),
                 "hyper_names": self.hyper_names, "entries": self.entries,
                 "sort_metric": self.sort_metric,
                 "decreasing": self.decreasing},
                # hyper values are often numpy scalars (np.arange grids)
                default=lambda o: o.item() if hasattr(o, "item") else str(o),
            ).encode())
        return path

    @staticmethod
    def load(path: str) -> "Grid":
        """h2o.load_grid analog."""
        from .. import persist
        with persist.open_read(f"{path}/grid.json") as f:
            meta = json.loads(f.read().decode())
        models = [Model.load(f"{path}/model_{i}.bin")
                  for i in range(meta["n_models"])]
        return Grid(meta["key"], models,
                    hyper_names=meta["hyper_names"],
                    entries=meta["entries"],
                    sort_metric=meta["sort_metric"],
                    decreasing=meta["decreasing"])

    def __repr__(self):
        return (f"<Grid {self.key}: {len(self.models)} models by "
                f"{self.sort_metric}>")


class GridSearch:
    """Grid driver — h2o.grid / H2OGridSearch analog.

    ``search_criteria``: {"strategy": "Cartesian"} (default) or
    {"strategy": "RandomDiscrete", "max_models": N, "max_runtime_secs": S,
    "seed": K, "stopping_rounds": R, "stopping_tolerance": T}.

    ``parallelism`` (GridSearch.java "parallelism"): 0 = auto (bounded
    pool), 1 = sequential, n = exactly n concurrent builds.  Parallel
    grids build in WAVES of ``parallelism`` models: budgets (max_models /
    max_runtime_secs) and sequence early-stopping are re-checked between
    waves, so stopping semantics degrade gracefully (a wave may overshoot
    by at most parallelism-1 models, exactly like the reference's
    parallel walker).
    """

    def __init__(self, builder_cls, hyper_params: Dict[str, Sequence],
                 search_criteria: Optional[dict] = None,
                 parallelism: int = 0, **base_params):
        self.builder_cls = builder_cls
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        self.search_criteria = dict(search_criteria or
                                    {"strategy": "Cartesian"})
        self.parallelism = parallelism
        self.base_params = base_params

    def _combos(self) -> List[dict]:
        names = list(self.hyper_params)
        all_combos = [dict(zip(names, vals)) for vals in
                      itertools.product(*(self.hyper_params[n]
                                          for n in names))]
        sc = self.search_criteria
        if sc.get("strategy", "Cartesian").lower() in (
                "randomdiscrete", "random_discrete"):
            rng = np.random.default_rng(sc.get("seed", 0))
            rng.shuffle(all_combos)
        return all_combos

    def train(self, frame: Frame, valid: Optional[Frame] = None,
              sort_metric: Optional[str] = None) -> Grid:
        sc = self.search_criteria
        max_models = sc.get("max_models", None)
        max_secs = sc.get("max_runtime_secs", None)
        stop_rounds = sc.get("stopping_rounds", 0)
        stop_tol = sc.get("stopping_tolerance", 1e-3)
        t0 = time.time()
        models, entries = [], []
        metric, decreasing = None, None
        series: List[float] = []
        combos = self._combos()
        from .parallel import effective_parallelism, map_builds
        par = effective_parallelism(self.parallelism, len(combos))
        pos = 0
        while pos < len(combos):
            if max_models and len(models) >= max_models:
                break
            if max_secs and time.time() - t0 > max_secs:
                break
            wave = combos[pos: pos + par]
            if max_models:
                wave = wave[: max_models - len(models)]
            pos += len(wave)

            def build(combo):
                # each member journals (and snapshots) itself through
                # ModelBuilder.train — the per-member resumability path
                from ..runtime import failure
                failure.maybe_inject("grid_member")
                builder = self.builder_cls(**{**self.base_params, **combo})
                return builder.train(frame, valid)

            for combo, m in zip(wave, map_builds(
                    [lambda c=c: build(c) for c in wave], par)):
                models.append(m)
                entries.append(combo)
                if metric is None:
                    if sort_metric is None:
                        metric, lower = default_sort_metric(m)
                    else:
                        from .scorekeeper import METRIC_MAXIMIZE
                        metric = sort_metric
                        lower = not METRIC_MAXIMIZE.get(sort_metric, False)
                    decreasing = not lower
                v = model_metric(m, metric)
                if v is not None:
                    series.append(v)
            # early stop over the *sequence of best-so-far* models,
            # checked between waves
            if stop_rounds and series and stop_early(
                    series, stop_rounds, stop_tol, maximize=decreasing):
                break
        if not models:
            raise ValueError("grid search trained no models")
        return Grid(dkv.make_key("grid"), models, list(self.hyper_params),
                    entries, metric, decreasing)
