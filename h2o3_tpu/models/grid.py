"""Grid search: Cartesian + RandomDiscrete hyperparameter walkers.

Reference: ``hex/grid/GridSearch.java`` + ``HyperSpaceWalker.java:213-216``
(Cartesian and RandomDiscrete walkers with max_models / max_runtime_secs
budgets and early stopping over the model sequence) + ``hex/grid/Grid.java``
(the model container, sorted metric table, resumable).

TPU-native redesign: each grid entry is an independent compiled training
program; the walker is plain host control flow.  (Coarse model-parallel
scheduling across mesh slices is the multi-slice AutoML pattern from
SURVEY.md §7 — entries are embarrassingly parallel.)
"""

from __future__ import annotations

import json

import itertools
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from .base import Model, ModelBuilder
from .scorekeeper import stop_early


def default_sort_metric(model: Model) -> (str, bool):
    """(metric, lower_is_better) by model category (Leaderboard defaults)."""
    di = model.datainfo
    if di.is_classifier and di.nclasses == 2:
        return "auc", False
    if di.is_classifier:
        return "logloss", True
    return "rmse", True


def model_metric(model: Model, metric: str,
                 prefer: str = "cv") -> Optional[float]:
    """Pull a metric off CV metrics when present, else training metrics."""
    for m in ((model.cross_validation_metrics, model.validation_metrics,
               model.training_metrics) if prefer == "cv" else
              (model.validation_metrics, model.cross_validation_metrics,
               model.training_metrics)):
        if m is None:
            continue
        v = getattr(m, metric, None)
        if v is None and isinstance(m, dict):
            v = m.get(metric)
        if v is not None:
            return float(v)
    return None


class _FailedBuild:
    """In-band sentinel for a wave member whose build raised — carried
    through map_builds' ordered results so sibling models survive."""

    def __init__(self, error: str):
        self.error = error


class Grid:
    """Trained-grid container — hex/grid/Grid.java analog."""

    def __init__(self, key: str, models: List[Model],
                 hyper_names: Sequence[str], entries: List[dict],
                 sort_metric: str, decreasing: bool,
                 failed_entries: Optional[List[dict]] = None):
        self.key = key
        self.models = models
        self.hyper_names = list(hyper_names)
        self.entries = entries
        self.sort_metric = sort_metric
        self.decreasing = decreasing
        # per-member fault tolerance (Grid.java failure_details analog):
        # combos whose build failed, each with its "error" repr — the
        # grid completes on the survivors instead of dying whole
        self.failed_entries = list(failed_entries or [])
        dkv.put(key, self)

    def _order(self) -> List[int]:
        vals = [model_metric(m, self.sort_metric) for m in self.models]
        keyed = [(v if v is not None else np.inf * (1 if not self.decreasing
                                                    else -1), i)
                 for i, v in enumerate(vals)]
        return [i for _, i in sorted(keyed, reverse=self.decreasing)]

    @property
    def best_model(self) -> Model:
        return self.models[self._order()[0]]

    def sorted_metric_table(self) -> List[dict]:
        rows = []
        for i in self._order():
            rows.append({**self.entries[i],
                         "model_id": self.models[i].key,
                         self.sort_metric: model_metric(
                             self.models[i], self.sort_metric)})
        return rows

    def save(self, path: str) -> str:
        """Persist the grid (h2o.save_grid analog): one file per model
        plus a manifest, under any persist URI prefix."""
        from .. import persist
        for i, m in enumerate(self.models):
            m.save(f"{path}/model_{i}.bin")
        with persist.open_write(f"{path}/grid.json") as f:
            f.write(json.dumps(
                {"key": self.key, "n_models": len(self.models),
                 "hyper_names": self.hyper_names, "entries": self.entries,
                 "sort_metric": self.sort_metric,
                 "decreasing": self.decreasing,
                 "failed_entries": self.failed_entries},
                # hyper values are often numpy scalars (np.arange grids)
                default=lambda o: o.item() if hasattr(o, "item") else str(o),
            ).encode())
        return path

    @staticmethod
    def load(path: str) -> "Grid":
        """h2o.load_grid analog."""
        from .. import persist
        with persist.open_read(f"{path}/grid.json") as f:
            meta = json.loads(f.read().decode())
        models = [Model.load(f"{path}/model_{i}.bin")
                  for i in range(meta["n_models"])]
        return Grid(meta["key"], models,
                    hyper_names=meta["hyper_names"],
                    entries=meta["entries"],
                    sort_metric=meta["sort_metric"],
                    decreasing=meta["decreasing"],
                    failed_entries=meta.get("failed_entries"))

    def __repr__(self):
        return (f"<Grid {self.key}: {len(self.models)} models by "
                f"{self.sort_metric}>")


class GridSearch:
    """Grid driver — h2o.grid / H2OGridSearch analog.

    ``search_criteria``: {"strategy": "Cartesian"} (default) or
    {"strategy": "RandomDiscrete", "max_models": N, "max_runtime_secs": S,
    "seed": K, "stopping_rounds": R, "stopping_tolerance": T}.

    ``parallelism`` (GridSearch.java "parallelism"): 0 = auto (bounded
    pool), 1 = sequential, n = exactly n concurrent builds.  Parallel
    grids build in WAVES of ``parallelism`` models: budgets (max_models /
    max_runtime_secs) and sequence early-stopping are re-checked between
    waves, so stopping semantics degrade gracefully (a wave may overshoot
    by at most parallelism-1 models, exactly like the reference's
    parallel walker).

    ``grid_batch``: "auto" (cost model picks), "on", or "off".  Combos
    that only vary scalar hyperparameters partition into shape-compatible
    COHORTS and train as ONE batched compiled program
    (models/tree/grid_batch.py) — G members for ~1 dispatch per level.
    Shape-changing combos (max_depth/nbins/ntrees/...) and every
    disqualified member fall back to the wave path with a recorded
    reason; "off" is exactly the wave path.  ``search_criteria`` gains
    ``successive_halving`` (bool), ``halving_eta`` (default 3) and
    ``halving_metric`` — in-batch retirement of losing members at
    geometric rung fences, zero recompiles.
    """

    def __init__(self, builder_cls, hyper_params: Dict[str, Sequence],
                 search_criteria: Optional[dict] = None,
                 parallelism: int = 0, grid_batch: str = "auto",
                 **base_params):
        self.builder_cls = builder_cls
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        self.search_criteria = dict(search_criteria or
                                    {"strategy": "Cartesian"})
        self.parallelism = parallelism
        self.grid_batch = grid_batch
        self.base_params = base_params

    def _combos(self) -> List[dict]:
        names = list(self.hyper_params)
        all_combos = [dict(zip(names, vals)) for vals in
                      itertools.product(*(self.hyper_params[n]
                                          for n in names))]
        sc = self.search_criteria
        if sc.get("strategy", "Cartesian").lower() in (
                "randomdiscrete", "random_discrete"):
            rng = np.random.default_rng(sc.get("seed", 0))
            rng.shuffle(all_combos)
        return all_combos

    def train(self, frame: Frame, valid: Optional[Frame] = None,
              sort_metric: Optional[str] = None) -> Grid:
        sc = self.search_criteria
        max_models = sc.get("max_models", None)
        max_secs = sc.get("max_runtime_secs", None)
        stop_rounds = sc.get("stopping_rounds", 0)
        stop_tol = sc.get("stopping_tolerance", 1e-3)
        t0 = time.time()
        # cooperative max_runtime_secs: the deadline threads into every
        # build (map_builds / the cohort trainer) and tree drivers poll
        # it at chunk fences — an in-flight member stops within one
        # chunk of the budget instead of overshooting by whole builds
        deadline = (time.monotonic() + max_secs) if max_secs else None
        models, entries = [], []
        failed_entries: List[dict] = []
        metric, decreasing = None, None
        series: List[float] = []
        combos = self._combos()

        def note(combo, m):
            nonlocal metric, decreasing
            models.append(m)
            entries.append(combo)
            if metric is None:
                if sort_metric is None:
                    metric, lower = default_sort_metric(m)
                else:
                    from .scorekeeper import METRIC_MAXIMIZE
                    metric = sort_metric
                    lower = not METRIC_MAXIMIZE.get(sort_metric, False)
                decreasing = not lower
            v = model_metric(m, metric)
            if v is not None:
                series.append(v)

        def seq_stop() -> bool:
            # early stop over the *sequence of best-so-far* models,
            # checked between waves/cohorts
            return bool(stop_rounds and series and stop_early(
                series, stop_rounds, stop_tol, maximize=decreasing))

        # ---- batched cohorts: shape-compatible combos train as ONE
        # compiled program (models/tree/grid_batch.py); every fallback
        # (shape-changing combos, disqualified members, CohortFallback
        # from the trainer, a cost model that prefers pipelining) is
        # RECORDED and rides the scheduler-parallel wave path below
        remaining = list(range(len(combos)))
        stopped = False
        mode = str(getattr(self, "grid_batch", "auto")).lower()
        if mode in ("auto", "on") and len(combos) > 1:
            from ..runtime import autotune
            from ..runtime.observability import record
            from .tree import grid_batch as gb
            scope = remaining[:max_models] if max_models else remaining
            cohorts, rest = gb.plan_cohorts(
                self.builder_cls, self.base_params,
                [combos[i] for i in scope])
            for j, reason in rest:
                record("grid_batch_fallback", combo=combos[scope[j]],
                       reason=reason)
            taken = set()
            for co in cohorts:
                idxs = [scope[j] for j in co]
                if stopped or (max_secs and time.time() - t0 > max_secs):
                    break
                if mode == "auto":
                    rep = self.builder_cls(
                        **{**self.base_params, **combos[idxs[0]]})
                    choice = autotune.resolve_grid_batch(
                        kind=rep.algo, F=max(len(frame.names) - 1, 1),
                        N=frame.nrows, G=len(idxs),
                        max_depth=rep.params.max_depth,
                        nbins=rep.params.nbins)
                    if choice != "batched":
                        record("grid_batch_fallback", members=len(idxs),
                               reason="cost model chose "
                                      "scheduler-parallel")
                        continue
                try:
                    res = gb.train_cohort(
                        self.builder_cls, self.base_params,
                        [combos[i] for i in idxs], frame, valid,
                        search_criteria=sc, deadline=deadline)
                except gb.CohortFallback as e:
                    record("grid_batch_fallback", members=len(idxs),
                           reason=str(e))
                    continue
                for i, (m, err) in zip(idxs, res):
                    taken.add(i)
                    if err is not None:
                        failed_entries.append({**combos[i], "error": err})
                    else:
                        note(combos[i], m)
                stopped = seq_stop()
            remaining = [i for i in remaining if i not in taken]

        from .parallel import effective_parallelism, map_builds
        par = effective_parallelism(self.parallelism, len(remaining))
        pos = 0
        while pos < len(remaining) and not stopped:
            if max_models and len(models) >= max_models:
                break
            if max_secs and time.time() - t0 > max_secs:
                break
            wave = remaining[pos: pos + par]
            if max_models:
                wave = wave[: max_models - len(models)]
            pos += len(wave)

            def build(i):
                # each member journals (and snapshots) itself through
                # ModelBuilder.train — the per-member resumability path
                from ..runtime import failure
                failure.maybe_inject("grid_member")
                builder = self.builder_cls(
                    **{**self.base_params, **combos[i]})
                return builder.train(frame, valid)

            def safe_build(i):
                # member fault tolerance: a failing combo (including a
                # mid-build DeadlineExceeded) becomes a failed_entries
                # row instead of killing the whole grid
                try:
                    return build(i)
                except Exception as e:                  # noqa: BLE001
                    return _FailedBuild(repr(e))

            for i, m in zip(wave, map_builds(
                    [lambda i=i: safe_build(i) for i in wave], par,
                    deadline=deadline)):
                if isinstance(m, _FailedBuild):
                    failed_entries.append({**combos[i], "error": m.error})
                    continue
                note(combos[i], m)
            stopped = seq_stop()
        if not models:
            raise ValueError(
                "grid search trained no models"
                + (f"; member failures: {failed_entries}"
                   if failed_entries else ""))
        return Grid(dkv.make_key("grid"), models, list(self.hyper_params),
                    entries, metric, decreasing,
                    failed_entries=failed_entries)
