"""Early stopping — the ScoreKeeper.stopEarly analog.

Reference: ``hex/ScoreKeeper.java:17,319`` — convergence test on a moving
average of the chosen stopping metric: stop when the best moving average over
the last ``stopping_rounds`` scoring events fails to improve on the previous
moving average by more than ``stopping_tolerance`` (relative).
"""

from __future__ import annotations

from typing import Sequence


def moving_average(xs: Sequence[float], k: int) -> list:
    out = []
    for i in range(len(xs) - k + 1):
        out.append(sum(xs[i:i + k]) / k)
    return out


def stop_early(values: Sequence[float], stopping_rounds: int,
               tolerance: float, maximize: bool) -> bool:
    """True when the metric's moving average has converged.

    ``values`` is the full scoring-history series (most recent last).
    Mirrors ScoreKeeper.stopEarly: needs at least ``stopping_rounds + 1``
    moving-average points; compares the latest to the best of the earlier
    ones with a relative tolerance.
    """
    k = stopping_rounds
    if k <= 0 or len(values) < 2 * k:
        return False
    ma = moving_average(list(values), k)
    if len(ma) < k + 1:
        return False
    recent = ma[-1]
    reference = ma[:-k] if len(ma) > k else ma[:1]
    best = max(reference) if maximize else min(reference)
    if maximize:
        return recent <= best * (1 + tolerance) if best >= 0 else \
            recent <= best * (1 - tolerance)
    return recent >= best * (1 - tolerance) if best >= 0 else \
        recent >= best * (1 + tolerance)


METRIC_MAXIMIZE = {
    "auc": True, "pr_auc": True, "accuracy": True, "r2": True,
    "logloss": False, "rmse": False, "mse": False, "mae": False,
    "deviance": False, "mean_per_class_error": False, "anomaly_score": False,
}


def metric_direction(name: str, is_classifier: bool) -> tuple:
    """Resolve stopping_metric='auto' -> (metric_name, maximize)."""
    if name in ("auto", "", None):
        return ("logloss", False) if is_classifier else \
            ("mean_residual_deviance", False)
    return name, METRIC_MAXIMIZE.get(name, False)
