"""Grep: regex search over raw text files — ``hex/grep/Grep.java`` analog.

The reference distributes a regex match over a file's raw byte chunks
(MRTask) and reports per-match offsets.  Coordinator-side work here (text
scan is not device math); multi-file inputs stream through the Persist
SPI like every other ingest path.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters


@dataclasses.dataclass
class GrepParameters(Parameters):
    regex: str = ""


class GrepModel(Model):
    algo = "grep"

    def result(self) -> Frame:
        return dkv.get(self.output["matches_frame"])

    def _predict_raw(self, X):
        raise NotImplementedError("grep produces a match table")


def grep(path, regex: str, destination_frame: Optional[str] = None) -> Frame:
    """Search file(s) for a regex; returns (file, offset, match) rows."""
    from ..frame.parse import _expand_paths, _open_decompressed
    pat = re.compile(regex.encode())     # byte-level: true byte offsets
    files: List[str] = []
    offsets: List[float] = []
    matches: List[str] = []
    for uri in _expand_paths(path):
        fh = _open_decompressed(uri)
        data = fh.read()
        fh.close()
        if isinstance(data, str):
            data = data.encode()
        for m in pat.finditer(data):
            files.append(uri)
            offsets.append(float(m.start()))
            matches.append(m.group(0).decode(errors="replace"))
    fr = Frame.from_numpy({
        "file": np.asarray(files, dtype=object),
        "byte_offset": np.asarray(offsets, np.float64),
        "match": np.asarray(matches, dtype=object)},
        key=destination_frame or dkv.make_key("grep"))
    return fr


class Grep(ModelBuilder):
    algo = "grep"
    model_class = GrepModel
    supervised = False

    def __init__(self, params: Optional[GrepParameters] = None, **kw):
        super().__init__(params or GrepParameters(**kw))

    def train_on_path(self, path) -> GrepModel:
        p: GrepParameters = self.params
        if not p.regex:
            raise ValueError("grep requires regex")
        job = Job(f"grep {p.regex!r}")

        def run(j):
            fr = grep(path, p.regex)
            model = GrepModel(dkv.make_key(self.algo), p, None)
            model.output["matches_frame"] = fr.key
            model.output["n_matches"] = fr.nrows
            return model
        return job.run(run)
