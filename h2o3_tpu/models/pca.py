"""PCA + SVD: Gram/eigen and randomized projections on the MXU.

Reference: ``hex/pca/PCA.java:41`` (methods GramSVD / Power / Randomized /
GLRM; transform NONE/STANDARDIZE/NORMALIZE/DEMEAN/DESCALE) and
``hex/svd/SVD.java`` — both accumulate a distributed Gram ``X'X`` via
``gram/Gram.java:1017`` GramTask MRTasks and eigendecompose on the driver.

TPU-native redesign: the Gram is one ``X.T @ (w * X)`` matmul over the
row-sharded design matrix (XLA partitioner inserts the psum that replaces the
GramTask reduce); eigh/svd of the small [P, P] Gram runs on host.  The
Randomized method is the Halko sketch — two tall-skinny MXU matmuls — which
is the TPU-preferred path for wide data.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo

TRANSFORMS = ("none", "standardize", "normalize", "demean", "descale")


@dataclasses.dataclass
class PCAParameters(Parameters):
    k: int = 1
    transform: str = "none"
    pca_method: str = "gram_s_v_d"      # gram_s_v_d | power | randomized
    use_all_factor_levels: bool = False
    compute_metrics: bool = True
    max_iterations: int = 1000


def _transform_flags(transform: str):
    if transform not in TRANSFORMS:
        raise ValueError(f"transform must be one of {TRANSFORMS}")
    demean = transform in ("standardize", "demean")
    descale = transform in ("standardize", "normalize", "descale")
    return demean, descale


@jax.jit
def _gram(X, w):
    Xw = X * w[:, None]
    return X.T @ Xw, jnp.sum(w)


class _ProjectionMixin:
    """Shared fitted-projection plumbing for PCA/SVD models."""

    def _std_matrix(self, frame: Frame) -> jax.Array:
        di = self.datainfo
        X = di.make_matrix(frame, standardize=False)
        mu = jnp.asarray(self.output["_mu"], jnp.float32)
        sd = jnp.asarray(self.output["_sd"], jnp.float32)
        return (X - mu[None, :]) * sd[None, :]

    def _score_matrix(self, frame: Frame) -> jax.Array:
        # _predict_raw projects in the fitted transform's space; generic
        # callers (StackedEnsemble level-one assembly, base scorer) must
        # feed it the same standardized matrix predict() uses, or stacked
        # PCA/SVD columns disagree with every exported representation
        return self._std_matrix(frame)


class PCAModel(_ProjectionMixin, Model):
    algo = "pca"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        V = jnp.asarray(self.output["eigenvectors"], jnp.float32)
        return X @ V

    def predict(self, frame: Frame) -> Frame:
        Z = np.asarray(self._predict_raw(self._std_matrix(frame)))
        Z = Z[: frame.nrows]
        names = [f"PC{i+1}" for i in range(Z.shape[1])]
        return Frame(names, [Vec.from_numpy(Z[:, i].astype(np.float64), T_NUM)
                             for i in range(Z.shape[1])])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        # reconstruction MSE in the transformed space on the given frame
        Xt = self._std_matrix(frame)
        V = jnp.asarray(self.output["eigenvectors"], jnp.float32)
        R = Xt - (Xt @ V) @ V.T
        w = self.datainfo.weights(frame)
        mse = float(jnp.sum(jnp.sum(R * R, axis=1) * w)
                    / jnp.maximum(jnp.sum(w), 1.0))
        return {"reconstruction_mse": mse}


class PCA(ModelBuilder):
    """PCA builder — h2o.prcomp / H2OPrincipalComponentAnalysisEstimator analog."""

    algo = "pca"
    model_class = PCAModel
    supervised = False

    def __init__(self, params: Optional[PCAParameters] = None, **kw):
        super().__init__(params or PCAParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            standardize=False, use_all_factor_levels=p.use_all_factor_levels,
            add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _centered(self, frame: Frame, di: DataInfo, transform: str):
        """[N,P] matrix after the PCA transform + (mu, sd) used."""
        X = di.make_matrix(frame, standardize=False)
        w = di.weights(frame)
        n = jnp.maximum(jnp.sum(w), 1.0)
        mu_all = jnp.sum(X * w[:, None], axis=0) / n
        var = jnp.sum((X - mu_all[None, :]) ** 2 * w[:, None], axis=0) \
            / jnp.maximum(n - 1.0, 1.0)
        demean, descale = _transform_flags(transform)
        mu = mu_all if demean else jnp.zeros_like(mu_all)
        sd = jnp.where(var > 0, 1.0 / jnp.sqrt(var), 1.0) if descale \
            else jnp.ones_like(var)
        Xt = (X - mu[None, :]) * sd[None, :]
        return Xt, w, mu, sd, n

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> PCAModel:
        p: PCAParameters = self.params
        k = min(p.k, di.nfeatures)
        Xt, w, mu, sd, n = self._centered(frame, di, p.transform)

        if p.pca_method == "randomized":
            eigvec, eigval = self._randomized(Xt, w, k, n, p)
        elif p.pca_method == "power":
            eigvec, eigval = self._power(Xt, w, k, n, p)
        else:
            G, _ = _gram(Xt, w)
            G = np.asarray(G, np.float64) / max(float(n) - 1.0, 1.0)
            vals, vecs = np.linalg.eigh(G)
            order = np.argsort(vals)[::-1][:k]
            eigval, eigvec = vals[order], vecs[:, order]

        eigval = np.maximum(np.asarray(eigval, np.float64), 0.0)
        sdev = np.sqrt(eigval)
        # sign convention: largest |component| positive (matches prcomp-ish)
        for j in range(eigvec.shape[1]):
            i = np.argmax(np.abs(eigvec[:, j]))
            if eigvec[i, j] < 0:
                eigvec[:, j] = -eigvec[:, j]

        total_var = float(jnp.sum(
            jnp.sum(Xt * Xt * w[:, None], axis=0) / jnp.maximum(n - 1.0, 1.0)))
        pve = sdev**2 / total_var if total_var > 0 else sdev * 0

        model = PCAModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "eigenvectors": np.asarray(eigvec, np.float64),
            "std_deviation": sdev,
            "pct_variance": pve,
            "cum_pct_variance": np.cumsum(pve),
            "coef_names": di.coef_names,
            "k": int(k),
            "_mu": np.asarray(mu, np.float64),
            "_sd": np.asarray(sd, np.float64),
        })
        if p.compute_metrics:
            model.training_metrics = {"total_variance": total_var}
        return model

    # -------------------------------------------------- iterative methods
    def _power(self, Xt, w, k, n, p):
        """Power iteration with deflation on the [P,P] Gram (PCA.java Power)."""
        G, _ = _gram(Xt, w)
        G = np.asarray(G, np.float64) / max(float(n) - 1.0, 1.0)
        P = G.shape[0]
        rng = np.random.default_rng(p.effective_seed())
        vecs, vals = [], []
        for _ in range(k):
            v = rng.normal(size=P)
            v /= np.linalg.norm(v)
            for _ in range(p.max_iterations):
                v2 = G @ v
                for u in vecs:
                    v2 -= (u @ v2) * u
                nv = np.linalg.norm(v2)
                if nv == 0:
                    break
                v2 /= nv
                if np.abs(v2 @ v) > 1 - 1e-12:
                    v = v2
                    break
                v = v2
            lam = float(v @ G @ v)
            vecs.append(v)
            vals.append(lam)
        return np.stack(vecs, axis=1), np.array(vals)

    def _randomized(self, Xt, w, k, n, p):
        """Halko randomized SVD: sketch + 2 power passes, all MXU matmuls."""
        P = Xt.shape[1]
        rng = np.random.default_rng(p.effective_seed())
        ell = min(P, k + 8)
        Om = jnp.asarray(rng.normal(size=(P, ell)), jnp.float32)
        Wc = w[:, None]
        Y = (Xt * Wc) @ Om
        for _ in range(2):
            Q, _ = jnp.linalg.qr(Y)
            Y = (Xt * Wc) @ (Xt.T @ Q)
        Q, _ = jnp.linalg.qr(Y)
        B = Q.T @ (Xt * jnp.sqrt(Wc))          # [ell, P]
        Bh = np.asarray(B, np.float64)
        _, s, Vt = np.linalg.svd(Bh, full_matrices=False)
        vals = (s**2) / max(float(n) - 1.0, 1.0)
        return Vt[:k].T, vals[:k]


# ============================================================ SVD builder
@dataclasses.dataclass
class SVDParameters(PCAParameters):
    nv: int = 1
    svd_method: str = "gram_s_v_d"
    keep_u: bool = True


class SVDModel(_ProjectionMixin, Model):
    algo = "svd"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        V = jnp.asarray(self.output["v"], jnp.float32)
        d = jnp.asarray(self.output["d"], jnp.float32)
        return (X @ V) / jnp.maximum(d[None, :], 1e-30)

    def predict(self, frame: Frame) -> Frame:
        U = np.asarray(self._predict_raw(self._std_matrix(frame)))[: frame.nrows]
        names = [f"u{i+1}" for i in range(U.shape[1])]
        return Frame(names, [Vec.from_numpy(U[:, i].astype(np.float64), T_NUM)
                             for i in range(U.shape[1])])

    def model_performance(self, frame=None):
        if frame is None:
            return self.training_metrics
        Xt = self._std_matrix(frame)
        V = jnp.asarray(self.output["v"], jnp.float32)
        R = Xt - (Xt @ V) @ V.T
        w = self.datainfo.weights(frame)
        mse = float(jnp.sum(jnp.sum(R * R, axis=1) * w)
                    / jnp.maximum(jnp.sum(w), 1.0))
        return {"reconstruction_mse": mse}


class SVD(PCA):
    """SVD builder — hex/svd/SVD.java analog (d, V, optional U)."""

    algo = "svd"
    model_class = SVDModel

    def __init__(self, params: Optional[SVDParameters] = None, **kw):
        ModelBuilder.__init__(self, params or SVDParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> SVDModel:
        p: SVDParameters = self.params
        k = min(p.nv, di.nfeatures)
        Xt, w, mu, sd, n = self._centered(frame, di, p.transform)
        G, _ = _gram(Xt, w)
        G = np.asarray(G, np.float64)
        vals, vecs = np.linalg.eigh(G)
        order = np.argsort(vals)[::-1][:k]
        vals = np.maximum(vals[order], 0.0)
        V = vecs[:, order]
        d = np.sqrt(vals)
        model = SVDModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "d": d, "v": V, "coef_names": di.coef_names, "k": int(k),
            "_mu": np.asarray(mu, np.float64), "_sd": np.asarray(sd, np.float64),
        })
        model.training_metrics = {"d": d.tolist()}
        if p.keep_u:
            u = model.predict(frame)
            u_key = dkv.make_key("svd_u")
            u.key = u_key
            dkv.put(u_key, u)
            model.output["u_key"] = u_key
        return model
