"""Isotonic regression: device sort + pool-adjacent-violators on thresholds.

Reference: ``hex/isotonic/IsotonicRegression.java`` — distributed PAV: rows
are aggregated into (x, y, w) triples, pooled until monotone; the model
stores threshold knots and predicts by linear interpolation with
``out_of_bounds`` NA/clip handling.

TPU-native redesign: the row-scale work (sort by x, duplicate-x aggregation
via segment sums) runs on device; the inherently sequential PAV pooling runs
on host over the *unique-x* knots (≤ cardinality of x, small after
aggregation), using the O(n) stack algorithm.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class IsotonicRegressionParameters(Parameters):
    out_of_bounds: str = "na"     # na | clip


@jax.jit
def _sort_xyw(x, y, w):
    invalid = jnp.isnan(x) | jnp.isnan(y) | (w <= 0)
    key = jnp.where(invalid, jnp.inf, x)
    order = jnp.argsort(key)
    return key[order], y[order], jnp.where(invalid, 0.0, w)[order]


def _pav(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Stack-based pool-adjacent-violators; returns the isotonic fit."""
    n = len(y)
    means = np.empty(n)
    weights = np.empty(n)
    sizes = np.empty(n, dtype=np.int64)
    top = -1
    for i in range(n):
        top += 1
        means[top], weights[top], sizes[top] = y[i], w[i], 1
        while top > 0 and means[top - 1] >= means[top]:
            tw = weights[top - 1] + weights[top]
            means[top - 1] = (means[top - 1] * weights[top - 1]
                              + means[top] * weights[top]) / tw
            weights[top - 1] = tw
            sizes[top - 1] += sizes[top]
            top -= 1
    return np.repeat(means[: top + 1], sizes[: top + 1])


class IsotonicRegressionModel(Model):
    algo = "isotonicregression"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        raise NotImplementedError("isotonic scores via thresholds")

    def predict(self, frame: Frame) -> Frame:
        x = np.asarray(frame.vec(self.output["feature"]).numeric_data(),
                       np.float64)[: frame.nrows]
        tx = self.output["thresholds_x"]
        ty = self.output["thresholds_y"]
        pred = np.interp(x, tx, ty)
        if self.params.out_of_bounds == "na":
            pred = np.where((x < tx[0]) | (x > tx[-1]), np.nan, pred)
        pred = np.where(np.isnan(x), np.nan, pred)
        return Frame(["predict"], [Vec.from_numpy(pred, T_NUM)])

    def model_performance(self, frame: Optional[Frame] = None):
        from ..metrics.core import regression_metrics
        if frame is None:
            return self.training_metrics
        p = self.predict(frame).vecs[0].to_numpy()
        y = np.asarray(frame.vec(self.params.response_column).numeric_data(),
                       np.float64)[: frame.nrows]
        ok = ~(np.isnan(p) | np.isnan(y))
        return regression_metrics(jnp.asarray(p[ok], jnp.float32),
                                  jnp.asarray(y[ok], jnp.float32),
                                  jnp.ones(int(ok.sum()), jnp.float32))


class IsotonicRegression(ModelBuilder):
    """Isotonic builder — H2OIsotonicRegressionEstimator analog."""

    algo = "isotonicregression"
    model_class = IsotonicRegressionModel

    def __init__(self, params: Optional[IsotonicRegressionParameters] = None,
                 **kw):
        super().__init__(params or IsotonicRegressionParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p = self.params
        feats = [n for n in frame.names
                 if n not in (p.response_column, p.weights_column)
                 and n not in p.ignored_columns]
        if len(feats) != 1:
            raise ValueError(
                f"isotonic regression needs exactly 1 feature, got {feats}")

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> IsotonicRegressionModel:
        p = self.params
        feature = di.specs[0].name
        x = frame.vec(feature).numeric_data()
        y = frame.vec(p.response_column).numeric_data()
        w = di.weights(frame)
        xs, ys, ws = _sort_xyw(x, y, w)
        xs = np.asarray(xs, np.float64)
        ys = np.asarray(ys, np.float64)
        ws = np.asarray(ws, np.float64)
        n = int((ws > 0).sum())
        xs, ys, ws = xs[:n], ys[:n], ws[:n]
        # aggregate duplicate x (weighted mean) so PAV runs on unique knots
        ux, start = np.unique(xs, return_index=True)
        wsum = np.add.reduceat(ws, start)
        ysum = np.add.reduceat(ys * ws, start)
        ymean = ysum / np.maximum(wsum, 1e-30)
        fit = _pav(ymean, wsum)
        # keep only segment-boundary knots (thresholds, as the reference does)
        keep = np.ones(len(fit), bool)
        if len(fit) > 2:
            interior = (fit[1:-1] == fit[:-2]) & (fit[1:-1] == fit[2:])
            keep[1:-1] = ~interior
        model = IsotonicRegressionModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "feature": feature,
            "thresholds_x": ux[keep], "thresholds_y": fit[keep],
            "nobs": n,
        })
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
