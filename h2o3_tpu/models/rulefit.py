"""RuleFit: tree-ensemble rules + linear terms under an L1 GLM.

Reference: ``hex/rulefit/RuleFit.java`` — fit a small tree ensemble, convert
every node's root path into a binary rule feature, optionally append the
(winsorized) linear terms, then fit a sparse GLM over [rules, linear].

TPU-native redesign: rule membership needs no per-rule evaluation — each
sample's leaf index per tree already encodes every ancestor node on its
path (node at depth d = leaf >> (D - d)), so the rule matrix is bit-shift
compares over the device leaf assignments.  The sparse fit is this
package's GLM with alpha=1.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class RuleFitParameters(Parameters):
    algorithm: str = "gbm"               # rule generator
    min_rule_length: int = 1
    max_rule_length: int = 3
    max_num_rules: int = -1              # -1: auto
    model_type: str = "rules_and_linear"  # rules | linear | rules_and_linear
    rule_generation_ntrees: int = 30
    lambda_: Optional[float] = None


class RuleFitModel(Model):
    algo = "rulefit"

    def _rule_matrix(self, frame: Frame) -> np.ndarray:
        from .tree.shared import stack_trees, traverse_jit
        gen = dkv.get(self.output["rule_model_key"])
        X = gen._design(frame)
        cols = []
        for t_i, tree in enumerate(gen.output["trees"]):
            # leaf index per row for this tree
            levels, values = stack_trees([tree])
            node = jnp.zeros(X.shape[0], jnp.int32)
            for (feat, thr, na_left, valid) in levels:
                f = feat[0][node]
                x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
                right = jnp.where(jnp.isnan(x), ~na_left[0][node],
                                  x >= thr[0][node])
                right = right & valid[0][node]
                node = 2 * node + right.astype(jnp.int32)
            leaf = np.asarray(node)[: frame.nrows]
            D = len(tree.feat)
            for (ti, d, nid) in self.output["rules"]:
                if ti == t_i:
                    cols.append((leaf >> (D - d)) == nid)
        return np.stack(cols, axis=1).astype(np.float64) if cols else \
            np.zeros((frame.nrows, 0))

    def _glm_frame(self, frame: Frame, with_response: bool) -> Frame:
        p: RuleFitParameters = self.params
        names, vecs = [], []
        if p.model_type in ("rules", "rules_and_linear"):
            R = self._rule_matrix(frame)
            for i in range(R.shape[1]):
                names.append(f"rule_{i}")
                vecs.append(Vec.from_numpy(R[:, i], T_NUM))
        if p.model_type in ("linear", "rules_and_linear"):
            for s in self.datainfo.specs:
                names.append(f"linear_{s.name}")
                v = frame.vec(s.name)
                vecs.append(v)
        if with_response:
            names.append(p.response_column)
            vecs.append(frame.vec(p.response_column))
        return Frame(names, vecs)

    def _predict_raw(self, X):
        raise NotImplementedError("rulefit scores via its GLM")

    def predict(self, frame: Frame) -> Frame:
        glm = dkv.get(self.output["glm_key"])
        return glm.predict(self._glm_frame(frame, with_response=False))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        glm = dkv.get(self.output["glm_key"])
        return glm.model_performance(self._glm_frame(frame, True))

    def rule_importance(self) -> List[dict]:
        glm = dkv.get(self.output["glm_key"])
        out = []
        for name, coef in glm.coef.items():
            if abs(coef) > 1e-10 and name != "Intercept":
                entry = {"variable": name, "coefficient": coef}
                if name.startswith("rule_"):
                    entry["rule"] = self.output["rule_descriptions"][
                        int(name.split("_")[1])]
                out.append(entry)
        return sorted(out, key=lambda r: -abs(r["coefficient"]))


class RuleFit(ModelBuilder):
    """RuleFit builder — H2ORuleFitEstimator analog."""

    algo = "rulefit"
    model_class = RuleFitModel

    def __init__(self, params: Optional[RuleFitParameters] = None, **kw):
        super().__init__(params or RuleFitParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> RuleFitModel:
        p: RuleFitParameters = self.params
        from .tree.gbm import GBM
        from .tree.drf import DRF
        from .glm import GLM
        if di.is_classifier and di.nclasses > 2:
            raise ValueError("rulefit supports regression and binary "
                             "classification only (multinomial rule "
                             "generation not yet implemented)")
        gen_cls = GBM if p.algorithm == "gbm" else DRF
        depth = max(p.max_rule_length, 1)
        job.update(0.1, "growing rule trees")
        gen = gen_cls(response_column=p.response_column,
                      ntrees=p.rule_generation_ntrees, max_depth=depth,
                      seed=p.effective_seed(),
                      sample_rate=0.7, learn_rate=0.1).train(frame)

        # enumerate rules: every node at depths [min_len, max_len]
        rules, descr = [], []
        for t_i, tree in enumerate(gen.output["trees"]):
            D = len(tree.feat)
            for d in range(p.min_rule_length, min(p.max_rule_length, D) + 1):
                for nid in range(2 ** d):
                    rules.append((t_i, d, nid))
                    descr.append(self._describe(tree, d, nid, di))
        if p.max_num_rules > 0 and len(rules) > p.max_num_rules:
            keep = np.random.default_rng(p.effective_seed()).choice(
                len(rules), p.max_num_rules, replace=False)
            rules = [rules[i] for i in sorted(keep)]
            descr = [descr[i] for i in sorted(keep)]

        model = RuleFitModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "rule_model_key": gen.key,
            "rules": rules,
            "rule_descriptions": descr,
        })

        job.update(0.5, f"fitting sparse GLM over {len(rules)} rules")
        glm_train = model._glm_frame(frame, with_response=True)
        lam = p.lambda_ if p.lambda_ is not None else None
        glm = GLM(response_column=p.response_column, alpha=1.0,
                  lambda_=lam, lambda_search=lam is None,
                  seed=p.effective_seed()).train(glm_train)
        model.output["glm_key"] = glm.key
        model.training_metrics = glm.training_metrics
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    @staticmethod
    def _describe(tree, depth: int, nid: int, di: DataInfo) -> str:
        """Root-path conjunction for a node (rule text)."""
        conds = []
        node = nid
        for d in range(depth - 1, -1, -1):
            parent = node >> 1
            right = node & 1
            feat = int(np.asarray(tree.feat[d][parent])) \
                if np.ndim(tree.feat[d]) else int(tree.feat[d])
            thr = float(np.asarray(tree.thr[d][parent]))
            name = di.specs[feat].name if feat < len(di.specs) else f"f{feat}"
            op = ">=" if right else "<"
            conds.append(f"{name} {op} {thr:.6g}")
            node = parent
        return " & ".join(reversed(conds))
